// pt_runtime: native host-side runtime for paddle_tpu.
//
// Reference analog: the C++ pieces of the reference's host pipeline —
// shared-memory DataLoader transport (python/paddle/io/dataloader/worker.py
// + paddle/fluid/memory shared storage) and host trace spans
// (paddle/fluid/platform/profiler/host_tracer.h). The TPU compute path is
// XLA; this library covers the host side: a lock-free SPSC shared-memory
// ring buffer so multiprocess DataLoader workers hand batches to the
// trainer process without pickling through pipes, plus nanosecond timestamp
// helpers for the profiler.
//
// Build: g++ -O2 -shared -fPIC -std=c++17 pt_runtime.cpp -o libpt_runtime.so
// (driven by paddle_tpu/utils/native.py at first use; pure-python fallback
// exists so the framework works without a toolchain.)

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <cstdio>
#include <ctime>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct RingHeader {
  std::atomic<uint64_t> head;   // next write offset (monotonic)
  std::atomic<uint64_t> tail;   // next read offset (monotonic)
  uint64_t capacity;            // data bytes
  uint32_t magic;
  uint32_t closed;
};

constexpr uint32_t kMagic = 0x50545231;  // "PTR1"

struct Ring {
  RingHeader* hdr;
  char* data;
  size_t map_size;
  int fd;
  char name[256];
};

inline uint64_t now_ns() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return uint64_t(ts.tv_sec) * 1000000000ull + uint64_t(ts.tv_nsec);
}

// copy n bytes into the ring at logical offset pos (wrapping)
void ring_put(Ring* r, uint64_t pos, const char* src, uint64_t n) {
  uint64_t cap = r->hdr->capacity;
  uint64_t off = pos % cap;
  uint64_t first = (n < cap - off) ? n : cap - off;
  std::memcpy(r->data + off, src, first);
  if (n > first) std::memcpy(r->data, src + first, n - first);
}

void ring_get(Ring* r, uint64_t pos, char* dst, uint64_t n) {
  uint64_t cap = r->hdr->capacity;
  uint64_t off = pos % cap;
  uint64_t first = (n < cap - off) ? n : cap - off;
  std::memcpy(dst, r->data + off, first);
  if (n > first) std::memcpy(dst + first, r->data, n - first);
}

}  // namespace

extern "C" {

// returns opaque handle or null. create=1 initializes a fresh segment.
void* pt_ring_open(const char* name, uint64_t capacity, int create) {
  int flags = create ? (O_CREAT | O_RDWR) : O_RDWR;
  int fd = shm_open(name, flags, 0600);
  if (fd < 0) return nullptr;
  size_t total = sizeof(RingHeader) + capacity;
  if (create) {
    if (ftruncate(fd, (off_t)total) != 0) {
      close(fd);
      return nullptr;
    }
  } else {
    struct stat st;
    if (fstat(fd, &st) != 0 || (size_t)st.st_size < sizeof(RingHeader)) {
      close(fd);
      return nullptr;
    }
    total = (size_t)st.st_size;
    capacity = total - sizeof(RingHeader);
  }
  void* mem = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (mem == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  Ring* r = new Ring();
  r->hdr = reinterpret_cast<RingHeader*>(mem);
  r->data = reinterpret_cast<char*>(mem) + sizeof(RingHeader);
  r->map_size = total;
  r->fd = fd;
  std::snprintf(r->name, sizeof(r->name), "%s", name);
  if (create) {
    r->hdr->head.store(0);
    r->hdr->tail.store(0);
    r->hdr->capacity = capacity;
    r->hdr->closed = 0;
    r->hdr->magic = kMagic;
  } else if (r->hdr->magic != kMagic) {
    munmap(mem, total);
    close(fd);
    delete r;
    return nullptr;
  }
  return r;
}

// write one length-prefixed message; blocks (sleep-polling) until space or
// timeout_ms elapses. returns 0 ok, -1 timeout, -2 closed/oversized.
int pt_ring_write(void* handle, const char* buf, uint64_t n,
                  int64_t timeout_ms) {
  Ring* r = static_cast<Ring*>(handle);
  uint64_t need = n + 8;
  if (need > r->hdr->capacity) return -2;
  uint64_t deadline = now_ns() + uint64_t(timeout_ms) * 1000000ull;
  for (;;) {
    if (r->hdr->closed) return -2;
    uint64_t head = r->hdr->head.load(std::memory_order_relaxed);
    uint64_t tail = r->hdr->tail.load(std::memory_order_acquire);
    if (r->hdr->capacity - (head - tail) >= need) {
      ring_put(r, head, reinterpret_cast<const char*>(&n), 8);
      ring_put(r, head + 8, buf, n);
      r->hdr->head.store(head + need, std::memory_order_release);
      return 0;
    }
    if (timeout_ms >= 0 && now_ns() > deadline) return -1;
    struct timespec ts = {0, 200000};  // 0.2 ms
    nanosleep(&ts, nullptr);
  }
}

// peek next message size; -1 if empty.
int64_t pt_ring_next_size(void* handle) {
  Ring* r = static_cast<Ring*>(handle);
  uint64_t tail = r->hdr->tail.load(std::memory_order_relaxed);
  uint64_t head = r->hdr->head.load(std::memory_order_acquire);
  if (head == tail) return -1;
  uint64_t n;
  ring_get(r, tail, reinterpret_cast<char*>(&n), 8);
  return (int64_t)n;
}

// read one message into buf (must be >= its size); blocks until data or
// timeout. returns size, -1 timeout, -2 closed-and-empty.
int64_t pt_ring_read(void* handle, char* buf, uint64_t maxn,
                     int64_t timeout_ms) {
  Ring* r = static_cast<Ring*>(handle);
  uint64_t deadline = now_ns() + uint64_t(timeout_ms) * 1000000ull;
  for (;;) {
    uint64_t tail = r->hdr->tail.load(std::memory_order_relaxed);
    uint64_t head = r->hdr->head.load(std::memory_order_acquire);
    if (head != tail) {
      uint64_t n;
      ring_get(r, tail, reinterpret_cast<char*>(&n), 8);
      if (n > maxn) return -3;
      ring_get(r, tail + 8, buf, n);
      r->hdr->tail.store(tail + n + 8, std::memory_order_release);
      return (int64_t)n;
    }
    if (r->hdr->closed) return -2;
    if (timeout_ms >= 0 && now_ns() > deadline) return -1;
    struct timespec ts = {0, 200000};
    nanosleep(&ts, nullptr);
  }
}

void pt_ring_mark_closed(void* handle) {
  static_cast<Ring*>(handle)->hdr->closed = 1;
}

void pt_ring_close(void* handle, int unlink_seg) {
  Ring* r = static_cast<Ring*>(handle);
  char name[256];
  std::snprintf(name, sizeof(name), "%s", r->name);
  munmap(r->hdr, r->map_size);
  close(r->fd);
  if (unlink_seg) shm_unlink(name);
  delete r;
}

uint64_t pt_now_ns() { return now_ns(); }

}  // extern "C"
