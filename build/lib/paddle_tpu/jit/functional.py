"""Layer -> pure-function bridge.

This is the load-bearing TPU-first mechanism (SURVEY.md §7 step 8): the same
nn.Layer that runs define-by-run eagerly can be traced into a pure
jax function of (params, buffers, inputs) by temporarily swapping each
Parameter/buffer's underlying array for a traced value. jax.jit/pjit then
compiles the WHOLE step into one XLA executable — the analog of the
reference's dy2static + PirInterpreter static path, with XLA doing what
CINN + the stream-scheduling executor do there.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Any, Dict, List, Tuple

import jax

from ..core.autograd import no_grad
from ..core.tensor import Tensor

_trace_lock = threading.RLock()


def layer_state(layer) -> Tuple[Dict[str, Tensor], Dict[str, Tensor]]:
    """Stable-ordered (params, buffers) name->Tensor maps."""
    params = dict(layer.named_parameters())
    buffers = dict(layer.named_buffers())
    return params, buffers


@contextlib.contextmanager
def _substituted(handles: List[Tensor], arrays: List[Any]):
    with _trace_lock:
        originals = [h._value for h in handles]
        grad_meta = [(h._grad_node, h._out_index) for h in handles]
        try:
            for h, a in zip(handles, arrays):
                h._value = a
                h._grad_node = None
            yield handles
        finally:
            for h, orig, (gn, oi) in zip(handles, originals, grad_meta):
                h._value = orig
                h._grad_node = gn
                h._out_index = oi


def call_functional(layer, param_arrays: Dict[str, Any],
                    buffer_arrays: Dict[str, Any], args, kwargs=None,
                    train: bool = True):
    """Run layer.forward as a pure function.

    Returns (outputs_as_arrays, new_buffer_arrays). Buffer mutation during
    forward (BN running stats) is captured by reading the handles back after
    the call — the functional answer to in-place buffer updates.
    """
    kwargs = kwargs or {}
    params, buffers = layer_state(layer)
    handles = list(params.values()) + list(buffers.values())
    arrays = [param_arrays[k] for k in params] + \
             [buffer_arrays[k] for k in buffers]
    was_training = layer.training
    if train != was_training:
        layer.train() if train else layer.eval()
    try:
        with _substituted(handles, arrays):
            with no_grad():
                ins = [Tensor(a, stop_gradient=True)
                       if isinstance(a, jax.Array) or hasattr(a, "shape")
                       and not isinstance(a, Tensor) else a for a in args]
                ins = [a if not isinstance(a, Tensor) else a for a in ins]
                out = layer(*ins, **kwargs)
            new_buffers = {k: b._value for k, b in buffers.items()}
        out_arrays = jax.tree.map(
            lambda x: x._value if isinstance(x, Tensor) else x, out,
            is_leaf=lambda x: isinstance(x, Tensor))
        return out_arrays, new_buffers
    finally:
        if train != was_training:
            layer.train() if was_training else layer.eval()


def current_params(layer) -> Dict[str, Any]:
    return {k: p._value for k, p in layer.named_parameters()}


def current_buffers(layer) -> Dict[str, Any]:
    return {k: b._value for k, b in layer.named_buffers()}


def write_back(layer, param_arrays: Dict[str, Any],
               buffer_arrays: Dict[str, Any] = None):
    params, buffers = layer_state(layer)
    for k, p in params.items():
        if k in param_arrays:
            p._value = param_arrays[k]
    if buffer_arrays:
        for k, b in buffers.items():
            if k in buffer_arrays:
                b._value = buffer_arrays[k]
