"""Fused layers (reference: python/paddle/incubate/nn/layer/)."""
from __future__ import annotations

from ... import nn


class FusedMultiHeadAttention(nn.MultiHeadAttention):
    """On TPU the standard MultiHeadAttention already routes to the fused
    Pallas kernel; this alias keeps the incubate API."""


class FusedFeedForward(nn.Layer):
    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, **kwargs):
        super().__init__()
        self.linear1 = nn.Linear(d_model, dim_feedforward)
        self.linear2 = nn.Linear(dim_feedforward, d_model)
        self.norm = nn.LayerNorm(d_model, epsilon=epsilon)
        self.dropout = nn.Dropout(dropout_rate)
        self.act_dropout = nn.Dropout(
            dropout_rate if act_dropout_rate is None else act_dropout_rate)
        self.activation = activation
        self.normalize_before = normalize_before

    def forward(self, src):
        from .. import nn as _  # noqa

        residual = src
        if self.normalize_before:
            src = self.norm(src)
        from ...nn import functional as F

        src = self.linear2(self.act_dropout(
            getattr(F, self.activation)(self.linear1(src))))
        src = residual + self.dropout(src)
        if not self.normalize_before:
            src = self.norm(src)
        return src
