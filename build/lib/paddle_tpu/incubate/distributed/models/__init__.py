from . import moe
