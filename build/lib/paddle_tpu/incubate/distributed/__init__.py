from . import models
