from . import nn
from . import distributed
