"""Quantization (reference: python/paddle/quantization/ — PTQ observers,
QAT fake-quant quanters, QuantConfig).

TPU-relevant forms: int8 PTQ via absmax/histogram observers and QAT with
straight-through fake-quant; fp8 via the native float8 dtypes."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply
from ..core.tensor import Tensor
from .. import nn

__all__ = ["AbsmaxObserver", "HistObserver", "AbsMaxChannelWiseObserver",
           "FakeQuanterWithAbsMax", "QuantConfig", "QAT", "PTQ",
           "quanter", "QuantedLinear", "QuantedConv2D",
           "ConvertedQuantLinear", "save_quantized_model"]


class _BaseObserver:
    def __init__(self, quant_bits=8):
        self.quant_bits = quant_bits
        self._scale = None

    def scales(self):
        return self._scale

    def quant_axis(self):
        return -1

    def bit_length(self):
        return self.quant_bits


class AbsmaxObserver(_BaseObserver):
    """reference: quantization/observers/abs_max.py."""

    def __init__(self, quant_bits=8):
        super().__init__(quant_bits)
        self._max = 0.0

    def observe(self, x):
        arr = x.numpy() if isinstance(x, Tensor) else np.asarray(x)
        self._max = max(self._max, float(np.abs(arr).max()))
        self._scale = self._max / (2 ** (self.quant_bits - 1) - 1)
        return x

    __call__ = observe


class AbsMaxChannelWiseObserver(_BaseObserver):
    def __init__(self, quant_bits=8, quant_axis=0):
        super().__init__(quant_bits)
        self._axis = quant_axis
        self._max = None

    def observe(self, x):
        arr = np.abs(x.numpy() if isinstance(x, Tensor) else np.asarray(x))
        axes = tuple(i for i in range(arr.ndim) if i != self._axis)
        cur = arr.max(axis=axes)
        self._max = cur if self._max is None else np.maximum(self._max, cur)
        self._scale = self._max / (2 ** (self.quant_bits - 1) - 1)
        return x

    __call__ = observe

    def quant_axis(self):
        return self._axis


class HistObserver(_BaseObserver):
    """Percentile-clipped histogram observer (reference hist.py)."""

    def __init__(self, quant_bits=8, bins_count=2048, percent=0.999):
        super().__init__(quant_bits)
        self.bins = bins_count
        self.percent = percent
        self._hist = None
        self._max = 0.0

    def observe(self, x):
        arr = np.abs(x.numpy() if isinstance(x, Tensor) else np.asarray(x))
        self._max = max(self._max, float(arr.max()))
        hist, _ = np.histogram(arr, bins=self.bins, range=(0, self._max))
        self._hist = hist if self._hist is None else self._hist + hist
        cdf = np.cumsum(self._hist) / self._hist.sum()
        cut = np.searchsorted(cdf, self.percent)
        clip_val = (cut + 1) / self.bins * self._max
        self._scale = clip_val / (2 ** (self.quant_bits - 1) - 1)
        return x

    __call__ = observe


def _fake_quant(x, scale, bits):
    qmax = 2 ** (bits - 1) - 1

    def fn(a):
        s = jnp.maximum(scale, 1e-9)
        q = jnp.clip(jnp.round(a / s), -qmax, qmax)
        deq = q * s
        # straight-through estimator
        return a + jax.lax.stop_gradient(deq - a)
    return apply(fn, x, op_name="fake_quant")


class FakeQuanterWithAbsMax(nn.Layer):
    """QAT fake-quant layer (reference quanters/abs_max.py) with running
    absmax scale + STE gradients."""

    def __init__(self, quant_bits=8, moving_rate=0.9, name=None):
        super().__init__()
        self.quant_bits = quant_bits
        self.moving_rate = moving_rate
        self.register_buffer("scale", Tensor(jnp.zeros(())))

    def forward(self, x):
        if self.training:
            cur = float(jnp.abs(x._value).max()) / (
                2 ** (self.quant_bits - 1) - 1)
            prev = float(self.scale._value)
            new = cur if prev == 0 else \
                self.moving_rate * prev + (1 - self.moving_rate) * cur
            self.scale._value = jnp.asarray(new)
        return _fake_quant(x, float(self.scale._value), self.quant_bits)


class QuantedLinear(nn.Layer):
    def __init__(self, linear: nn.Linear, q_config=None):
        super().__init__()
        self.inner = linear
        self.act_quanter = FakeQuanterWithAbsMax()
        self.weight_quanter = FakeQuanterWithAbsMax()

    def forward(self, x):
        x = self.act_quanter(x)
        w = self.weight_quanter(self.inner.weight)
        from ..nn import functional as F

        return F.linear(x, w, self.inner.bias)


class QuantedConv2D(nn.Layer):
    def __init__(self, conv, q_config=None):
        super().__init__()
        self.inner = conv
        self.act_quanter = FakeQuanterWithAbsMax()
        self.weight_quanter = FakeQuanterWithAbsMax()

    def forward(self, x):
        x = self.act_quanter(x)
        w = self.weight_quanter(self.inner.weight)
        from ..nn import functional as F

        c = self.inner
        return F.conv2d(x, w, c.bias, stride=c._stride,
                        padding=c._padding, dilation=c._dilation,
                        groups=c._groups)


class ConvertedQuantLinear(nn.Layer):
    """Deploy form after QAT/PTQ convert: int8 weight + per-channel scale,
    dequantized into the matmul (the weight_only_linear kernel)."""

    def __init__(self, linear: nn.Linear, act_scale=None):
        super().__init__()
        import numpy as np

        w = np.asarray(linear.weight._value, np.float32)
        scale = np.abs(w).max(axis=0) / 127.0
        self.qweight = np.clip(
            np.round(w / np.maximum(scale, 1e-12)[None, :]),
            -127, 127).astype(np.int8)
        self.register_buffer("weight_scale", __import__(
            "paddle_tpu").to_tensor(scale.astype(np.float32)))
        self.bias = linear.bias
        self.act_scale = act_scale

    def forward(self, x):
        from ..ops.registry import get

        out = get("weight_only_linear").fn(
            x._value, self.qweight, None, self.weight_scale._value)
        from ..core.tensor import Tensor

        y = Tensor(out)
        if self.bias is not None:
            y = y + self.bias
        return y


class QuantConfig:
    """reference: quantization/config.py."""

    def __init__(self, activation=None, weight=None):
        self.activation = activation or (lambda: FakeQuanterWithAbsMax())
        self.weight = weight or (lambda: FakeQuanterWithAbsMax())
        self._types = {nn.Linear: QuantedLinear,
                       nn.Conv2D: QuantedConv2D}

    def add_layer_config(self, layers, activation=None, weight=None):
        pass

    def add_type_config(self, layer_type, activation=None, weight=None):
        pass


def quanter(name):
    def deco(cls):
        return cls
    return deco


class QAT:
    """Quantization-aware training driver (reference: quantization/qat.py)."""

    def __init__(self, q_config: QuantConfig = None):
        self.config = q_config or QuantConfig()

    def quantize(self, model, inplace=False):
        def convert(layer):
            for name, sub in list(layer._sub_layers.items()):
                if type(sub) in self.config._types:
                    layer._sub_layers[name] = self.config._types[type(sub)](
                        sub, self.config)
                else:
                    convert(sub)
        convert(model)
        return model

    def convert(self, model, inplace=False):
        """Fold trained fake-quant observers into deployable int8 weights
        (reference qat.py convert -> quantized inference program)."""
        def fold(layer):
            for name, sub in list(layer._sub_layers.items()):
                if isinstance(sub, QuantedLinear):
                    act_scale = float(sub.act_quanter.scale._value)
                    layer._sub_layers[name] = ConvertedQuantLinear(
                        sub.inner, act_scale=act_scale)
                elif isinstance(sub, QuantedConv2D):
                    # conv deploy form keeps fake-quant folded weights
                    import jax.numpy as jnp

                    w = sub.weight_quanter(sub.inner.weight)
                    sub.inner.weight._value = jnp.asarray(w._value)
                    layer._sub_layers[name] = sub.inner
                else:
                    fold(sub)
        fold(model)
        return model


class PTQ:
    """Post-training quantization driver (reference: quantization/ptq.py)."""

    def __init__(self, q_config: QuantConfig = None):
        self.config = q_config or QuantConfig()
        self.observers = {}

    def quantize(self, model, inplace=False):
        for name, layer in model.named_sublayers():
            if isinstance(layer, nn.Linear):
                obs = AbsmaxObserver()
                self.observers[name] = obs

                def make_hook(o):
                    def hook(lyr, inputs):
                        o.observe(inputs[0])
                    return hook
                layer.register_forward_pre_hook(make_hook(obs))
        return model

    def convert(self, model, inplace=False):
        """Apply observed scales: swap observed Linears to the int8 deploy
        form (reference ptq.py convert)."""
        name_to_obs = dict(self.observers)

        def fold(layer, prefix=""):
            for name, sub in list(layer._sub_layers.items()):
                full = f"{prefix}.{name}" if prefix else name
                if isinstance(sub, nn.Linear) and full in name_to_obs:
                    obs = name_to_obs[full]
                    scale = obs.scales()
                    layer._sub_layers[name] = ConvertedQuantLinear(
                        sub, act_scale=float(scale)
                        if scale is not None else None)
                else:
                    fold(sub, full)
        fold(model)
        return model


def save_quantized_model(model, path, input_spec, **configs):
    """Export a converted (int8-weight) model through the serving path
    (reference: QAT export via paddle.jit.save + quant passes)."""
    from ..inference import save_inference_model

    return save_inference_model(path, model, input_spec)
