"""AMP autocast state consulted by the dispatcher.

Reference analog: the AMP auto-cast hook baked into every generated
*_ad_func (paddle/fluid/eager/amp_auto_cast.h) driven by op allow/block
lists (python/paddle/amp/amp_lists.py). bf16 is the TPU-native low
precision: MXU-native, same exponent range as fp32, so no loss scaling is
required at O1 (GradScaler still provided for fp16 parity).
"""
from __future__ import annotations

import threading

import numpy as np

# ops that benefit from low precision (matmul/conv class — MXU ops)
WHITE_LIST = {
    "matmul", "bmm", "mm", "mv", "linear", "conv1d", "conv2d", "conv3d",
    "conv1d_transpose", "conv2d_transpose", "conv3d_transpose", "einsum",
    "addmm", "scaled_dot_product_attention", "flash_attention",
}

# ops that must stay fp32 for numerics
BLACK_LIST = {
    "exp", "log", "log2", "log10", "log1p", "expm1", "pow", "square",
    "reciprocal", "rsqrt", "softmax", "log_softmax", "cross_entropy",
    "softmax_with_cross_entropy", "layer_norm", "batch_norm", "group_norm",
    "instance_norm", "rms_norm", "mse_loss", "l1_loss", "nll_loss",
    "binary_cross_entropy", "bce_with_logits", "kl_div", "sum", "mean",
    "logsumexp", "norm", "cumsum", "erf", "erfinv",
}


class _AmpState(threading.local):
    def __init__(self):
        self.enabled = False
        self.dtype = None  # np.dtype target
        self.level = "O1"
        self.custom_white = set()
        self.custom_black = set()


_state = _AmpState()


def amp_enabled():
    return _state.enabled


def amp_dtype():
    return _state.dtype


def amp_level():
    return _state.level


def set_amp(enabled, dtype=None, level="O1", custom_white=None,
            custom_black=None):
    prev = (_state.enabled, _state.dtype, _state.level, _state.custom_white,
            _state.custom_black)
    _state.enabled = enabled
    _state.dtype = dtype
    _state.level = level
    _state.custom_white = set(custom_white or ())
    _state.custom_black = set(custom_black or ())
    return prev


def restore_amp(prev):
    (_state.enabled, _state.dtype, _state.level, _state.custom_white,
     _state.custom_black) = prev


def cast_policy(op_name):
    """Return the dtype ops' float inputs should be cast to, or None."""
    if not _state.enabled:
        return None
    name = op_name or ""
    if name in _state.custom_black or name in BLACK_LIST:
        return np.dtype(np.float32)
    if _state.level == "O2":
        # O2: everything not blacklisted runs in low precision
        return _state.dtype
    if name in _state.custom_white or name in WHITE_LIST:
        return _state.dtype
    return None
