"""Dtype system for paddle_tpu.

Mirrors the reference's dtype surface (paddle.float32 etc., see
/root/reference/python/paddle/framework/dtype.py) but maps directly onto
jax.numpy scalar types so arrays stay XLA-native. bfloat16 is first-class —
it is the TPU matmul dtype (MXU-native).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# Canonical scalar types (these ARE the jnp types, so jnp ops accept them).
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
uint8 = jnp.uint8
uint16 = jnp.uint16
uint32 = jnp.uint32
uint64 = jnp.uint64
bool_ = jnp.bool_
complex64 = jnp.complex64
complex128 = jnp.complex128
float8_e4m3fn = jnp.float8_e4m3fn
float8_e5m2 = jnp.float8_e5m2

_NAME_TO_DTYPE = {
    "float16": float16,
    "bfloat16": bfloat16,
    "float32": float32,
    "float64": float64,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int64": int64,
    "uint8": uint8,
    "uint16": uint16,
    "uint32": uint32,
    "uint64": uint64,
    "bool": bool_,
    "complex64": complex64,
    "complex128": complex128,
    "float8_e4m3fn": float8_e4m3fn,
    "float8_e5m2": float8_e5m2,
    # aliases
    "fp16": float16,
    "bf16": bfloat16,
    "fp32": float32,
    "fp64": float64,
    "half": float16,
    "float": float32,
    "double": float64,
    "int": int32,
    "long": int64,
}

FLOATING = frozenset(
    np.dtype(t)
    for t in (float16, bfloat16, float32, float64, float8_e4m3fn, float8_e5m2)
)
COMPLEX = frozenset(np.dtype(t) for t in (complex64, complex128))


# XLA on TPU runs with 64-bit types disabled (jax x64 off): int64/uint64/
# float64 are LOGICAL dtypes that map onto their 32-bit physical forms, the
# same way the reference runs int64 indices through 32-bit CUDA kernels when
# safe. This keeps MXU/VPU codegen on native widths.
_LOGICAL_64 = {
    np.dtype(np.int64): np.dtype(np.int32),
    np.dtype(np.uint64): np.dtype(np.uint32),
    np.dtype(np.float64): np.dtype(np.float32),
    np.dtype(np.complex128): np.dtype(np.complex64),
}


def convert_dtype(dtype):
    """Normalize any dtype spec (str, np.dtype, jnp type, None) to the
    physical np.dtype used on device."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        if dtype not in _NAME_TO_DTYPE:
            raise ValueError(f"unknown dtype name: {dtype!r}")
        d = np.dtype(_NAME_TO_DTYPE[dtype])
    else:
        d = np.dtype(dtype)
    return _LOGICAL_64.get(d, d)


def is_floating_point(dtype) -> bool:
    d = convert_dtype(dtype)
    return d in FLOATING


def is_complex(dtype) -> bool:
    return convert_dtype(dtype) in COMPLEX


def is_integer(dtype) -> bool:
    d = convert_dtype(dtype)
    return np.issubdtype(d, np.integer)


def dtype_name(dtype) -> str:
    d = convert_dtype(dtype)
    return d.name
