from . import dtype, place, autograd
from .tensor import Tensor, Parameter
from .dispatch import apply, defop
