"""paddle_tpu.vision (reference: python/paddle/vision/)."""
from . import models
from . import transforms
from . import datasets
from .models import LeNet, ResNet, resnet18, resnet34, resnet50, resnet101, resnet152


def set_image_backend(backend):
    return None


def get_image_backend():
    return "numpy"
