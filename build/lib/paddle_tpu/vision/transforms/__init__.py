"""Minimal numpy-backed vision transforms (reference:
python/paddle/vision/transforms/)."""
from __future__ import annotations

import numbers

import numpy as np

__all__ = ["Compose", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomCrop", "RandomHorizontalFlip", "Transpose", "normalize",
           "to_tensor", "resize"]


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class ToTensor:
    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        arr = np.asarray(img, np.float32) / 255.0
        if arr.ndim == 2:
            arr = arr[None] if self.data_format == "CHW" else arr[..., None]
        elif self.data_format == "CHW" and arr.shape[-1] in (1, 3, 4):
            arr = arr.transpose(2, 0, 1)
        return arr


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def __call__(self, img):
        arr = np.asarray(img, np.float32)
        shape = [1] * arr.ndim
        ch = 0 if self.data_format == "CHW" else arr.ndim - 1
        shape[ch] = -1
        return (arr - self.mean.reshape(shape)) / self.std.reshape(shape)


def _resize_np(arr, size):
    # nearest-neighbor resize, HWC layout
    h, w = arr.shape[:2]
    if isinstance(size, numbers.Number):
        if h < w:
            oh, ow = size, int(size * w / h)
        else:
            oh, ow = int(size * h / w), size
    else:
        oh, ow = size
    yi = (np.arange(oh) * h / oh).astype(int)
    xi = (np.arange(ow) * w / ow).astype(int)
    return arr[yi][:, xi]


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = size

    def __call__(self, img):
        return _resize_np(np.asarray(img), self.size)


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, numbers.Number) else size

    def __call__(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[:2]
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return arr[i:i + th, j:j + tw]


class RandomCrop:
    def __init__(self, size, padding=None):
        self.size = (size, size) if isinstance(size, numbers.Number) else size
        self.padding = padding

    def __call__(self, img):
        arr = np.asarray(img)
        if self.padding:
            p = self.padding
            pads = [(p, p), (p, p)] + [(0, 0)] * (arr.ndim - 2)
            arr = np.pad(arr, pads)
        h, w = arr.shape[:2]
        th, tw = self.size
        i = np.random.randint(0, max(h - th, 0) + 1)
        j = np.random.randint(0, max(w - tw, 0) + 1)
        return arr[i:i + th, j:j + tw]


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return np.asarray(img)[:, ::-1].copy()
        return np.asarray(img)


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[..., None]
        return arr.transpose(self.order)


def to_tensor(img, data_format="CHW"):
    return ToTensor(data_format)(img)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size)(np.asarray(img))
