"""MobileNetV2 (reference: python/paddle/vision/models/mobilenetv2.py)."""
from __future__ import annotations

from ... import nn

__all__ = ["MobileNetV2", "mobilenet_v2"]


def _make_divisible(v, divisor=8, min_value=None):
    min_value = min_value or divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class _ConvBNReLU6(nn.Layer):
    def __init__(self, in_c, out_c, k=3, stride=1, groups=1):
        super().__init__()
        self.conv = nn.Conv2D(in_c, out_c, k, stride=stride,
                              padding=(k - 1) // 2, groups=groups,
                              bias_attr=False)
        self.bn = nn.BatchNorm2D(out_c)
        self.act = nn.ReLU6()

    def forward(self, x):
        return self.act(self.bn(self.conv(x)))


class _InvertedResidual(nn.Layer):
    def __init__(self, in_c, out_c, stride, expand_ratio):
        super().__init__()
        hidden = int(round(in_c * expand_ratio))
        self.use_res = stride == 1 and in_c == out_c
        layers = []
        if expand_ratio != 1:
            layers.append(_ConvBNReLU6(in_c, hidden, k=1))
        layers += [
            _ConvBNReLU6(hidden, hidden, stride=stride, groups=hidden),
            nn.Conv2D(hidden, out_c, 1, bias_attr=False),
            nn.BatchNorm2D(out_c),
        ]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2),
               (6, 64, 4, 2), (6, 96, 3, 1), (6, 160, 3, 2),
               (6, 320, 1, 1)]
        in_c = _make_divisible(32 * scale)
        last_c = _make_divisible(1280 * max(1.0, scale))
        layers = [_ConvBNReLU6(3, in_c, stride=2)]
        for t, c, n, s in cfg:
            out_c = _make_divisible(c * scale)
            for i in range(n):
                layers.append(_InvertedResidual(
                    in_c, out_c, s if i == 0 else 1, t))
                in_c = out_c
        layers.append(_ConvBNReLU6(in_c, last_c, k=1))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.2), nn.Linear(last_c, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.reshape([x.shape[0], -1])
            x = self.classifier(x)
        return x


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV2(scale=scale, **kwargs)
