"""MobileNetV3 Small/Large (reference:
python/paddle/vision/models/mobilenetv3.py)."""
from __future__ import annotations

from ... import nn
from .mobilenetv2 import _make_divisible

__all__ = ["MobileNetV3Small", "MobileNetV3Large", "mobilenet_v3_small",
           "mobilenet_v3_large"]


class _SE(nn.Layer):
    def __init__(self, c, r=4):
        super().__init__()
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(c, _make_divisible(c // r), 1)
        self.relu = nn.ReLU()
        self.fc2 = nn.Conv2D(_make_divisible(c // r), c, 1)
        self.hsig = nn.Hardsigmoid()

    def forward(self, x):
        s = self.hsig(self.fc2(self.relu(self.fc1(self.pool(x)))))
        return x * s


class _ConvBNAct(nn.Layer):
    def __init__(self, in_c, out_c, k, stride=1, groups=1, act="hardswish"):
        super().__init__()
        self.conv = nn.Conv2D(in_c, out_c, k, stride=stride,
                              padding=(k - 1) // 2, groups=groups,
                              bias_attr=False)
        self.bn = nn.BatchNorm2D(out_c)
        self.act = {"relu": nn.ReLU, "hardswish": nn.Hardswish,
                    None: None}[act]
        if self.act is not None:
            self.act = self.act()

    def forward(self, x):
        x = self.bn(self.conv(x))
        return self.act(x) if self.act is not None else x


class _Bneck(nn.Layer):
    def __init__(self, in_c, exp, out_c, k, stride, se, act):
        super().__init__()
        self.use_res = stride == 1 and in_c == out_c
        layers = []
        if exp != in_c:
            layers.append(_ConvBNAct(in_c, exp, 1, act=act))
        layers.append(_ConvBNAct(exp, exp, k, stride, groups=exp, act=act))
        if se:
            layers.append(_SE(exp))
        layers.append(_ConvBNAct(exp, out_c, 1, act=None))
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


_LARGE = [
    # k, exp, out, se, act, stride
    (3, 16, 16, False, "relu", 1), (3, 64, 24, False, "relu", 2),
    (3, 72, 24, False, "relu", 1), (5, 72, 40, True, "relu", 2),
    (5, 120, 40, True, "relu", 1), (5, 120, 40, True, "relu", 1),
    (3, 240, 80, False, "hardswish", 2), (3, 200, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1), (3, 184, 80, False, "hardswish", 1),
    (3, 480, 112, True, "hardswish", 1), (3, 672, 112, True, "hardswish", 1),
    (5, 672, 160, True, "hardswish", 2), (5, 960, 160, True, "hardswish", 1),
    (5, 960, 160, True, "hardswish", 1),
]
_SMALL = [
    (3, 16, 16, True, "relu", 2), (3, 72, 24, False, "relu", 2),
    (3, 88, 24, False, "relu", 1), (5, 96, 40, True, "hardswish", 2),
    (5, 240, 40, True, "hardswish", 1), (5, 240, 40, True, "hardswish", 1),
    (5, 120, 48, True, "hardswish", 1), (5, 144, 48, True, "hardswish", 1),
    (5, 288, 96, True, "hardswish", 2), (5, 576, 96, True, "hardswish", 1),
    (5, 576, 96, True, "hardswish", 1),
]


class _MobileNetV3(nn.Layer):
    def __init__(self, cfg, last_exp, last_c, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        s = lambda c: _make_divisible(c * scale)
        in_c = s(16)
        layers = [_ConvBNAct(3, in_c, 3, stride=2, act="hardswish")]
        for k, exp, out_c, se, act, stride in cfg:
            layers.append(_Bneck(in_c, s(exp), s(out_c), k, stride, se,
                                 act))
            in_c = s(out_c)
        layers.append(_ConvBNAct(in_c, s(last_exp), 1, act="hardswish"))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(s(last_exp), last_c), nn.Hardswish(),
                nn.Dropout(0.2), nn.Linear(last_c, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.reshape([x.shape[0], -1])
            x = self.classifier(x)
        return x


class MobileNetV3Large(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_LARGE, 960, 1280, scale, num_classes, with_pool)


class MobileNetV3Small(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_SMALL, 576, 1024, scale, num_classes, with_pool)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV3Small(scale=scale, **kwargs)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV3Large(scale=scale, **kwargs)
