"""ShuffleNetV2 (reference: python/paddle/vision/models/shufflenetv2.py)."""
from __future__ import annotations

from ... import nn
from ...ops.manipulation import concat, reshape, transpose

__all__ = ["ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_33",
           "shufflenet_v2_x0_5", "shufflenet_v2_x1_0",
           "shufflenet_v2_x1_5", "shufflenet_v2_x2_0",
           "shufflenet_v2_swish"]


def _channel_shuffle(x, groups):
    b, c, h, w = x.shape
    x = reshape(x, [b, groups, c // groups, h, w])
    x = transpose(x, [0, 2, 1, 3, 4])
    return reshape(x, [b, c, h, w])


class _ConvBNAct(nn.Layer):
    def __init__(self, in_c, out_c, k, stride=1, groups=1, act="relu"):
        super().__init__()
        self.conv = nn.Conv2D(in_c, out_c, k, stride=stride,
                              padding=(k - 1) // 2, groups=groups,
                              bias_attr=False)
        self.bn = nn.BatchNorm2D(out_c)
        self.act = {"relu": nn.ReLU(), "swish": nn.Swish(),
                    None: None}[act]

    def forward(self, x):
        x = self.bn(self.conv(x))
        return self.act(x) if self.act is not None else x


class _ShuffleUnit(nn.Layer):
    def __init__(self, in_c, out_c, stride, act="relu"):
        super().__init__()
        self.stride = stride
        branch_c = out_c // 2
        if stride == 1:
            self.branch2 = nn.Sequential(
                _ConvBNAct(branch_c, branch_c, 1, act=act),
                _ConvBNAct(branch_c, branch_c, 3, stride, groups=branch_c,
                           act=None),
                _ConvBNAct(branch_c, branch_c, 1, act=act))
        else:
            self.branch1 = nn.Sequential(
                _ConvBNAct(in_c, in_c, 3, stride, groups=in_c, act=None),
                _ConvBNAct(in_c, branch_c, 1, act=act))
            self.branch2 = nn.Sequential(
                _ConvBNAct(in_c, branch_c, 1, act=act),
                _ConvBNAct(branch_c, branch_c, 3, stride, groups=branch_c,
                           act=None),
                _ConvBNAct(branch_c, branch_c, 1, act=act))

    def forward(self, x):
        if self.stride == 1:
            c = x.shape[1] // 2
            x1, x2 = x[:, :c], x[:, c:]
            out = concat([x1, self.branch2(x2)], axis=1)
        else:
            out = concat([self.branch1(x), self.branch2(x)], axis=1)
        return _channel_shuffle(out, 2)


_STAGE_OUT = {
    0.25: [24, 24, 48, 96, 512], 0.33: [24, 32, 64, 128, 512],
    0.5: [24, 48, 96, 192, 1024], 1.0: [24, 116, 232, 464, 1024],
    1.5: [24, 176, 352, 704, 1024], 2.0: [24, 244, 488, 976, 2048],
}


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        cs = _STAGE_OUT[scale]
        self.conv1 = _ConvBNAct(3, cs[0], 3, stride=2, act=act)
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1)
        stages = []
        in_c = cs[0]
        for i, reps in enumerate([4, 8, 4]):
            out_c = cs[i + 1]
            units = [_ShuffleUnit(in_c, out_c, 2, act)]
            for _ in range(reps - 1):
                units.append(_ShuffleUnit(out_c, out_c, 1, act))
            stages.append(nn.Sequential(*units))
            in_c = out_c
        self.stages = nn.Sequential(*stages)
        self.conv_last = _ConvBNAct(in_c, cs[4], 1, act=act)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(cs[4], num_classes)

    def forward(self, x):
        x = self.maxpool(self.conv1(x))
        x = self.conv_last(self.stages(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.reshape([x.shape[0], -1])
            x = self.fc(x)
        return x


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return ShuffleNetV2(0.25, **kwargs)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    return ShuffleNetV2(0.33, **kwargs)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return ShuffleNetV2(0.5, **kwargs)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return ShuffleNetV2(1.0, **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return ShuffleNetV2(1.5, **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return ShuffleNetV2(2.0, **kwargs)


def shufflenet_v2_swish(pretrained=False, **kwargs):
    return ShuffleNetV2(1.0, act="swish", **kwargs)
