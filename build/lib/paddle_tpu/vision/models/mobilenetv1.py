"""MobileNetV1 (reference: python/paddle/vision/models/mobilenetv1.py)."""
from __future__ import annotations

from ... import nn

__all__ = ["MobileNetV1", "mobilenet_v1"]


class _ConvBNRelu(nn.Layer):
    def __init__(self, in_c, out_c, k, stride=1, padding=0, groups=1):
        super().__init__()
        self.conv = nn.Conv2D(in_c, out_c, k, stride=stride,
                              padding=padding, groups=groups,
                              bias_attr=False)
        self.bn = nn.BatchNorm2D(out_c)
        self.relu = nn.ReLU()

    def forward(self, x):
        return self.relu(self.bn(self.conv(x)))


class _DepthwiseSep(nn.Layer):
    def __init__(self, in_c, out_c, stride):
        super().__init__()
        self.dw = _ConvBNRelu(in_c, in_c, 3, stride, 1, groups=in_c)
        self.pw = _ConvBNRelu(in_c, out_c, 1)

    def forward(self, x):
        return self.pw(self.dw(x))


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        s = lambda c: max(int(c * scale), 8)
        cfg = [(64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
               (512, 1), (512, 1), (512, 1), (512, 1), (512, 1),
               (1024, 2), (1024, 1)]
        layers = [_ConvBNRelu(3, s(32), 3, 2, 1)]
        in_c = s(32)
        for out_c, stride in cfg:
            layers.append(_DepthwiseSep(in_c, s(out_c), stride))
            in_c = s(out_c)
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(s(1024), num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.reshape([x.shape[0], -1])
            x = self.fc(x)
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV1(scale=scale, **kwargs)
