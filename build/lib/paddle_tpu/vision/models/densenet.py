"""DenseNet (reference: python/paddle/vision/models/densenet.py)."""
from __future__ import annotations

from ... import nn
from ...ops.manipulation import concat

__all__ = ["DenseNet", "densenet121", "densenet161", "densenet169",
           "densenet201", "densenet264"]

_CFGS = {
    121: (64, 32, [6, 12, 24, 16]),
    161: (96, 48, [6, 12, 36, 24]),
    169: (64, 32, [6, 12, 32, 32]),
    201: (64, 32, [6, 12, 48, 32]),
    264: (64, 32, [6, 12, 64, 48]),
}


class _DenseLayer(nn.Layer):
    def __init__(self, in_c, growth, bn_size, dropout):
        super().__init__()
        self.bn1 = nn.BatchNorm2D(in_c)
        self.relu = nn.ReLU()
        self.conv1 = nn.Conv2D(in_c, bn_size * growth, 1, bias_attr=False)
        self.bn2 = nn.BatchNorm2D(bn_size * growth)
        self.conv2 = nn.Conv2D(bn_size * growth, growth, 3, padding=1,
                               bias_attr=False)
        self.dropout = nn.Dropout(dropout) if dropout else None

    def forward(self, x):
        out = self.conv1(self.relu(self.bn1(x)))
        out = self.conv2(self.relu(self.bn2(out)))
        if self.dropout is not None:
            out = self.dropout(out)
        return concat([x, out], axis=1)


class _Transition(nn.Layer):
    def __init__(self, in_c, out_c):
        super().__init__()
        self.bn = nn.BatchNorm2D(in_c)
        self.relu = nn.ReLU()
        self.conv = nn.Conv2D(in_c, out_c, 1, bias_attr=False)
        self.pool = nn.AvgPool2D(2, stride=2)

    def forward(self, x):
        return self.pool(self.conv(self.relu(self.bn(x))))


class DenseNet(nn.Layer):
    def __init__(self, layers=121, bn_size=4, dropout=0.0,
                 num_classes=1000, with_pool=True):
        super().__init__()
        num_init, growth, block_cfg = _CFGS[layers]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            nn.Conv2D(3, num_init, 7, stride=2, padding=3,
                      bias_attr=False),
            nn.BatchNorm2D(num_init), nn.ReLU(),
            nn.MaxPool2D(3, stride=2, padding=1))
        blocks = []
        c = num_init
        for i, n in enumerate(block_cfg):
            for _ in range(n):
                blocks.append(_DenseLayer(c, growth, bn_size, dropout))
                c += growth
            if i != len(block_cfg) - 1:
                blocks.append(_Transition(c, c // 2))
                c //= 2
        self.blocks = nn.Sequential(*blocks)
        self.bn_last = nn.BatchNorm2D(c)
        self.relu = nn.ReLU()
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(c, num_classes)

    def forward(self, x):
        x = self.relu(self.bn_last(self.blocks(self.stem(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.reshape([x.shape[0], -1])
            x = self.fc(x)
        return x


def densenet121(pretrained=False, **kwargs):
    return DenseNet(121, **kwargs)


def densenet161(pretrained=False, **kwargs):
    return DenseNet(161, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return DenseNet(169, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return DenseNet(201, **kwargs)


def densenet264(pretrained=False, **kwargs):
    return DenseNet(264, **kwargs)
