"""Dataset stubs + synthetic datasets (reference: python/paddle/vision/datasets/).
Real dataset downloads are environment-gated (zero egress); FakeData mirrors
torchvision-style synthetic data for smoke training."""
from __future__ import annotations

import numpy as np

from ...io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "FakeImageDataset"]


class FakeImageDataset(Dataset):
    def __init__(self, num_samples=1024, image_shape=(1, 28, 28),
                 num_classes=10, transform=None, seed=0):
        self.num_samples = num_samples
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self._rng = np.random.RandomState(seed)
        self._images = self._rng.rand(
            num_samples, *self.image_shape).astype(np.float32)
        self._labels = self._rng.randint(
            0, num_classes, (num_samples, 1)).astype(np.int64)

    def __getitem__(self, idx):
        img = self._images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self._labels[idx]

    def __len__(self):
        return self.num_samples


class MNIST(FakeImageDataset):
    """Offline env: synthesizes MNIST-shaped data; pass data_file to load a
    local .npz with keys images/labels."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None,
                 data_file=None):
        if data_file is not None:
            d = np.load(data_file)
            n = len(d["labels"])
            super().__init__(n, (1, 28, 28), 10, transform)
            self._images = d["images"].astype(np.float32).reshape(
                n, 1, 28, 28)
            self._labels = d["labels"].astype(np.int64).reshape(n, 1)
        else:
            n = 60000 if mode == "train" else 10000
            super().__init__(min(n, 4096), (1, 28, 28), 10, transform)


class FashionMNIST(MNIST):
    pass


class Cifar10(FakeImageDataset):
    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        n = 2048 if mode == "train" else 512
        super().__init__(n, (3, 32, 32), 10, transform)

    def __getitem__(self, idx):
        img, label = super().__getitem__(idx)
        return img, int(label[0])


class Cifar100(Cifar10):
    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        n = 2048 if mode == "train" else 512
        FakeImageDataset.__init__(self, n, (3, 32, 32), 100, transform)
