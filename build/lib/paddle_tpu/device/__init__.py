"""paddle_tpu.device (reference: python/paddle/device/__init__.py:62,191)."""
from __future__ import annotations

import jax

from ..core.place import (CPUPlace, Place, TPUPlace, get_device, set_device)

__all__ = ["set_device", "get_device", "get_all_device_type",
           "get_all_custom_device_type", "get_available_device",
           "get_available_custom_device", "is_compiled_with_cinn",
           "device_count", "synchronize", "Stream", "Event",
           "current_stream", "stream_guard", "cuda", "xpu"]


def get_all_device_type():
    return ["cpu", "tpu"]


def get_all_custom_device_type():
    return ["tpu"]


def get_available_device():
    out = ["cpu"]
    try:
        if jax.default_backend() != "cpu":
            out += [f"tpu:{i}" for i in range(len(jax.devices()))]
    except Exception:
        pass
    return out


def get_available_custom_device():
    return [d for d in get_available_device() if d != "cpu"]


def device_count():
    try:
        return len(jax.devices())
    except Exception:
        return 0


def is_compiled_with_cinn():
    return False


def synchronize(device=None):
    """Block until all dispatched device work finishes (the analog of
    cudaDeviceSynchronize; XLA exposes it as blocking on array readiness)."""
    try:
        (jax.device_put(0) + 0).block_until_ready()
    except Exception:
        pass


class Stream:
    """XLA manages its own streams; kept for API parity."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize()

    def wait_stream(self, stream):
        pass

    def record_event(self, event=None):
        return event or Event()

    def wait_event(self, event):
        pass


class Event:
    def __init__(self, enable_timing=False, blocking=False, interprocess=False):
        pass

    def record(self, stream=None):
        pass

    def query(self):
        return True

    def synchronize(self):
        synchronize()


_current = Stream()


def current_stream(device=None):
    return _current


class stream_guard:
    def __init__(self, stream):
        self.stream = stream

    def __enter__(self):
        return self.stream

    def __exit__(self, *exc):
        return False


class _CudaCompat:
    """paddle.device.cuda compatibility namespace -> TPU."""

    @staticmethod
    def device_count():
        return device_count()

    @staticmethod
    def is_available():
        return jax.default_backend() != "cpu"

    @staticmethod
    def synchronize(device=None):
        synchronize()

    @staticmethod
    def empty_cache():
        pass

    @staticmethod
    def max_memory_allocated(device=None):
        try:
            stats = jax.devices()[0].memory_stats()
            return stats.get("peak_bytes_in_use", 0)
        except Exception:
            return 0

    @staticmethod
    def memory_allocated(device=None):
        try:
            stats = jax.devices()[0].memory_stats()
            return stats.get("bytes_in_use", 0)
        except Exception:
            return 0

    @staticmethod
    def max_memory_reserved(device=None):
        return _CudaCompat.max_memory_allocated(device)

    @staticmethod
    def memory_reserved(device=None):
        return _CudaCompat.memory_allocated(device)

    Stream = Stream
    Event = Event


cuda = _CudaCompat()
xpu = _CudaCompat()
