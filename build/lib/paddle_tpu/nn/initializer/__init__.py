"""Weight initializers (reference: python/paddle/nn/initializer/)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dtype import convert_dtype
from ...framework.random import next_key

__all__ = [
    "Initializer", "Constant", "Normal", "TruncatedNormal", "Uniform",
    "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
    "Assign", "Orthogonal", "Dirac", "calculate_gain", "set_global_initializer",
]


def calculate_gain(nonlinearity, param=None):
    gains = {
        "sigmoid": 1.0,
        "linear": 1.0,
        "conv1d": 1.0,
        "conv2d": 1.0,
        "conv3d": 1.0,
        "tanh": 5.0 / 3,
        "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param if param is not None
                                            else 0.01) ** 2)),
        "selu": 3.0 / 4,
    }
    return gains[nonlinearity]


def _fan_in_out(shape):
    shape = tuple(int(s) for s in shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels: [out_c, in_c, *spatial] (reference layout)
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(tuple(int(s) for s in shape), self.value, dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        return self.mean + self.std * jax.random.normal(
            next_key(), tuple(int(s) for s in shape), jnp.float32
        ).astype(dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype):
        z = jax.random.truncated_normal(
            next_key(), self.a, self.b, tuple(int(s) for s in shape),
            jnp.float32)
        return (self.mean + self.std * z).astype(dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        return jax.random.uniform(
            next_key(), tuple(int(s) for s in shape), jnp.float32, self.low,
            self.high).astype(dtype)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return (std * jax.random.normal(
            next_key(), tuple(int(s) for s in shape), jnp.float32
        )).astype(dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(
            next_key(), tuple(int(s) for s in shape), jnp.float32, -limit,
            limit).astype(dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        return (std * jax.random.normal(
            next_key(), tuple(int(s) for s in shape), jnp.float32
        )).astype(dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(
            next_key(), tuple(int(s) for s in shape), jnp.float32, -limit,
            limit).astype(dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype):
        from ...core.tensor import Tensor

        v = self.value
        if isinstance(v, Tensor):
            v = v.numpy()
        return jnp.asarray(np.asarray(v), dtype=dtype).reshape(
            tuple(int(s) for s in shape))


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype):
        shape = tuple(int(s) for s in shape)
        rows = shape[0]
        cols = int(np.prod(shape[1:])) if len(shape) > 1 else 1
        flat = jax.random.normal(next_key(), (max(rows, cols),
                                              min(rows, cols)), jnp.float32)
        q, r = jnp.linalg.qr(flat)
        q = q * jnp.sign(jnp.diagonal(r))
        if rows < cols:
            q = q.T
        return (self.gain * q[:rows, :cols]).reshape(shape).astype(dtype)


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype):
        shape = tuple(int(s) for s in shape)
        out = np.zeros(shape, np.float32)
        out_c, in_c = shape[0], shape[1]
        minc = min(out_c // self.groups, in_c)
        centers = tuple(s // 2 for s in shape[2:])
        for g in range(self.groups):
            for i in range(minc):
                out[(g * (out_c // self.groups) + i, i) + centers] = 1.0
        return jnp.asarray(out, dtype)


_global_weight_init = None
_global_bias_init = None


def set_global_initializer(weight_init, bias_init=None):
    global _global_weight_init, _global_bias_init
    _global_weight_init = weight_init
    _global_bias_init = bias_init


def _apply_initializer(init, shape, dtype):
    d = convert_dtype(dtype)
    return init(shape, d)
