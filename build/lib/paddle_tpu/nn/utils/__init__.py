"""nn.utils (reference: python/paddle/nn/utils/)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor

__all__ = ["clip_grad_norm_", "clip_grad_value_", "parameters_to_vector",
           "vector_to_parameters", "weight_norm", "remove_weight_norm",
           "spectral_norm"]


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p.grad for p in parameters if p.grad is not None]
    if not grads:
        return Tensor(jnp.zeros(()))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g._value))
                                   for g in grads]))
    else:
        total = jnp.power(
            sum(jnp.sum(jnp.power(jnp.abs(g._value.astype(jnp.float32)),
                                  norm_type)) for g in grads),
            1.0 / norm_type)
    clip_coef = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    for p in parameters:
        if p.grad is not None:
            p.grad._value = (p.grad._value.astype(jnp.float32)
                             * clip_coef).astype(p.grad._value.dtype)
    return Tensor(total)


def clip_grad_value_(parameters, clip_value):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    for p in parameters:
        if p.grad is not None:
            p.grad._value = jnp.clip(p.grad._value, -clip_value, clip_value)


def parameters_to_vector(parameters, name=None):
    return Tensor(jnp.concatenate(
        [p._value.reshape(-1) for p in parameters]))


def vector_to_parameters(vec, parameters, name=None):
    offset = 0
    for p in parameters:
        n = int(np.prod(p.shape))
        p.set_value(vec._value[offset:offset + n].reshape(p.shape))
        offset += n


def weight_norm(layer, name="weight", dim=0):
    # normalize-at-access reparameterization
    import jax

    weight = getattr(layer, name)
    w = weight._value
    if dim is None:
        g = jnp.linalg.norm(w)
    else:
        axes = tuple(i for i in range(w.ndim) if i != dim)
        g = jnp.sqrt(jnp.sum(jnp.square(w), axis=axes))
    from ...core.tensor import Parameter

    layer.add_parameter(name + "_g", Parameter(g))
    layer.add_parameter(name + "_v", Parameter(w))
    del layer._parameters[name]

    def hook(lyr, inputs):
        from ...core.dispatch import apply

        def fn(g_, v_):
            if dim is None:
                return v_ * (g_ / jnp.linalg.norm(v_))
            axes = tuple(i for i in range(v_.ndim) if i != dim)
            norm = jnp.sqrt(jnp.sum(jnp.square(v_), axis=axes,
                                    keepdims=True))
            shape = [1] * v_.ndim
            shape[dim] = -1
            return v_ / norm * g_.reshape(shape)
        w_t = apply(fn, getattr(lyr, name + "_g"), getattr(lyr, name + "_v"),
                    op_name="weight_norm")
        object.__setattr__(lyr, name, w_t)
    layer._weight_norm_hook = layer.register_forward_pre_hook(hook)
    return layer


def remove_weight_norm(layer, name="weight"):
    hook = getattr(layer, "_weight_norm_hook", None)
    if hook is not None:
        hook.remove()
    g = layer._parameters.pop(name + "_g")
    v = layer._parameters.pop(name + "_v")
    import jax.numpy as jnp

    w = v._value
    from ...core.tensor import Parameter

    layer.add_parameter(name, Parameter(w))
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=None):
    return layer
