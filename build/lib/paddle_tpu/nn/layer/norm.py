"""Normalization layers (reference: python/paddle/nn/layer/norm.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Tensor
from .. import functional as F
from ..initializer import Constant
from .layers import Layer

__all__ = ["LayerNorm", "BatchNorm", "BatchNorm1D", "BatchNorm2D",
           "BatchNorm3D", "SyncBatchNorm", "GroupNorm", "InstanceNorm1D",
           "InstanceNorm2D", "InstanceNorm3D", "LocalResponseNorm", "RMSNorm",
           "SpectralNorm"]


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is not False:
            self.weight = self.create_parameter(
                self._normalized_shape, attr=weight_attr,
                default_initializer=Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                self._normalized_shape, attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight,
                            self.bias, self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}"


class RMSNorm(Layer):
    """TPU-first: routed to the Pallas rmsnorm kernel (see
    paddle_tpu/ops/pallas/rms_norm.py)."""

    def __init__(self, normalized_shape, epsilon=1e-6, weight_attr=None,
                 name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            list(normalized_shape), attr=weight_attr,
            default_initializer=Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self._epsilon)


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        if weight_attr is not False:
            self.weight = self.create_parameter(
                [num_features], attr=weight_attr,
                default_initializer=Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter([num_features], attr=bias_attr,
                                              is_bias=True)
        else:
            self.bias = None
        self.register_buffer("_mean", Tensor(jnp.zeros(num_features)))
        self.register_buffer("_variance", Tensor(jnp.ones(num_features)))

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum,
            epsilon=self._epsilon, data_format=self._data_format,
            use_global_stats=self._use_global_stats)


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica batchnorm. On TPU, inside pjit/shard_map the batch axis
    is sharded and XLA computes global statistics when the reduction spans
    the mesh axis; in eager DP, stats are synced via the dp process group."""

    def forward(self, x):
        from ...distributed import collective

        if self.training and collective.is_initialized() and \
                collective.get_world_size() > 1:
            # eager path: compute local stats, allreduce them
            import jax

            ch_axis = 1 if x.ndim > 2 else x.ndim - 1
            axes = tuple(i for i in range(x.ndim) if i != ch_axis)
            xa = x._value.astype(jnp.float32)
            mean = jnp.mean(xa, axis=axes)
            meansq = jnp.mean(jnp.square(xa), axis=axes)
            stats = Tensor(jnp.concatenate([mean, meansq]))
            collective.all_reduce(stats)
            n = collective.get_world_size()
            stats = stats / n
            gm = stats._value[: self._num_features]
            gv = stats._value[self._num_features:] - jnp.square(gm)
            mean_t, var_t = Tensor(gm), Tensor(gv)
            return F.batch_norm(
                x, mean_t, var_t, self.weight, self.bias, training=False,
                momentum=self._momentum, epsilon=self._epsilon,
                data_format=self._data_format, use_global_stats=True)
        return super().forward(x)

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        out = layer
        if isinstance(layer, _BatchNormBase) and not isinstance(
                layer, SyncBatchNorm):
            out = SyncBatchNorm(layer._num_features, layer._momentum,
                                layer._epsilon)
            if layer.weight is not None:
                out.weight.set_value(layer.weight)
                out.bias.set_value(layer.bias)
            out._mean.set_value(layer._mean)
            out._variance.set_value(layer._variance)
        for name, sub in layer._sub_layers.items():
            out._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return out


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        if weight_attr is not False:
            self.weight = self.create_parameter(
                [num_channels], attr=weight_attr,
                default_initializer=Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                [num_channels], attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight,
                            self.bias)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is not False:
            self.weight = self.create_parameter(
                [num_features], attr=weight_attr,
                default_initializer=Constant(1.0))
            self.bias = self.create_parameter([num_features], attr=bias_attr,
                                              is_bias=True)
        else:
            self.weight = None
            self.bias = None

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self._epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.args = (size, alpha, beta, k)

    def forward(self, x):
        return F.local_response_norm(x, *self.args)


class SpectralNorm(Layer):
    def __init__(self, weight_shape, axis=0, power_iters=1, epsilon=1e-12,
                 name=None, dtype="float32"):
        super().__init__()
        self._power_iters = power_iters
        self._epsilon = epsilon
        self._axis = axis
        self._weight_shape = list(weight_shape)
        import numpy as np

        h = self._weight_shape[axis]
        w = int(np.prod(self._weight_shape)) // h
        from ..initializer import Normal

        self.weight_u = self.create_parameter(
            [h], default_initializer=Normal(0.0, 1.0))
        self.weight_u.stop_gradient = True
        self.weight_v = self.create_parameter(
            [w], default_initializer=Normal(0.0, 1.0))
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        from ...core.dispatch import apply

        axis = self._axis
        p = self._power_iters
        eps = self._epsilon

        def fn(w, u, v):
            h = w.shape[axis]
            mat = jnp.moveaxis(w, axis, 0).reshape(h, -1)
            for _ in range(p):
                v = mat.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = mat @ v
                u = u / (jnp.linalg.norm(u) + eps)
            sigma = u @ mat @ v
            return w / sigma

        return apply(fn, weight, self.weight_u, self.weight_v,
                     op_name="spectral_norm")
