"""Recurrent layers (reference: python/paddle/nn/layer/rnn.py).

TPU-first design: the time loop is a jax.lax.scan inside a single recorded
op, so the whole unrolled recurrence is ONE tape node whose backward is the
scanned transpose — XLA compiles it as a fused loop instead of S separate
kernel launches."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...core.dispatch import apply
from ...core.tensor import Tensor
from ..initializer import Uniform
from .layers import Layer, LayerList

__all__ = ["SimpleRNNCell", "LSTMCell", "GRUCell", "RNN", "SimpleRNN",
           "LSTM", "GRU", "BiRNN"]


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        b = batch_ref.shape[batch_dim_idx]
        return Tensor(jnp.full((b, self.hidden_size), init_value,
                               jnp.float32))


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        std = 1.0 / math.sqrt(hidden_size)
        u = Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            [hidden_size, input_size], weight_ih_attr, default_initializer=u)
        self.weight_hh = self.create_parameter(
            [hidden_size, hidden_size], weight_hh_attr, default_initializer=u)
        self.bias_ih = self.create_parameter(
            [hidden_size], bias_ih_attr, is_bias=True, default_initializer=u)
        self.bias_hh = self.create_parameter(
            [hidden_size], bias_hh_attr, is_bias=True, default_initializer=u)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu

        def fn(x, h, wi, wh, bi, bh):
            out = act(x @ wi.T + bi + h @ wh.T + bh)
            return out
        h = apply(fn, inputs, states, self.weight_ih, self.weight_hh,
                  self.bias_ih, self.bias_hh, op_name="simple_rnn_cell")
        return h, h

    @property
    def state_shape(self):
        return (self.hidden_size,)


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 proj_size=0, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        u = Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            [4 * hidden_size, input_size], weight_ih_attr,
            default_initializer=u)
        self.weight_hh = self.create_parameter(
            [4 * hidden_size, hidden_size], weight_hh_attr,
            default_initializer=u)
        self.bias_ih = self.create_parameter(
            [4 * hidden_size], bias_ih_attr, is_bias=True,
            default_initializer=u)
        self.bias_hh = self.create_parameter(
            [4 * hidden_size], bias_hh_attr, is_bias=True,
            default_initializer=u)

    def forward(self, inputs, states=None):
        if states is None:
            h = self.get_initial_states(inputs)
            c = self.get_initial_states(inputs)
            states = (h, c)
        h, c = states

        def fn(x, h, c, wi, wh, bi, bh):
            gates = x @ wi.T + bi + h @ wh.T + bh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            c_new = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
            h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
            return h_new, c_new
        h_new, c_new = apply(fn, inputs, h, c, self.weight_ih,
                             self.weight_hh, self.bias_ih, self.bias_hh,
                             op_name="lstm_cell")
        return h_new, (h_new, c_new)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        u = Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            [3 * hidden_size, input_size], weight_ih_attr,
            default_initializer=u)
        self.weight_hh = self.create_parameter(
            [3 * hidden_size, hidden_size], weight_hh_attr,
            default_initializer=u)
        self.bias_ih = self.create_parameter(
            [3 * hidden_size], bias_ih_attr, is_bias=True,
            default_initializer=u)
        self.bias_hh = self.create_parameter(
            [3 * hidden_size], bias_hh_attr, is_bias=True,
            default_initializer=u)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)

        def fn(x, h, wi, wh, bi, bh):
            xg = x @ wi.T + bi
            hg = h @ wh.T + bh
            xr, xz, xn = jnp.split(xg, 3, axis=-1)
            hr, hz, hn = jnp.split(hg, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            n = jnp.tanh(xn + r * hn)
            return (1 - z) * n + z * h
        h = apply(fn, inputs, states, self.weight_ih, self.weight_hh,
                  self.bias_ih, self.bias_hh, op_name="gru_cell")
        return h, h

    @property
    def state_shape(self):
        return (self.hidden_size,)


def _scan_rnn(cell_kind, x, init_states, weights, time_major, reverse):
    """Run a whole sequence as one lax.scan op (single tape node)."""
    def fn(xs, *flat):
        if cell_kind == "lstm":
            h0, c0, wi, wh, bi, bh = flat
            carry0 = (h0, c0)
        else:
            h0, wi, wh, bi, bh = flat
            carry0 = h0
        seq = xs if time_major else jnp.swapaxes(xs, 0, 1)
        if reverse:
            seq = jnp.flip(seq, axis=0)

        def step(carry, xt):
            if cell_kind == "lstm":
                h, c = carry
                gates = xt @ wi.T + bi + h @ wh.T + bh
                i, f, g, o = jnp.split(gates, 4, axis=-1)
                c_new = jax.nn.sigmoid(f) * c \
                    + jax.nn.sigmoid(i) * jnp.tanh(g)
                h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
                return (h_new, c_new), h_new
            if cell_kind == "gru":
                h = carry
                xg = xt @ wi.T + bi
                hg = h @ wh.T + bh
                xr, xz, xn = jnp.split(xg, 3, axis=-1)
                hr, hz, hn = jnp.split(hg, 3, axis=-1)
                r = jax.nn.sigmoid(xr + hr)
                z = jax.nn.sigmoid(xz + hz)
                n = jnp.tanh(xn + r * hn)
                h_new = (1 - z) * n + z * h
                return h_new, h_new
            h = carry
            h_new = jnp.tanh(xt @ wi.T + bi + h @ wh.T + bh)
            return h_new, h_new

        final, outs = jax.lax.scan(step, carry0, seq)
        if reverse:
            outs = jnp.flip(outs, axis=0)
        if not time_major:
            outs = jnp.swapaxes(outs, 0, 1)
        if cell_kind == "lstm":
            return outs, final[0], final[1]
        return outs, final

    args = [x] + list(init_states) + list(weights)
    return apply(fn, *args, op_name=f"{cell_kind}_layer")


class RNN(Layer):
    """Wraps a cell into a full-sequence runner (reference RNN wrapper)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        kind = ("lstm" if isinstance(self.cell, LSTMCell)
                else "gru" if isinstance(self.cell, GRUCell) else "rnn")
        if initial_states is None:
            if kind == "lstm":
                initial_states = (self.cell.get_initial_states(inputs),
                                  self.cell.get_initial_states(inputs))
            else:
                initial_states = self.cell.get_initial_states(inputs)
        states = initial_states if isinstance(initial_states, (list, tuple)) \
            else (initial_states,)
        weights = (self.cell.weight_ih, self.cell.weight_hh,
                   self.cell.bias_ih, self.cell.bias_hh)
        outs = _scan_rnn(kind, inputs, states, weights, self.time_major,
                         self.is_reverse)
        if kind == "lstm":
            return outs[0], (outs[1], outs[2])
        return outs[0], outs[1]


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        fw_states = bw_states = None
        if initial_states is not None:
            fw_states, bw_states = initial_states
        out_fw, st_fw = self.rnn_fw(inputs, fw_states)
        out_bw, st_bw = self.rnn_bw(inputs, bw_states)
        from ...ops.manipulation import concat

        return concat([out_fw, out_bw], axis=-1), (st_fw, st_bw)


class _RNNBase(Layer):
    _cell_cls = None
    _kind = "rnn"

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.bidirectional = direction in ("bidirect", "bidirectional")
        num_dir = 2 if self.bidirectional else 1
        self.layers = LayerList()
        for i in range(num_layers):
            in_size = input_size if i == 0 else hidden_size * num_dir
            if self.bidirectional:
                kw = {}
                if self._kind == "rnn":
                    kw["activation"] = activation
                self.layers.append(BiRNN(
                    self._cell_cls(in_size, hidden_size, **kw),
                    self._cell_cls(in_size, hidden_size, **kw), time_major))
            else:
                kw = {}
                if self._kind == "rnn":
                    kw["activation"] = activation
                self.layers.append(RNN(
                    self._cell_cls(in_size, hidden_size, **kw),
                    time_major=time_major))

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from .. import functional as F

        out = inputs
        final_states = []
        for i, layer in enumerate(self.layers):
            out, st = layer(out)
            final_states.append(st)
            if self.dropout > 0 and i < self.num_layers - 1:
                out = F.dropout(out, self.dropout, training=self.training)
        return out, final_states


class SimpleRNN(_RNNBase):
    _cell_cls = SimpleRNNCell
    _kind = "rnn"


class LSTM(_RNNBase):
    _cell_cls = LSTMCell
    _kind = "lstm"


class GRU(_RNNBase):
    _cell_cls = GRUCell
    _kind = "gru"
