"""Activation layers (reference: python/paddle/nn/layer/activation.py)."""
from __future__ import annotations

from .. import functional as F
from ..initializer import Constant
from .layers import Layer

__all__ = ["ReLU", "ReLU6", "GELU", "SiLU", "Swish", "Sigmoid", "Tanh",
           "Softmax", "LogSoftmax", "Softplus", "Softsign", "Softshrink",
           "Hardshrink", "Hardsigmoid", "Hardswish", "Hardtanh", "LeakyReLU",
           "ELU", "SELU", "CELU", "PReLU", "RReLU", "Mish", "Tanhshrink",
           "ThresholdedReLU", "Maxout", "GLU", "LogSigmoid"]


def _act_layer(name, fname, **defaults):
    def make(cls_name):
        class _Act(Layer):
            def __init__(self, *args, **kwargs):
                super().__init__()
                self._args = args
                self._kwargs = {**defaults, **kwargs}

            def forward(self, x):
                return getattr(F, fname)(x, *self._args, **self._kwargs)

        _Act.__name__ = cls_name
        _Act.__qualname__ = cls_name
        return _Act
    return make(name)


ReLU = _act_layer("ReLU", "relu")
ReLU6 = _act_layer("ReLU6", "relu6")
GELU = _act_layer("GELU", "gelu")
SiLU = _act_layer("SiLU", "silu")
Swish = _act_layer("Swish", "swish")
Sigmoid = _act_layer("Sigmoid", "sigmoid")
Tanh = _act_layer("Tanh", "tanh")
Softmax = _act_layer("Softmax", "softmax")
LogSoftmax = _act_layer("LogSoftmax", "log_softmax")
Softplus = _act_layer("Softplus", "softplus")
Softsign = _act_layer("Softsign", "softsign")
Softshrink = _act_layer("Softshrink", "softshrink")
Hardshrink = _act_layer("Hardshrink", "hardshrink")
Hardsigmoid = _act_layer("Hardsigmoid", "hardsigmoid")
Hardswish = _act_layer("Hardswish", "hardswish")
Hardtanh = _act_layer("Hardtanh", "hardtanh")
LeakyReLU = _act_layer("LeakyReLU", "leaky_relu")
ELU = _act_layer("ELU", "elu")
SELU = _act_layer("SELU", "selu")
CELU = _act_layer("CELU", "celu")
Mish = _act_layer("Mish", "mish")
Tanhshrink = _act_layer("Tanhshrink", "tanhshrink")
ThresholdedReLU = _act_layer("ThresholdedReLU", "thresholded_relu")
GLU = _act_layer("GLU", "glu")
LogSigmoid = _act_layer("LogSigmoid", "log_sigmoid")


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter(
            [num_parameters], attr=weight_attr,
            default_initializer=Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, data_format=self._data_format)


class RReLU(Layer):
    def __init__(self, lower=1.0 / 8, upper=1.0 / 3, name=None):
        super().__init__()
        self.lower, self.upper = lower, upper

    def forward(self, x):
        return F.rrelu(x, self.lower, self.upper, training=self.training)


class Maxout(Layer):
    def __init__(self, groups, axis=1, name=None):
        super().__init__()
        self.groups, self.axis = groups, axis

    def forward(self, x):
        return F.maxout(x, self.groups, self.axis)
