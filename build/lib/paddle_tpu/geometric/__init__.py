"""paddle.geometric (reference: python/paddle/geometric/ — message passing
+ segment ops). Segment ops map to jax.ops.segment_* (XLA scatter-reduce)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.tensor import Tensor

__all__ = ["segment_sum", "segment_mean", "segment_max", "segment_min",
           "send_u_recv", "send_ue_recv", "send_uv"]


def _nseg(segment_ids):
    import numpy as np

    ids = segment_ids.numpy() if isinstance(segment_ids, Tensor) else \
        np.asarray(segment_ids)
    return int(ids.max()) + 1 if ids.size else 0


def segment_sum(data, segment_ids, name=None):
    n = _nseg(segment_ids)
    return apply(lambda d, i: jax.ops.segment_sum(d, i, num_segments=n),
                 data, segment_ids, op_name="segment_sum")


def segment_mean(data, segment_ids, name=None):
    n = _nseg(segment_ids)

    def fn(d, i):
        s = jax.ops.segment_sum(d, i, num_segments=n)
        c = jax.ops.segment_sum(jnp.ones(d.shape[:1]), i, num_segments=n)
        return s / jnp.maximum(c, 1.0).reshape((-1,) + (1,) * (d.ndim - 1))
    return apply(fn, data, segment_ids, op_name="segment_mean")


def segment_max(data, segment_ids, name=None):
    n = _nseg(segment_ids)
    return apply(lambda d, i: jax.ops.segment_max(d, i, num_segments=n),
                 data, segment_ids, op_name="segment_max")


def segment_min(data, segment_ids, name=None):
    n = _nseg(segment_ids)
    return apply(lambda d, i: jax.ops.segment_min(d, i, num_segments=n),
                 data, segment_ids, op_name="segment_min")


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather x[src], scatter-reduce to dst (reference message passing)."""
    import numpy as np

    n = out_size or (int(dst_index.numpy().max()) + 1
                     if isinstance(dst_index, Tensor)
                     else int(np.asarray(dst_index).max()) + 1)

    def fn(xa, s, d):
        msgs = jnp.take(xa, s, axis=0)
        if reduce_op == "sum":
            return jax.ops.segment_sum(msgs, d, num_segments=n)
        if reduce_op == "mean":
            tot = jax.ops.segment_sum(msgs, d, num_segments=n)
            cnt = jax.ops.segment_sum(jnp.ones(msgs.shape[:1]), d,
                                      num_segments=n)
            return tot / jnp.maximum(cnt, 1.0).reshape(
                (-1,) + (1,) * (msgs.ndim - 1))
        if reduce_op == "max":
            return jax.ops.segment_max(msgs, d, num_segments=n)
        if reduce_op == "min":
            return jax.ops.segment_min(msgs, d, num_segments=n)
        raise ValueError(reduce_op)
    return apply(fn, x, src_index, dst_index, op_name="send_u_recv")


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    import numpy as np

    n = out_size or (int(dst_index.numpy().max()) + 1
                     if isinstance(dst_index, Tensor)
                     else int(np.asarray(dst_index).max()) + 1)

    def fn(xa, ya, s, d):
        msgs = jnp.take(xa, s, axis=0)
        if message_op == "add":
            msgs = msgs + ya
        elif message_op == "mul":
            msgs = msgs * ya
        if reduce_op == "sum":
            return jax.ops.segment_sum(msgs, d, num_segments=n)
        if reduce_op == "max":
            return jax.ops.segment_max(msgs, d, num_segments=n)
        raise ValueError(reduce_op)
    return apply(fn, x, y, src_index, dst_index, op_name="send_ue_recv")


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    def fn(xa, ya, s, d):
        a = jnp.take(xa, s, axis=0)
        b = jnp.take(ya, d, axis=0)
        return a + b if message_op == "add" else a * b
    return apply(fn, x, y, src_index, dst_index, op_name="send_uv")
