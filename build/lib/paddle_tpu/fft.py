"""paddle_tpu.fft (reference: python/paddle/fft.py) — jnp.fft backed."""
from __future__ import annotations

import jax.numpy as jnp

from .core.dispatch import apply

__all__ = ["fft", "ifft", "fft2", "ifft2", "fftn", "ifftn", "rfft", "irfft",
           "rfft2", "irfft2", "rfftn", "irfftn", "hfft", "ihfft", "fftfreq",
           "rfftfreq", "fftshift", "ifftshift"]


def _mk(name, fn, has_n=True):
    if has_n:
        def op(x, n=None, axis=-1, norm="backward", name=None):
            return apply(lambda a: fn(a, n=n, axis=int(axis), norm=norm), x,
                         op_name=name)
    else:
        def op(x, s=None, axes=None, norm="backward", name=None):
            return apply(lambda a: fn(a, s=s, axes=axes, norm=norm), x,
                         op_name=name)
    op.__name__ = name
    return op


fft = _mk("fft", jnp.fft.fft)
ifft = _mk("ifft", jnp.fft.ifft)
rfft = _mk("rfft", jnp.fft.rfft)
irfft = _mk("irfft", jnp.fft.irfft)
hfft = _mk("hfft", jnp.fft.hfft)
ihfft = _mk("ihfft", jnp.fft.ihfft)
fftn = _mk("fftn", jnp.fft.fftn, has_n=False)
ifftn = _mk("ifftn", jnp.fft.ifftn, has_n=False)
rfftn = _mk("rfftn", jnp.fft.rfftn, has_n=False)
irfftn = _mk("irfftn", jnp.fft.irfftn, has_n=False)


def fft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return apply(lambda a: jnp.fft.fft2(a, s=s, axes=axes, norm=norm), x,
                 op_name="fft2")


def ifft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return apply(lambda a: jnp.fft.ifft2(a, s=s, axes=axes, norm=norm), x,
                 op_name="ifft2")


def rfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return apply(lambda a: jnp.fft.rfft2(a, s=s, axes=axes, norm=norm), x,
                 op_name="rfft2")


def irfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return apply(lambda a: jnp.fft.irfft2(a, s=s, axes=axes, norm=norm), x,
                 op_name="irfft2")


def fftfreq(n, d=1.0, dtype=None, name=None):
    from .core.tensor import Tensor

    return Tensor(jnp.fft.fftfreq(int(n), d))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    from .core.tensor import Tensor

    return Tensor(jnp.fft.rfftfreq(int(n), d))


def fftshift(x, axes=None, name=None):
    return apply(lambda a: jnp.fft.fftshift(a, axes=axes), x,
                 op_name="fftshift")


def ifftshift(x, axes=None, name=None):
    return apply(lambda a: jnp.fft.ifftshift(a, axes=axes), x,
                 op_name="ifftshift")
