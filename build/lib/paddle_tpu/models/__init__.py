from . import bert, gpt, llama
from .bert import BERT_PRESETS, BertConfig, BertForPretraining, BertModel
from .gpt import GPT_PRESETS, GPTConfig, GPTForCausalLM
from .llama import LLAMA_PRESETS, LlamaConfig, LlamaForCausalLM, LlamaModel
