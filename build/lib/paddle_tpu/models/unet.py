"""Stable-Diffusion-style UNet (BASELINE row "Stable-Diffusion UNet
throughput via compiler/fusion path").

Reference analog: the diffusion UNet family the reference serves through
its inference/fusion stack (paddle/fluid/inference + CINN); here the whole
denoising step is one jit-compiled XLA program — conv/attention blocks are
written so XLA fuses the GroupNorm/SiLU chains into the convs and the
attention rides the same F.scaled_dot_product_attention path (Pallas on
chip) as the language models.

Architecture: timestep sinusoidal embedding -> MLP; down path of
[ResBlock(+time), optional self+cross attention] with strided-conv
downsample; middle block; mirrored up path with skip concats; GroupNorm ->
SiLU -> conv head. Cross-attention conditions on an encoder context
(text embeddings), the SD layout.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import List, Optional

from .. import nn
from ..nn import functional as F
from ..ops.creation import arange
from ..ops.manipulation import concat
from ..ops.math import exp

__all__ = ["UNetConfig", "UNetModel", "UNET_PRESETS"]


@dataclass
class UNetConfig:
    in_channels: int = 4
    out_channels: int = 4
    base_channels: int = 320
    channel_mults: tuple = (1, 2, 4, 4)
    num_res_blocks: int = 2
    attention_levels: tuple = (0, 1, 2)   # levels with self+cross attn
    num_heads: int = 8
    context_dim: int = 768
    groups: int = 32


UNET_PRESETS = {
    "sd15": UNetConfig(),
    "debug": UNetConfig(base_channels=32, channel_mults=(1, 2),
                        num_res_blocks=1, attention_levels=(1,),
                        num_heads=2, context_dim=32, groups=8),
}


def timestep_embedding(t, dim):
    """Sinusoidal embedding [B] -> [B, dim]."""
    half = dim // 2
    freqs = exp(arange(half, dtype="float32")
                * (-math.log(10000.0) / half))
    args = t.astype("float32").unsqueeze(-1) * freqs.unsqueeze(0)
    return concat([args.sin(), args.cos()], axis=-1)


class ResBlock(nn.Layer):
    def __init__(self, in_c, out_c, time_dim, groups):
        super().__init__()
        self.norm1 = nn.GroupNorm(groups, in_c)
        self.conv1 = nn.Conv2D(in_c, out_c, 3, padding=1)
        self.time_proj = nn.Linear(time_dim, out_c)
        self.norm2 = nn.GroupNorm(groups, out_c)
        self.conv2 = nn.Conv2D(out_c, out_c, 3, padding=1)
        self.skip = nn.Conv2D(in_c, out_c, 1) if in_c != out_c else None

    def forward(self, x, t_emb):
        h = self.conv1(F.silu(self.norm1(x)))
        h = h + self.time_proj(F.silu(t_emb)).unsqueeze(-1).unsqueeze(-1)
        h = self.conv2(F.silu(self.norm2(h)))
        return h + (self.skip(x) if self.skip is not None else x)


class SpatialTransformer(nn.Layer):
    """Self-attention + cross-attention + GEGLU FFN over flattened
    spatial tokens (the SD transformer block)."""

    def __init__(self, channels, num_heads, context_dim, groups):
        super().__init__()
        self.norm = nn.GroupNorm(groups, channels)
        self.proj_in = nn.Conv2D(channels, channels, 1)
        self.norm1 = nn.LayerNorm(channels)
        self.self_attn = nn.MultiHeadAttention(channels, num_heads)
        self.norm2 = nn.LayerNorm(channels)
        self.cross_q = nn.Linear(channels, channels)
        self.cross_k = nn.Linear(context_dim, channels)
        self.cross_v = nn.Linear(context_dim, channels)
        self.cross_out = nn.Linear(channels, channels)
        self.num_heads = num_heads
        self.norm3 = nn.LayerNorm(channels)
        self.ff1 = nn.Linear(channels, channels * 4)
        self.ff2 = nn.Linear(channels * 4, channels)
        self.proj_out = nn.Conv2D(channels, channels, 1)

    def _cross(self, x, context):
        b, s, c = x.shape
        hd = c // self.num_heads
        q = self.cross_q(x).reshape([b, s, self.num_heads, hd])
        k = self.cross_k(context).reshape(
            [b, context.shape[1], self.num_heads, hd])
        v = self.cross_v(context).reshape(
            [b, context.shape[1], self.num_heads, hd])
        out = F.scaled_dot_product_attention(q, k, v)
        return self.cross_out(out.reshape([b, s, c]))

    def forward(self, x, context):
        b, c, h, w = x.shape
        res = x
        x = self.proj_in(self.norm(x))
        x = x.reshape([b, c, h * w]).transpose([0, 2, 1])  # [B, HW, C]
        x = x + self.self_attn(self.norm1(x))
        x = x + self._cross(self.norm2(x), context)
        x = x + self.ff2(F.gelu(self.ff1(self.norm3(x))))
        x = x.transpose([0, 2, 1]).reshape([b, c, h, w])
        return self.proj_out(x) + res


class UNetModel(nn.Layer):
    def __init__(self, cfg: UNetConfig):
        super().__init__()
        self.config = cfg
        ch = cfg.base_channels
        time_dim = ch * 4
        self.time_mlp1 = nn.Linear(ch, time_dim)
        self.time_mlp2 = nn.Linear(time_dim, time_dim)
        self.conv_in = nn.Conv2D(cfg.in_channels, ch, 3, padding=1)

        self.down_blocks = nn.LayerList()
        self.down_attns = nn.LayerList()
        self.downsamples = nn.LayerList()
        chans = [ch]
        cur = ch
        for level, mult in enumerate(cfg.channel_mults):
            out_c = ch * mult
            blocks = nn.LayerList()
            attns = nn.LayerList()
            for _ in range(cfg.num_res_blocks):
                blocks.append(ResBlock(cur, out_c, time_dim, cfg.groups))
                attns.append(SpatialTransformer(
                    out_c, cfg.num_heads, cfg.context_dim, cfg.groups)
                    if level in cfg.attention_levels else None)
                cur = out_c
                chans.append(cur)
            self.down_blocks.append(blocks)
            self.down_attns.append(attns)
            if level != len(cfg.channel_mults) - 1:
                self.downsamples.append(
                    nn.Conv2D(cur, cur, 3, stride=2, padding=1))
                chans.append(cur)
            else:
                self.downsamples.append(None)

        self.mid_block1 = ResBlock(cur, cur, time_dim, cfg.groups)
        self.mid_attn = SpatialTransformer(cur, cfg.num_heads,
                                           cfg.context_dim, cfg.groups)
        self.mid_block2 = ResBlock(cur, cur, time_dim, cfg.groups)

        self.up_blocks = nn.LayerList()
        self.up_attns = nn.LayerList()
        self.upsamples = nn.LayerList()
        for level, mult in reversed(list(enumerate(cfg.channel_mults))):
            out_c = ch * mult
            blocks = nn.LayerList()
            attns = nn.LayerList()
            for _ in range(cfg.num_res_blocks + 1):
                blocks.append(ResBlock(cur + chans.pop(), out_c, time_dim,
                                       cfg.groups))
                attns.append(SpatialTransformer(
                    out_c, cfg.num_heads, cfg.context_dim, cfg.groups)
                    if level in cfg.attention_levels else None)
                cur = out_c
            self.up_blocks.append(blocks)
            self.up_attns.append(attns)
            self.upsamples.append(
                nn.Conv2D(cur, cur, 3, padding=1) if level != 0 else None)

        self.norm_out = nn.GroupNorm(cfg.groups, cur)
        self.conv_out = nn.Conv2D(cur, cfg.out_channels, 3, padding=1)

    def forward(self, x, timesteps, context):
        """x [B, C, H, W] latents; timesteps [B]; context [B, T, Dctx]."""
        t = timestep_embedding(timesteps, self.config.base_channels)
        t = self.time_mlp2(F.silu(self.time_mlp1(t)))

        h = self.conv_in(x)
        skips = [h]
        for blocks, attns, down in zip(self.down_blocks, self.down_attns,
                                       self.downsamples):
            for blk, attn in zip(blocks, attns):
                h = blk(h, t)
                if attn is not None:
                    h = attn(h, context)
                skips.append(h)
            if down is not None:
                h = down(h)
                skips.append(h)

        h = self.mid_block2(self.mid_attn(self.mid_block1(h, t), context),
                            t)

        for blocks, attns, up in zip(self.up_blocks, self.up_attns,
                                     self.upsamples):
            for blk, attn in zip(blocks, attns):
                h = blk(concat([h, skips.pop()], axis=1), t)
                if attn is not None:
                    h = attn(h, context)
            if up is not None:
                h = F.interpolate(h, scale_factor=2, mode="nearest")
                h = up(h)

        return self.conv_out(F.silu(self.norm_out(h)))
