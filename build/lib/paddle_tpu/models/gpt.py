"""GPT model family (reference workload: ERNIE/GPT pretraining through
PaddleNLP on Fleet; the layers come from this framework's nn/transformer
stack, attention from the Pallas flash kernel)."""
from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from .. import nn
from ..core.tensor import Tensor
from ..nn import functional as F

__all__ = ["GPTConfig", "GPTForCausalLM", "GPT_PRESETS"]


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 1024
    layer_norm_eps: float = 1e-5
    dropout: float = 0.1
    dtype: str = "bfloat16"


GPT_PRESETS = {
    "gpt2": GPTConfig(),
    "gpt2-medium": GPTConfig(hidden_size=1024, num_hidden_layers=24,
                             num_attention_heads=16, intermediate_size=4096),
    "gpt2-large": GPTConfig(hidden_size=1280, num_hidden_layers=36,
                            num_attention_heads=20, intermediate_size=5120),
    "debug": GPTConfig(vocab_size=256, hidden_size=64, num_hidden_layers=2,
                       num_attention_heads=2, intermediate_size=128,
                       max_position_embeddings=128, dropout=0.0,
                       dtype="float32"),
}


class GPTBlock(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        h = cfg.hidden_size
        self.ln_1 = nn.LayerNorm(h, epsilon=cfg.layer_norm_eps)
        self.attn = nn.MultiHeadAttention(h, cfg.num_attention_heads,
                                          dropout=cfg.dropout)
        self.ln_2 = nn.LayerNorm(h, epsilon=cfg.layer_norm_eps)
        self.mlp = nn.Sequential(
            nn.Linear(h, cfg.intermediate_size),
            nn.GELU(approximate=True),
            nn.Linear(cfg.intermediate_size, h),
        )
        self.drop = nn.Dropout(cfg.dropout)

    def forward(self, x, attn_mask=None):
        a = self.attn._forward_causal(self.ln_1(x))
        x = x + self.drop(a)
        x = x + self.drop(self.mlp(self.ln_2(x)))
        return x


# causal attention variant bound onto MultiHeadAttention
def _mha_forward_causal(self, x):
    b, s = x.shape[0], x.shape[1]
    q = self.q_proj(x).reshape([b, s, self.num_heads, self.head_dim])
    k = self.k_proj(x).reshape([b, s, self.num_heads, self.head_dim])
    v = self.v_proj(x).reshape([b, s, self.num_heads, self.head_dim])
    out = F.scaled_dot_product_attention(
        q, k, v, is_causal=True, dropout_p=self.dropout,
        training=self.training)
    return self.out_proj(out.reshape([b, s, self.embed_dim]))


nn.MultiHeadAttention._forward_causal = _mha_forward_causal


class GPTForCausalLM(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.config = cfg
        self.wte = nn.Embedding(cfg.vocab_size, cfg.hidden_size)
        self.wpe = nn.Embedding(cfg.max_position_embeddings, cfg.hidden_size)
        self.drop = nn.Dropout(cfg.dropout)
        self.blocks = nn.LayerList(
            [GPTBlock(cfg) for _ in range(cfg.num_hidden_layers)])
        self.ln_f = nn.LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)
        self.lm_head = nn.Linear(cfg.hidden_size, cfg.vocab_size,
                                 bias_attr=False)

    def forward(self, input_ids, labels=None):
        b, s = input_ids.shape[0], input_ids.shape[1]
        from ..ops.creation import arange

        pos = arange(s, dtype="int64").unsqueeze(0)
        x = self.drop(self.wte(input_ids) + self.wpe(pos))
        for block in self.blocks:
            x = block(x)
        x = self.ln_f(x)
        logits = self.lm_head(x)
        if labels is not None:
            return F.cross_entropy(
                logits.reshape([-1, self.config.vocab_size]),
                labels.reshape([-1]))
        return logits

    @classmethod
    def from_preset(cls, name):
        import copy

        return cls(copy.deepcopy(GPT_PRESETS[name]))
