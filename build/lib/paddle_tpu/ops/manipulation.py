"""Shape/layout manipulation ops (reference: python/paddle/tensor/manipulation.py).

All of these lower to XLA reshape/transpose/gather/scatter HLOs — free or
fused under XLA, so no custom kernels are needed on TPU."""
from __future__ import annotations

import builtins

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply
from ..core.tensor import Tensor
from . import registry

__all__ = [
    "reshape", "reshape_", "flatten", "squeeze", "squeeze_", "unsqueeze",
    "unsqueeze_", "transpose", "concat", "stack", "split", "tensor_split",
    "chunk", "slice", "crop", "gather", "gather_nd", "scatter", "scatter_",
    "scatter_nd", "scatter_nd_add", "index_select", "index_add", "index_put",
    "index_sample", "masked_select", "masked_fill", "tile", "expand",
    "expand_as", "broadcast_to", "broadcast_tensors", "flip", "roll", "pad",
    "unbind", "repeat_interleave", "take_along_axis", "put_along_axis",
    "strided_slice", "moveaxis", "swapaxes", "unstack", "rollaxis",
    "as_complex", "as_real", "view", "view_as", "unfold", "unflatten",
    "flatten_", "tolist", "atleast_1d", "atleast_2d", "atleast_3d",
    "select_scatter", "diagonal_scatter", "slice_scatter",
]


def _shape_arg(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.numpy())
    return tuple(
        int(s._value) if isinstance(s, Tensor) else int(s) for s in shape
    )


def reshape(x, shape, name=None):
    s = _shape_arg(shape)
    return apply(lambda a: jnp.reshape(a, s), x, op_name="reshape")


def reshape_(x, shape, name=None):
    out = reshape(x, shape)
    x._value = out._value
    x._grad_node = out._grad_node
    x._out_index = out._out_index
    x.stop_gradient = out.stop_gradient
    return x


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return x.astype(shape_or_dtype)


def view_as(x, other, name=None):
    return reshape(x, other.shape)


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    def fn(a):
        nd = a.ndim
        s = start_axis % nd if nd else 0
        e = stop_axis % nd if nd else 0
        new_shape = a.shape[:s] + (-1,) + a.shape[e + 1:]
        return jnp.reshape(a, new_shape)
    return apply(fn, x, op_name="flatten")


def flatten_(x, start_axis=0, stop_axis=-1, name=None):
    out = flatten(x, start_axis, stop_axis)
    x._value = out._value
    x._grad_node = out._grad_node
    x._out_index = out._out_index
    x.stop_gradient = out.stop_gradient
    return x


def squeeze(x, axis=None, name=None):
    def fn(a):
        if axis is None:
            return jnp.squeeze(a)
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        axes = tuple(
            ax % a.ndim for ax in axes if a.shape[ax % a.ndim] == 1
        )
        return jnp.squeeze(a, axis=axes) if axes else a
    return apply(fn, x, op_name="squeeze")


def squeeze_(x, axis=None, name=None):
    out = squeeze(x, axis)
    x._value, x._grad_node = out._value, out._grad_node
    x._out_index, x.stop_gradient = out._out_index, out.stop_gradient
    return x


def unsqueeze(x, axis, name=None):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    axes = [int(a._value) if isinstance(a, Tensor) else int(a) for a in axes]
    def fn(a):
        out = a
        for ax in sorted(ax if ax >= 0 else ax + out.ndim + 1 for ax in axes):
            out = jnp.expand_dims(out, ax)
        return out
    return apply(fn, x, op_name="unsqueeze")


def unsqueeze_(x, axis, name=None):
    out = unsqueeze(x, axis)
    x._value, x._grad_node = out._value, out._grad_node
    x._out_index, x.stop_gradient = out._out_index, out.stop_gradient
    return x


def transpose(x, perm, name=None):
    p = tuple(int(i) for i in perm)
    return apply(lambda a: jnp.transpose(a, p), x, op_name="transpose")


def moveaxis(x, source, destination, name=None):
    return apply(lambda a: jnp.moveaxis(a, source, destination), x,
                 op_name="moveaxis")


def swapaxes(x, axis0, axis1, name=None):
    return apply(lambda a: jnp.swapaxes(a, int(axis0), int(axis1)), x,
                 op_name="swapaxes")


rollaxis = moveaxis


def concat(x, axis=0, name=None):
    ax = int(axis._value) if isinstance(axis, Tensor) else int(axis)
    tensors = list(x)
    return apply(lambda *xs: jnp.concatenate(xs, axis=ax), *tensors,
                 op_name="concat")


def stack(x, axis=0, name=None):
    tensors = list(x)
    return apply(lambda *xs: jnp.stack(xs, axis=int(axis)), *tensors,
                 op_name="stack")


def split(x, num_or_sections, axis=0, name=None):
    ax = int(axis._value) if isinstance(axis, Tensor) else int(axis)
    dim = x.shape[ax]
    if isinstance(num_or_sections, int):
        sizes = [dim // num_or_sections] * num_or_sections
    else:
        sizes = [
            int(s._value) if isinstance(s, Tensor) else int(s)
            for s in num_or_sections
        ]
        n_unknown = builtins.sum(1 for s in sizes if s < 0)
        if n_unknown:
            known = builtins.sum(s for s in sizes if s >= 0)
            sizes = [s if s >= 0 else dim - known for s in sizes]
    offsets = np.cumsum([0] + sizes[:-1]).tolist()
    outs = apply(
        lambda a: tuple(
            jax.lax.slice_in_dim(a, o, o + s, axis=ax)
            for o, s in zip(offsets, sizes)
        ),
        x, op_name="split")
    return list(outs)


def tensor_split(x, num_or_indices, axis=0, name=None):
    ax = int(axis)
    dim = x.shape[ax]
    if isinstance(num_or_indices, int):
        n = num_or_indices
        base, rem = divmod(dim, n)
        sizes = [base + (1 if i < rem else 0) for i in range(n)]
        return split(x, sizes, axis=ax)
    idxs = [0] + [int(i) for i in num_or_indices] + [dim]
    sizes = [idxs[i + 1] - idxs[i] for i in range(len(idxs) - 1)]
    return split(x, sizes, axis=ax)


def chunk(x, chunks, axis=0, name=None):
    return split(x, int(chunks), axis)


def unstack(x, axis=0, num=None, name=None):
    n = num or x.shape[int(axis)]
    outs = apply(
        lambda a: tuple(
            jnp.squeeze(s, axis=int(axis))
            for s in jnp.split(a, n, axis=int(axis))
        ),
        x, op_name="unstack")
    return list(outs)


def unbind(x, axis=0, name=None):
    return unstack(x, axis)


def slice(x, axes, starts, ends, name=None):
    def conv(v):
        return int(v._value) if isinstance(v, Tensor) else int(v)
    axes = [conv(a) for a in axes]
    starts = [conv(s) for s in starts]
    ends = [conv(e) for e in ends]
    def fn(a):
        idx = [builtins.slice(None)] * a.ndim
        for ax, s, e in zip(axes, starts, ends):
            idx[ax] = builtins.slice(s, e)
        return a[tuple(idx)]
    return apply(fn, x, op_name="slice")


def strided_slice(x, axes, starts, ends, strides, name=None):
    def fn(a):
        idx = [builtins.slice(None)] * a.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            idx[int(ax)] = builtins.slice(int(s), int(e), int(st))
        return a[tuple(idx)]
    return apply(fn, x, op_name="strided_slice")


def crop(x, shape=None, offsets=None, name=None):
    shape = _shape_arg(shape)
    offsets = [0] * x.ndim if offsets is None else [
        int(o._value) if isinstance(o, Tensor) else int(o) for o in offsets
    ]
    shape = [x.shape[i] - offsets[i] if s == -1 else s
             for i, s in enumerate(shape)]
    def fn(a):
        idx = tuple(
            builtins.slice(o, o + s) for o, s in zip(offsets, shape)
        )
        return a[idx]
    return apply(fn, x, op_name="crop")


def gather(x, index, axis=0, name=None):
    ax = int(axis._value) if isinstance(axis, Tensor) else int(axis)
    return apply(
        lambda a, i: jnp.take(a, i.reshape(-1) if i.ndim > 1 else i, axis=ax),
        x, index, op_name="gather")


def gather_nd(x, index, name=None):
    def fn(a, i):
        idx_depth = i.shape[-1]
        idx = tuple(jnp.moveaxis(i, -1, 0))
        return a[idx]
    return apply(fn, x, index, op_name="gather_nd")


def scatter(x, index, updates, overwrite=True, name=None):
    def fn(a, i, u):
        i = i.reshape(-1)
        if overwrite:
            return a.at[i].set(u)
        # accumulate-mode scatter zeroes target rows first (reference semantics)
        zeroed = a.at[i].set(jnp.zeros_like(u))
        return zeroed.at[i].add(u)
    return apply(fn, x, index, updates, op_name="scatter")


def scatter_(x, index, updates, overwrite=True, name=None):
    out = scatter(x, index, updates, overwrite)
    x._value, x._grad_node = out._value, out._grad_node
    x._out_index, x.stop_gradient = out._out_index, out.stop_gradient
    return x


def scatter_nd(index, updates, shape, name=None):
    s = _shape_arg(shape)
    def fn(i, u):
        z = jnp.zeros(s, u.dtype)
        idx = tuple(jnp.moveaxis(i, -1, 0))
        return z.at[idx].add(u)
    return apply(fn, index, updates, op_name="scatter_nd")


def scatter_nd_add(x, index, updates, name=None):
    def fn(a, i, u):
        idx = tuple(jnp.moveaxis(i, -1, 0))
        return a.at[idx].add(u)
    return apply(fn, x, index, updates, op_name="scatter_nd_add")


def index_select(x, index, axis=0, name=None):
    return apply(lambda a, i: jnp.take(a, i, axis=int(axis)), x, index,
                 op_name="index_select")


def index_add(x, index, axis, value, name=None):
    def fn(a, i, v):
        ax = int(axis) % a.ndim
        a2 = jnp.moveaxis(a, ax, 0)
        v2 = jnp.moveaxis(v, ax, 0)
        out = a2.at[i].add(v2)
        return jnp.moveaxis(out, 0, ax)
    return apply(fn, x, index, value, op_name="index_add")


def index_put(x, indices, value, accumulate=False, name=None):
    def fn(a, v, *idx):
        if accumulate:
            return a.at[tuple(idx)].add(v)
        return a.at[tuple(idx)].set(v)
    return apply(fn, x, value, *indices, op_name="index_put")


def index_sample(x, index, name=None):
    return apply(
        lambda a, i: jnp.take_along_axis(a, i.astype(jnp.int32), axis=1),
        x, index, op_name="index_sample")


def masked_select(x, mask, name=None):
    # dynamic output shape: eager-only (XLA needs static shapes under jit)
    arr = np.asarray(x.numpy())[np.asarray(mask.numpy())]
    return Tensor(jnp.asarray(arr), stop_gradient=True)


def masked_fill(x, mask, value, name=None):
    v = value._value if isinstance(value, Tensor) else value
    return apply(lambda a, m: jnp.where(m, jnp.asarray(v, a.dtype), a), x,
                 mask, op_name="masked_fill")


def tile(x, repeat_times, name=None):
    reps = _shape_arg(repeat_times)
    return apply(lambda a: jnp.tile(a, reps), x, op_name="tile")


def expand(x, shape, name=None):
    s = _shape_arg(shape)
    def fn(a):
        target = list(s)
        pad = len(target) - a.ndim
        for i in range(len(target)):
            if target[i] == -1:
                target[i] = a.shape[i - pad]
        return jnp.broadcast_to(a, target)
    return apply(fn, x, op_name="expand")


def expand_as(x, y, name=None):
    return expand(x, y.shape)


def broadcast_to(x, shape, name=None):
    return expand(x, shape)


def broadcast_tensors(inputs, name=None):
    outs = apply(lambda *xs: jnp.broadcast_arrays(*xs), *inputs,
                 op_name="broadcast_tensors")
    return list(outs)


def flip(x, axis, name=None):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    return apply(lambda a: jnp.flip(a, axis=tuple(int(i) for i in axes)), x,
                 op_name="flip")


def roll(x, shifts, axis=None, name=None):
    def fn(a):
        if axis is None:
            return jnp.roll(a.reshape(-1), shifts).reshape(a.shape)
        return jnp.roll(a, shifts, axis=axis)
    return apply(fn, x, op_name="roll")


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    pad_list = [int(p._value) if isinstance(p, Tensor) else int(p)
                for p in (pad.numpy() if isinstance(pad, Tensor) else pad)]
    def fn(a):
        nd = a.ndim
        if len(pad_list) == 2 * nd:
            width = [
                (pad_list[2 * i], pad_list[2 * i + 1]) for i in range(nd)
            ]
        else:
            # reference NCHW/NCDHW convention: pad applies to trailing
            # spatial dims, innermost-last pair ordering
            n_spatial = len(pad_list) // 2
            width = [(0, 0)] * (nd - n_spatial)
            trailing = [
                (pad_list[2 * i], pad_list[2 * i + 1])
                for i in range(n_spatial)
            ][::-1]
            if data_format.endswith("C") and nd >= 3:  # NHWC-style
                width = [(0, 0)] + trailing + [(0, 0)]
                width = width[:nd]
            else:
                width += trailing
        jmode = {"constant": "constant", "reflect": "reflect",
                 "replicate": "edge", "circular": "wrap"}[mode]
        kw = {"constant_values": value} if jmode == "constant" else {}
        return jnp.pad(a, width, mode=jmode, **kw)
    return apply(fn, x, op_name="pad")


def repeat_interleave(x, repeats, axis=None, name=None):
    if isinstance(repeats, Tensor):
        repeats = repeats._value
    def fn(a):
        if axis is None:
            return jnp.repeat(a.reshape(-1), repeats)
        return jnp.repeat(a, repeats, axis=int(axis))
    return apply(fn, x, op_name="repeat_interleave")


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    def fn(a, i):
        return jnp.take_along_axis(a, i, axis=int(axis))
    return apply(fn, arr, indices, op_name="take_along_axis")


def put_along_axis(arr, indices, values, axis, reduce="assign",
                   include_self=True, broadcast=True, name=None):
    def fn(a, i, v):
        v = jnp.broadcast_to(jnp.asarray(v, a.dtype), i.shape)
        ax = int(axis) % a.ndim
        # build explicit index grid for scatter along `ax`
        grids = jnp.meshgrid(
            *[jnp.arange(s) for s in i.shape], indexing="ij"
        )
        grids[ax] = i
        idx = tuple(grids)
        if reduce == "assign":
            return a.at[idx].set(v)
        if reduce in ("add", "sum"):
            return a.at[idx].add(v)
        if reduce in ("mul", "multiply"):
            return a.at[idx].multiply(v)
        if reduce == "amax":
            return a.at[idx].max(v)
        if reduce == "amin":
            return a.at[idx].min(v)
        raise ValueError(f"unknown reduce {reduce}")
    return apply(fn, arr, indices,
                 values if isinstance(values, Tensor) else Tensor(values),
                 op_name="put_along_axis")


def as_complex(x, name=None):
    return apply(lambda a: jax.lax.complex(a[..., 0], a[..., 1]), x,
                 op_name="as_complex")


def as_real(x, name=None):
    return apply(lambda a: jnp.stack([jnp.real(a), jnp.imag(a)], axis=-1), x,
                 op_name="as_real")


def unfold(x, axis, size, step, name=None):
    def fn(a):
        ax = int(axis) % a.ndim
        n = (a.shape[ax] - size) // step + 1
        slices = [
            jax.lax.slice_in_dim(a, i * step, i * step + size, axis=ax)
            for i in range(n)
        ]
        return jnp.stack(slices, axis=ax)  # windows inserted at axis
    out = apply(fn, x, op_name="unfold")
    # reference places the window dim last
    perm = list(range(out.ndim))
    ax = int(axis) % x.ndim
    return out  # shape (..., n, size, ...) along axis — documented layout


def unflatten(x, axis, shape, name=None):
    s = _shape_arg(shape)
    def fn(a):
        ax = int(axis) % a.ndim
        new_shape = a.shape[:ax] + tuple(s) + a.shape[ax + 1:]
        return jnp.reshape(a, new_shape)
    return apply(fn, x, op_name="unflatten")


def tolist(x):
    return x.tolist()


def atleast_1d(*inputs, name=None):
    outs = [apply(jnp.atleast_1d, t, op_name="atleast_1d") for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs, name=None):
    outs = [apply(jnp.atleast_2d, t, op_name="atleast_2d") for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs, name=None):
    outs = [apply(jnp.atleast_3d, t, op_name="atleast_3d") for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def select_scatter(x, values, axis, index, name=None):
    def fn(a, v):
        idx = [builtins.slice(None)] * a.ndim
        idx[int(axis)] = int(index)
        return a.at[tuple(idx)].set(v)
    return apply(fn, x, values, op_name="select_scatter")


def slice_scatter(x, value, axes, starts, ends, strides, name=None):
    def fn(a, v):
        idx = [builtins.slice(None)] * a.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            idx[int(ax)] = builtins.slice(int(s), int(e), int(st))
        return a.at[tuple(idx)].set(v)
    return apply(fn, x, value, op_name="slice_scatter")


def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None):
    def fn(a, v):
        n = builtins.min(a.shape[axis1], a.shape[axis2])
        i = jnp.arange(v.shape[-1])
        r = i + builtins.max(-offset, 0)
        c = i + builtins.max(offset, 0)
        a2 = jnp.moveaxis(a, (axis1, axis2), (0, 1))
        out = a2.at[r, c].set(jnp.moveaxis(v, -1, 0))
        return jnp.moveaxis(out, (0, 1), (axis1, axis2))
    return apply(fn, x, y, op_name="diagonal_scatter")


for _n in __all__:
    registry.register(_n, globals()[_n], tags=("manipulation",))
