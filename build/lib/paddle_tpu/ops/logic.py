"""Comparison & logical ops (reference: python/paddle/tensor/logic.py).
All non-differentiable (bool outputs)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.tensor import Tensor
from . import registry

__all__ = [
    "equal", "not_equal", "greater_than", "greater_equal", "less_than",
    "less_equal", "equal_all", "logical_and", "logical_or", "logical_not",
    "logical_xor", "bitwise_and", "bitwise_or", "bitwise_not", "bitwise_xor",
    "bitwise_left_shift", "bitwise_right_shift", "isclose", "allclose",
    "is_empty", "is_tensor", "where", "where_",
]


def _cmp(op_name, fn):
    def op(x, y, name=None):
        if not isinstance(x, Tensor):
            x = Tensor(x)
        return apply(fn, x, y, op_name=op_name, differentiable=False)
    op.__name__ = op_name
    return op


equal = _cmp("equal", jnp.equal)
not_equal = _cmp("not_equal", jnp.not_equal)
greater_than = _cmp("greater_than", jnp.greater)
greater_equal = _cmp("greater_equal", jnp.greater_equal)
less_than = _cmp("less_than", jnp.less)
less_equal = _cmp("less_equal", jnp.less_equal)
logical_and = _cmp("logical_and", jnp.logical_and)
logical_or = _cmp("logical_or", jnp.logical_or)
logical_xor = _cmp("logical_xor", jnp.logical_xor)
bitwise_and = _cmp("bitwise_and", jnp.bitwise_and)
bitwise_or = _cmp("bitwise_or", jnp.bitwise_or)
bitwise_xor = _cmp("bitwise_xor", jnp.bitwise_xor)
bitwise_left_shift = _cmp("bitwise_left_shift", jnp.left_shift)
bitwise_right_shift = _cmp("bitwise_right_shift", jnp.right_shift)


def logical_not(x, name=None):
    return apply(jnp.logical_not, x, op_name="logical_not",
                 differentiable=False)


def bitwise_not(x, name=None):
    return apply(jnp.bitwise_not, x, op_name="bitwise_not",
                 differentiable=False)


def equal_all(x, y, name=None):
    return apply(lambda a, b: jnp.array_equal(a, b), x, y,
                 op_name="equal_all", differentiable=False)


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply(
        lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol,
                                 equal_nan=equal_nan),
        x, y, op_name="isclose", differentiable=False)


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply(
        lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol,
                                  equal_nan=equal_nan),
        x, y, op_name="allclose", differentiable=False)


def is_empty(x, name=None):
    return Tensor(jnp.asarray(x.size == 0))


def is_tensor(x):
    return isinstance(x, Tensor)


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        from .search import nonzero

        return tuple(nonzero(condition, as_tuple=True))
    return apply(
        lambda c, a, b: jnp.where(c, a, b), condition,
        x if isinstance(x, Tensor) else Tensor(x),
        y if isinstance(y, Tensor) else Tensor(y),
        op_name="where")


def where_(condition, x, y, name=None):
    out = where(condition, x, y)
    x._value, x._grad_node = out._value, out._grad_node
    x._out_index, x.stop_gradient = out._out_index, out.stop_gradient
    return x


for _n in __all__:
    registry.register(_n, globals()[_n], tags=("logic",))
