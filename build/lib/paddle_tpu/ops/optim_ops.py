"""Functional optimizer-update kernels (the ops.yaml optimizer surface).

Reference analog: the optimizer ops in /root/reference/paddle/phi/ops/yaml/
ops.yaml (sgd_, momentum_, adam_, adamw_, lamb_, ... — kernels under
paddle/phi/kernels/*adam*). There each is an in-place CUDA kernel; here each
is a pure jax function state -> new state (XLA donates the buffers when
called under jit, recovering the in-place behavior), registered under the
reference op name. The high-level `paddle_tpu.optimizer` classes express the
same math at the Tensor layer; these kernels are the raw per-op surface used
by the fleet/auto-tuner paths and the OpTest suite.

All take arrays, return tuples of arrays ordered as the yaml `output` lists.
`master_param` is the fp32 shadow for multi-precision training: when passed,
the update runs on it and `param` is produced by casting back.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register

__all__ = []


def _split_master(param, master_param):
    """Return (compute_param, had_master)."""
    if master_param is not None:
        return master_param, True
    return param, False


def _join_master(new_w, param_dtype, had_master):
    if had_master:
        return new_w.astype(param_dtype), new_w
    return new_w, None


def sgd_(param, learning_rate, grad, master_param=None,
         multi_precision=False):
    w, has_m = _split_master(param, master_param)
    new_w = w - learning_rate.astype(w.dtype) * grad.astype(w.dtype)
    p, m = _join_master(new_w, param.dtype, has_m)
    return p, m


def momentum_(param, grad, velocity, learning_rate, master_param=None,
              mu=0.9, use_nesterov=False, regularization_method="",
              regularization_coeff=0.0, multi_precision=False,
              rescale_grad=1.0):
    w, has_m = _split_master(param, master_param)
    g = grad.astype(w.dtype) * rescale_grad
    if regularization_method == "l2_decay":
        g = g + regularization_coeff * w
    v = mu * velocity + g
    lr = learning_rate.astype(w.dtype)
    if use_nesterov:
        new_w = w - (g + mu * v) * lr
    else:
        new_w = w - lr * v
    p, m = _join_master(new_w, param.dtype, has_m)
    return p, v, m


def adam_(param, grad, learning_rate, moment1, moment2, beta1_pow,
          beta2_pow, master_param=None, skip_update=None, beta1=0.9,
          beta2=0.999, epsilon=1e-8, lazy_mode=False,
          min_row_size_to_use_multithread=1000, multi_precision=False,
          use_global_beta_pow=False):
    w, has_m = _split_master(param, master_param)
    g = grad.astype(w.dtype)
    m1 = beta1 * moment1 + (1 - beta1) * g
    m2 = beta2 * moment2 + (1 - beta2) * g * g
    # input pows are beta^t at step t (reference AdamKernel uses them as-is
    # and emits pow*beta for the next step)
    lr = learning_rate.astype(w.dtype) * jnp.sqrt(1 - beta2_pow) \
        / (1 - beta1_pow)
    new_w = w - lr * m1 / (jnp.sqrt(m2) + epsilon)
    b1p = beta1_pow * beta1
    b2p = beta2_pow * beta2
    if skip_update is not None:
        skip = jnp.asarray(skip_update).astype(bool).reshape(())
        new_w = jnp.where(skip, w, new_w)
        m1 = jnp.where(skip, moment1, m1)
        m2 = jnp.where(skip, moment2, m2)
        b1p = jnp.where(skip, beta1_pow, b1p)
        b2p = jnp.where(skip, beta2_pow, b2p)
    p, m = _join_master(new_w, param.dtype, has_m)
    return p, m1, m2, b1p, b2p, m


def adamw_(param, grad, learning_rate, moment1, moment2, beta1_pow,
           beta2_pow, master_param=None, skip_update=None, beta1=0.9,
           beta2=0.999, epsilon=1e-8, lr_ratio=1.0, coeff=0.01,
           with_decay=True, lazy_mode=False,
           min_row_size_to_use_multithread=1000, multi_precision=False,
           use_global_beta_pow=False):
    w, has_m = _split_master(param, master_param)
    lr = learning_rate.astype(w.dtype) * lr_ratio
    if with_decay:
        w = w * (1 - lr * coeff)       # decoupled decay before the step
    g = grad.astype(w.dtype)
    m1 = beta1 * moment1 + (1 - beta1) * g
    m2 = beta2 * moment2 + (1 - beta2) * g * g
    step_lr = lr * jnp.sqrt(1 - beta2_pow) / (1 - beta1_pow)
    new_w = w - step_lr * m1 / (jnp.sqrt(m2) + epsilon)
    b1p = beta1_pow * beta1
    b2p = beta2_pow * beta2
    p, m = _join_master(new_w, param.dtype, has_m)
    return p, m1, m2, b1p, b2p, m


def adagrad_(param, grad, moment, learning_rate, master_param=None,
             epsilon=1e-6, multi_precision=False):
    w, has_m = _split_master(param, master_param)
    g = grad.astype(w.dtype)
    mom = moment + g * g
    new_w = w - learning_rate.astype(w.dtype) * g / (jnp.sqrt(mom) + epsilon)
    p, m = _join_master(new_w, param.dtype, has_m)
    return p, mom, m


def decayed_adagrad(param, grad, moment, learning_rate, decay=0.95,
                    epsilon=1e-6):
    mom = decay * moment + (1 - decay) * grad * grad
    new_w = param - learning_rate.astype(param.dtype) * grad \
        / (jnp.sqrt(mom) + epsilon)
    return new_w, mom


def adadelta_(param, grad, avg_squared_grad, avg_squared_update,
              learning_rate, master_param=None, rho=0.95, epsilon=1e-6,
              multi_precision=False):
    w, has_m = _split_master(param, master_param)
    g = grad.astype(w.dtype)
    asg = rho * avg_squared_grad + (1 - rho) * g * g
    update = -jnp.sqrt((avg_squared_update + epsilon) / (asg + epsilon)) * g
    asu = rho * avg_squared_update + (1 - rho) * update * update
    new_w = w + learning_rate.astype(w.dtype) * update
    p, m = _join_master(new_w, param.dtype, has_m)
    return p, asg, asu, m


def adamax_(param, grad, learning_rate, moment, inf_norm, beta1_pow,
            master_param=None, beta1=0.9, beta2=0.999, epsilon=1e-8,
            multi_precision=False):
    w, has_m = _split_master(param, master_param)
    g = grad.astype(w.dtype)
    mom = beta1 * moment + (1 - beta1) * g
    inf = jnp.maximum(beta2 * inf_norm, jnp.abs(g))
    lr = learning_rate.astype(w.dtype) / (1 - beta1_pow)
    new_w = w - lr * mom / (inf + epsilon)
    p, m = _join_master(new_w, param.dtype, has_m)
    return p, mom, inf, m


def asgd_(param, grad, learning_rate, d, y, n, master_param=None,
          multi_precision=False):
    """Averaged SGD (reference phi AsgdKernel): d += g - y_old; y = g;
    param -= lr/n * d."""
    w, has_m = _split_master(param, master_param)
    g = grad.astype(w.dtype)
    d_new = d + g - y
    new_w = w - learning_rate.astype(w.dtype) * d_new / n
    p, m = _join_master(new_w, param.dtype, has_m)
    return p, d_new, g, m


def rmsprop_(param, mean_square, grad, moment, learning_rate,
             mean_grad=None, master_param=None, epsilon=1e-10, decay=0.9,
             momentum=0.0, centered=False, multi_precision=False):
    w, has_m = _split_master(param, master_param)
    g = grad.astype(w.dtype)
    ms = decay * mean_square + (1 - decay) * g * g
    lr = learning_rate.astype(w.dtype)
    if centered:
        if mean_grad is None:
            raise ValueError(
                "rmsprop_ with centered=True requires a mean_grad "
                "accumulator (reference: rmsprop op MeanGrad input)"
            )
        mg = decay * mean_grad + (1 - decay) * g
        denom = jnp.sqrt(ms - mg * mg + epsilon)
    else:
        mg = mean_grad
        denom = jnp.sqrt(ms + epsilon)
    mom = momentum * moment + lr * g / denom
    new_w = w - mom
    p, m = _join_master(new_w, param.dtype, has_m)
    return p, mom, ms, mg, m


def rprop_(param, grad, prev, learning_rate, master_param=None,
           learning_rate_range=(1e-6, 50.0), etas=(0.5, 1.2),
           multi_precision=False):
    """Resilient backprop (reference RpropKernel): per-element lr grows by
    eta_plus when the gradient keeps sign, shrinks by eta_minus on a sign
    flip (and the step is skipped)."""
    w, has_m = _split_master(param, master_param)
    g = grad.astype(w.dtype)
    lr_min, lr_max = learning_rate_range
    eta_neg, eta_pos = etas
    sign = jnp.sign(g * prev)
    factor = jnp.where(sign > 0, eta_pos, jnp.where(sign < 0, eta_neg, 1.0))
    lr = jnp.clip(learning_rate * factor, lr_min, lr_max)
    g_eff = jnp.where(sign < 0, jnp.zeros_like(g), g)
    new_w = w - lr.astype(w.dtype) * jnp.sign(g_eff)
    p, m = _join_master(new_w, param.dtype, has_m)
    return p, g_eff, lr, m


def lamb_(param, grad, learning_rate, moment1, moment2, beta1_pow,
          beta2_pow, master_param=None, skip_update=None, weight_decay=0.01,
          beta1=0.9, beta2=0.999, epsilon=1e-6, always_adapt=False,
          multi_precision=False):
    w, has_m = _split_master(param, master_param)
    g = grad.astype(w.dtype)
    m1 = beta1 * moment1 + (1 - beta1) * g
    m2 = beta2 * moment2 + (1 - beta2) * g * g
    m1_hat = m1 / (1 - beta1_pow)
    m2_hat = m2 / (1 - beta2_pow)
    b1p = beta1_pow * beta1
    b2p = beta2_pow * beta2
    r = m1_hat / (jnp.sqrt(m2_hat) + epsilon) + weight_decay * w
    w_norm = jnp.linalg.norm(w.astype(jnp.float32))
    r_norm = jnp.linalg.norm(r.astype(jnp.float32))
    trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
    new_w = w - learning_rate.astype(w.dtype) * trust.astype(w.dtype) * r
    p, m = _join_master(new_w, param.dtype, has_m)
    return p, m1, m2, b1p, b2p, m


def nadam_(param, grad, learning_rate, momentum_decay_pow, beta2_pow,
           mu_product, moment1, moment2, master_param=None, beta1=0.9,
           beta2=0.999, epsilon=1e-8, momentum_decay=0.004,
           multi_precision=False):
    w, has_m = _split_master(param, master_param)
    g = grad.astype(w.dtype)
    mdp = momentum_decay_pow * 0.96
    b2p = beta2_pow * beta2
    mu_t = beta1 * (1 - 0.5 * mdp)
    mu_t1 = beta1 * (1 - 0.5 * mdp * 0.96)
    mup = mu_product * mu_t
    m1 = beta1 * moment1 + (1 - beta1) * g
    m2 = beta2 * moment2 + (1 - beta2) * g * g
    m1_hat = mu_t1 * m1 / (1 - mup * mu_t1) + (1 - mu_t) * g / (1 - mup)
    m2_hat = m2 / (1 - b2p)
    new_w = w - learning_rate.astype(w.dtype) * m1_hat \
        / (jnp.sqrt(m2_hat) + epsilon)
    p, m = _join_master(new_w, param.dtype, has_m)
    return p, mdp, b2p, mup, m1, m2, m


def radam_(param, grad, learning_rate, beta1_pow, beta2_pow, rho,
           moment1, moment2, master_param=None, beta1=0.9, beta2=0.999,
           epsilon=1e-8, multi_precision=False):
    w, has_m = _split_master(param, master_param)
    g = grad.astype(w.dtype)
    rho_inf = 2.0 / (1 - beta2) - 1
    step = jnp.log(beta2_pow) / jnp.log(beta2)   # recovered step count
    rho_t = rho_inf - 2.0 * step * beta2_pow / (1 - beta2_pow)
    m1 = beta1 * moment1 + (1 - beta1) * g
    m2 = beta2 * moment2 + (1 - beta2) * g * g
    m1_hat = m1 / (1 - beta1_pow)
    b1p = beta1_pow * beta1
    b2p = beta2_pow * beta2
    r_num = (rho_t - 4) * (rho_t - 2) * rho_inf
    r_den = (rho_inf - 4) * (rho_inf - 2) * rho_t
    r = jnp.sqrt(jnp.maximum(r_num / r_den, 0.0))
    adaptive = r * m1_hat / (jnp.sqrt(m2 / (1 - beta2_pow)) + epsilon)
    sgd_step = m1_hat
    new_w = w - learning_rate.astype(w.dtype) \
        * jnp.where(rho_t > 5.0, adaptive, sgd_step)
    p, m = _join_master(new_w, param.dtype, has_m)
    return p, b1p, b2p, rho_t, m1, m2, m


def average_accumulates_(param, in_sum_1, in_sum_2, in_sum_3,
                         in_num_accumulates, in_old_num_accumulates,
                         in_num_updates, average_window=0.0,
                         max_average_window=2 ** 62,
                         min_average_window=10000):
    """Sliding-window parameter averaging (reference
    AverageAccumulatesKernel) — accumulators roll over when the window
    limit is hit."""
    num_updates = in_num_updates + 1
    num_acc = in_num_accumulates + 1
    window = jnp.maximum(
        jnp.asarray(average_window) * num_updates.astype(jnp.float32),
        float(min_average_window)).astype(num_acc.dtype)
    window = jnp.minimum(window, max_average_window)
    roll = num_acc >= window
    sum1 = in_sum_1 + param
    sum2 = jnp.where(roll, in_sum_2 + sum1, in_sum_2)
    sum1 = jnp.where(roll, jnp.zeros_like(sum1), sum1)
    sum3 = jnp.where(num_acc + in_old_num_accumulates >= max_average_window,
                     sum2, in_sum_3)
    old_num = jnp.where(roll, num_acc, in_old_num_accumulates)
    num_acc = jnp.where(roll, jnp.zeros_like(num_acc), num_acc)
    return sum1, sum2, sum3, num_acc, old_num, num_updates


def merged_adam_(param, grad, learning_rate, moment1, moment2, beta1_pow,
                 beta2_pow, master_param=None, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, multi_precision=False,
                 use_global_beta_pow=False):
    """List-of-tensors adam (reference merged_adam — one fused launch; on
    XLA the jit boundary fuses the per-param updates equivalently)."""
    n = len(param)
    mp = master_param if master_param is not None else [None] * n
    outs = [adam_(param[i], grad[i], learning_rate[i], moment1[i],
                  moment2[i], beta1_pow[i], beta2_pow[i], mp[i],
                  None, beta1, beta2, epsilon) for i in range(n)]
    return tuple(list(col) for col in zip(*outs))


def merged_momentum_(param, grad, velocity, learning_rate,
                     master_param=None, mu=0.9, use_nesterov=False,
                     regularization_method=(), regularization_coeff=(),
                     multi_precision=False, rescale_grad=1.0):
    n = len(param)
    mp = master_param if master_param is not None else [None] * n
    rm = list(regularization_method) + [""] * n
    rc = list(regularization_coeff) + [0.0] * n
    outs = [momentum_(param[i], grad[i], velocity[i], learning_rate[i],
                      mp[i], mu, use_nesterov, rm[i], rc[i],
                      multi_precision, rescale_grad) for i in range(n)]
    return tuple(list(col) for col in zip(*outs))


# -- AMP loss-scaling ops ---------------------------------------------------

def check_finite_and_unscale_(x, scale):
    """reference: check_finite_and_unscale op (amp) — divide every tensor
    by scale; found_infinite is true if any value is non-finite."""
    inv = 1.0 / scale
    outs = [t * inv.astype(t.dtype) for t in x]
    found = jnp.any(jnp.stack(
        [jnp.any(~jnp.isfinite(t.astype(jnp.float32))) for t in x])) \
        if x else jnp.asarray(False)
    return outs, found


def update_loss_scaling_(x, found_infinite, prev_loss_scaling,
                         in_good_steps, in_bad_steps, incr_every_n_steps,
                         decr_every_n_nan_or_inf, incr_ratio, decr_ratio,
                         stop_update=False):
    """reference: update_loss_scaling op — dynamic loss-scale schedule."""
    found = jnp.asarray(found_infinite).reshape(())
    good = jnp.where(found, jnp.zeros_like(in_good_steps), in_good_steps + 1)
    bad = jnp.where(found, in_bad_steps + 1, jnp.zeros_like(in_bad_steps))
    grow = good >= incr_every_n_steps
    shrink = bad >= decr_every_n_nan_or_inf
    scale = jnp.where(
        shrink, jnp.maximum(prev_loss_scaling * decr_ratio, 1.0),
        jnp.where(grow, prev_loss_scaling * incr_ratio, prev_loss_scaling))
    good = jnp.where(grow | shrink, jnp.zeros_like(good), good)
    bad = jnp.where(shrink, jnp.zeros_like(bad), bad)
    if stop_update:
        scale, good, bad = prev_loss_scaling, in_good_steps, in_bad_steps
    outs = [jnp.where(found, jnp.zeros_like(t), t) for t in x]
    return outs, scale, good, bad


_OPTIM_OPS = [
    sgd_, momentum_, adam_, adamw_, adagrad_, decayed_adagrad, adadelta_,
    adamax_, asgd_, rmsprop_, rprop_, lamb_, nadam_, radam_,
    average_accumulates_, merged_adam_, merged_momentum_,
    check_finite_and_unscale_, update_loss_scaling_,
]

for _fn in _OPTIM_OPS:
    register(_fn.__name__, _fn, differentiable=False, tags=("optimizer",))
    __all__.append(_fn.__name__)
