"""nn op surface under the reference ops.yaml names.

Reference analog: the nn entries of /root/reference/paddle/phi/ops/yaml/
ops.yaml (relu, conv2d, layer_norm, bilinear_interp, ...). Each entry here
registers a pure-array kernel: where `paddle_tpu.nn.functional` already
implements the math, the kernel is that same code path (functional accepts
raw arrays; outputs are unwrapped), so there is exactly one implementation
per op; genuinely missing ops (spectral_norm, hsigmoid_loss,
margin_cross_entropy, huber_loss, pooling-with-index, fractional pooling,
unpool, pad3d, ...) are implemented here directly.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from .registry import register

__all__ = []


def _uw(out):
    """Unwrap Tensors (functional wraps outputs) back to raw arrays."""
    return jax.tree_util.tree_map(
        lambda t: t._value if isinstance(t, Tensor) else t, out,
        is_leaf=lambda t: isinstance(t, Tensor))


def _F():
    from ..nn import functional as F
    return F


def _adapter(fname, **fixed):
    """Kernel = the nn.functional implementation itself, on raw arrays."""
    def kernel(*args, **kw):
        f = getattr(_F(), fname)
        return _uw(f(*args, **{**fixed, **kw}))
    kernel.__name__ = fname
    return kernel


def _reg(name, fn, differentiable=True, tags=("nn",)):
    register(name, fn, differentiable=differentiable, tags=tags)
    __all__.append(name)


# ---------------------------------------------------------------------------
# activations — the functional implementation is the kernel
# ---------------------------------------------------------------------------
for _n, _fname in [
    ("relu", "relu"), ("relu6", "relu6"), ("silu", "silu"),
    ("swish", "swish"), ("gelu", "gelu"), ("elu", "elu"), ("celu", "celu"),
    ("selu", "selu"), ("leaky_relu", "leaky_relu"),
    ("hardshrink", "hardshrink"), ("hardsigmoid", "hardsigmoid"),
    ("hardtanh", "hardtanh"), ("logsigmoid", "log_sigmoid"),
    ("mish", "mish"), ("softplus", "softplus"),
    ("softshrink", "softshrink"), ("softsign", "softsign"),
    ("tanh_shrink", "tanhshrink"), ("thresholded_relu", "thresholded_relu"),
    ("prelu", "prelu"), ("maxout", "maxout"),
    ("log_softmax", "log_softmax"), ("gumbel_softmax", "gumbel_softmax"),
    ("label_smooth", "label_smooth"),
]:
    _reg(_n, _adapter(_fname))

_reg("rrelu", _adapter("rrelu", training=False))


def dropout(x, p=0.5, training=True, mode="upscale_in_train", seed=0):
    """Pure dropout (reference dropout op, fixed_seed path): the eager
    functional.dropout draws from the framework RNG; this kernel takes the
    seed explicitly so it is a pure function."""
    if not training or p == 0.0:
        return x, jnp.ones_like(x, dtype=jnp.uint8)
    key = jax.random.PRNGKey(seed)
    keep = jax.random.bernoulli(key, 1.0 - p, x.shape)
    scale = 1.0 / (1.0 - p) if mode == "upscale_in_train" else 1.0
    out = jnp.where(keep, x * scale, 0.0).astype(x.dtype)
    return out, keep.astype(jnp.uint8)


_reg("dropout", dropout)


# ---------------------------------------------------------------------------
# conv / pool
# ---------------------------------------------------------------------------
_reg("conv2d", _adapter("conv2d"))
_reg("conv3d", _adapter("conv3d"))
_reg("conv2d_transpose", _adapter("conv2d_transpose"))
_reg("conv3d_transpose", _adapter("conv3d_transpose"))
_reg("conv2d_transpose_bias", _adapter("conv2d_transpose"))


def depthwise_conv2d(x, weight, stride=1, padding=0, dilation=1,
                     data_format="NCHW"):
    groups = x.shape[-1] if data_format.endswith("C") and \
        len(data_format) > 2 else x.shape[1]
    return _uw(_F().conv2d(x, weight, None, stride, padding, dilation,
                           int(groups), data_format))


def depthwise_conv2d_transpose(x, weight, stride=1, padding=0, dilation=1,
                               data_format="NCHW"):
    groups = x.shape[-1] if data_format.endswith("C") and \
        len(data_format) > 2 else x.shape[1]
    return _uw(_F().conv2d_transpose(x, weight, None, stride, padding,
                                     output_padding=0, groups=int(groups),
                                     dilation=dilation,
                                     data_format=data_format))


_reg("depthwise_conv2d", depthwise_conv2d)
_reg("depthwise_conv2d_transpose", depthwise_conv2d_transpose)


def pool2d(x, kernel_size, strides=None, paddings=0, ceil_mode=False,
           exclusive=True, data_format="NCHW", pooling_type="max",
           global_pooling=False, adaptive=False, padding_algorithm="EXPLICIT"):
    F = _F()
    if global_pooling:
        kernel_size = x.shape[2:4] if data_format == "NCHW" else x.shape[1:3]
        paddings = 0
    if adaptive:
        f = F.adaptive_max_pool2d if pooling_type == "max" \
            else F.adaptive_avg_pool2d
        return _uw(f(x, kernel_size))
    if pooling_type == "max":
        return _uw(F.max_pool2d(x, kernel_size, strides, paddings,
                                ceil_mode, False, data_format))
    return _uw(F.avg_pool2d(x, kernel_size, strides, paddings, ceil_mode,
                            not exclusive, None, data_format))


def pool3d(x, kernel_size, strides=None, paddings=0, ceil_mode=False,
           exclusive=True, data_format="NCDHW", pooling_type="max",
           global_pooling=False, adaptive=False,
           padding_algorithm="EXPLICIT"):
    F = _F()
    if global_pooling:
        kernel_size = x.shape[2:5] if data_format == "NCDHW" \
            else x.shape[1:4]
        paddings = 0
    if adaptive:
        f = F.adaptive_max_pool3d if pooling_type == "max" \
            else F.adaptive_avg_pool3d
        return _uw(f(x, kernel_size))
    if pooling_type == "max":
        return _uw(F.max_pool3d(x, kernel_size, strides, paddings,
                                ceil_mode, False, data_format))
    return _uw(F.avg_pool3d(x, kernel_size, strides, paddings, ceil_mode,
                            not exclusive, None, data_format))


_reg("pool2d", pool2d)
_reg("pool3d", pool3d)


def _tup(v, n):
    if isinstance(v, (int, np.integer)):
        return (int(v),) * n
    return tuple(int(i) for i in np.asarray(v).reshape(-1))[:n]


def _neg_fill(dtype):
    d = np.dtype(dtype)
    if np.issubdtype(d, np.floating):
        return float(np.finfo(np.float32).min) if d == jnp.bfloat16 \
            else float(np.finfo(d).min)
    return int(np.iinfo(d).min)


def _max_pool_with_index(x, kernel_size, stride, padding, n,
                         ceil_mode=False):
    """Windowed argmax via patch extraction: conv_general_dilated_patches
    lays every window out along a channel axis; argmax over it gives the
    in-window offset, converted to a flat spatial index (reference
    max_pool2d_with_index op).

    Padding is applied explicitly with the dtype's lowest value so pad
    positions can never win the max (lax patch extraction pads with 0,
    which is wrong for all-negative windows); ceil_mode extends the right
    pad so partial windows are kept."""
    ks, st = _tup(kernel_size, n), _tup(stride or kernel_size, n)
    pd = _tup(padding, n)
    B, C = x.shape[0], x.shape[1]
    spatial = x.shape[2:2 + n]
    pads = [[0, 0], [0, 0]]
    for d in range(n):
        hi = pd[d]
        if ceil_mode:
            span = spatial[d] + 2 * pd[d] - ks[d]
            out_d = -(-span // st[d]) + 1
            hi = max(hi, (out_d - 1) * st[d] + ks[d] - spatial[d] - pd[d])
        pads.append([pd[d], hi])
    xp = jnp.pad(x, pads, constant_values=_neg_fill(x.dtype))
    psp = xp.shape[2:]
    out_sp = tuple((psp[d] - ks[d]) // st[d] + 1 for d in range(n))
    # one strided slice per in-window offset (row-major over the kernel),
    # stacked on a K axis: [B, C, K, *out_sp]. Avoids the conv-patches
    # route, whose accumulation overflows on the -inf-like fill values.
    import itertools

    K = int(np.prod(ks))
    slabs = []
    for off in itertools.product(*[range(k) for k in ks]):
        idx = (slice(None), slice(None)) + tuple(
            slice(off[d], off[d] + (out_sp[d] - 1) * st[d] + 1, st[d])
            for d in range(n))
        slabs.append(xp[idx])
    patches = jnp.stack(slabs, axis=2)
    vals = jnp.max(patches, axis=2)
    arg = jnp.argmax(patches, axis=2)           # offset within the window
    # flat index into the (unpadded) input spatial grid
    idx = jnp.zeros_like(arg)
    rem = arg
    grid = jnp.meshgrid(*[jnp.arange(s) for s in out_sp], indexing="ij")
    for d in range(n):
        inner = int(np.prod(ks[d + 1:]))
        off_d = rem // inner
        rem = rem % inner
        pos_d = grid[d].reshape((1, 1) + out_sp) * st[d] - pd[d] + off_d
        pos_d = jnp.clip(pos_d, 0, spatial[d] - 1)
        idx = idx * spatial[d] + pos_d
    return vals, idx.astype(jnp.int32)


def _adaptive_max_pool_with_index(x, output_size, n):
    """Adaptive windowed argmax: cell d spans [floor(i*S/O), ceil((i+1)*S/O))
    — same binning as the reference's adaptive pooling. Output sizes are
    static and small, so a per-cell slice loop unrolls fine under jit."""
    import itertools

    spatial = x.shape[2:2 + n]
    outs = _tup(output_size, n)
    cells_v, cells_i = {}, {}
    for cell in itertools.product(*[range(o) for o in outs]):
        lo = [(cell[d] * spatial[d]) // outs[d] for d in range(n)]
        hi = [-(-((cell[d] + 1) * spatial[d]) // outs[d]) for d in range(n)]
        region = x
        for d in range(n):
            region = jax.lax.slice_in_dim(region, lo[d], hi[d], axis=2 + d)
        rs = region.shape[2:]
        flat = region.reshape(region.shape[:2] + (-1,))
        a = jnp.argmax(flat, axis=-1)
        v = jnp.max(flat, axis=-1)
        pos, rem = None, a
        for d in range(n):
            inner = int(np.prod(rs[d + 1:]))
            p_d = rem // inner + lo[d]
            rem = rem % inner
            pos = p_d if pos is None else pos * spatial[d] + p_d
        cells_v[cell], cells_i[cell] = v, pos
    shape = x.shape[:2] + outs
    vals = jnp.stack([cells_v[c] for c in sorted(cells_v)], axis=-1)
    idx = jnp.stack([cells_i[c] for c in sorted(cells_i)], axis=-1)
    return vals.reshape(shape), idx.reshape(shape).astype(jnp.int32)


def max_pool2d_with_index(x, kernel_size, strides=None, paddings=0,
                          global_pooling=False, adaptive=False,
                          ceil_mode=False):
    if adaptive:
        return _adaptive_max_pool_with_index(x, kernel_size, 2)
    if global_pooling:
        kernel_size, strides, paddings = x.shape[2:4], None, 0
    return _max_pool_with_index(x, kernel_size, strides, paddings, 2,
                                ceil_mode)


def max_pool3d_with_index(x, kernel_size, strides=None, paddings=0,
                          global_pooling=False, adaptive=False,
                          ceil_mode=False):
    if adaptive:
        return _adaptive_max_pool_with_index(x, kernel_size, 3)
    if global_pooling:
        kernel_size, strides, paddings = x.shape[2:5], None, 0
    return _max_pool_with_index(x, kernel_size, strides, paddings, 3,
                                ceil_mode)


_reg("max_pool2d_with_index", max_pool2d_with_index)
_reg("max_pool3d_with_index", max_pool3d_with_index)


def unpool(x, indices, kernel_size, stride=None, padding=0,
           output_size=None, data_format="NCHW"):
    """Inverse of max_pool2d_with_index: scatter pooled values back to
    their argmax positions (reference unpool op)."""
    B, C, H, W = x.shape
    if output_size is None:
        ks, st = _tup(kernel_size, 2), _tup(stride or kernel_size, 2)
        pd = _tup(padding, 2)
        output_size = ((H - 1) * st[0] - 2 * pd[0] + ks[0],
                       (W - 1) * st[1] - 2 * pd[1] + ks[1])
    oh, ow = int(output_size[-2]), int(output_size[-1])
    flat = jnp.zeros((B, C, oh * ow), x.dtype)
    out = jax.vmap(jax.vmap(
        lambda f, v, i: f.at[i.reshape(-1)].add(v.reshape(-1))))(
            flat, x, indices)
    return out.reshape(B, C, oh, ow)


def unpool3d(x, indices, kernel_size, stride=None, padding=0,
             output_size=None, data_format="NCDHW"):
    B, C, D, H, W = x.shape
    if output_size is None:
        ks, st = _tup(kernel_size, 3), _tup(stride or kernel_size, 3)
        pd = _tup(padding, 3)
        output_size = ((D - 1) * st[0] - 2 * pd[0] + ks[0],
                       (H - 1) * st[1] - 2 * pd[1] + ks[1],
                       (W - 1) * st[2] - 2 * pd[2] + ks[2])
    od, oh, ow = (int(s) for s in output_size[-3:])
    flat = jnp.zeros((B, C, od * oh * ow), x.dtype)
    out = jax.vmap(jax.vmap(
        lambda f, v, i: f.at[i.reshape(-1)].add(v.reshape(-1))))(
            flat, x, indices)
    return out.reshape(B, C, od, oh, ow)


_reg("unpool", unpool)
_reg("unpool3d", unpool3d)


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCHW"):
    """(sum |x|^p)^(1/p) over windows (reference lp_pool2d)."""
    p = float(norm_type)
    ks, st = _tup(kernel_size, 2), _tup(stride or kernel_size, 2)
    pd = [(i, i) for i in _tup(padding, 2)]
    powed = jnp.abs(x.astype(jnp.float32)) ** p
    if data_format == "NHWC":
        window = (1,) + ks + (1,)
        strides = (1,) + st + (1,)
        pads = [(0, 0)] + pd + [(0, 0)]
    else:
        window = (1, 1) + ks
        strides = (1, 1) + st
        pads = [(0, 0), (0, 0)] + pd
    s = jax.lax.reduce_window(powed, 0.0, jax.lax.add, window, strides,
                              pads)
    return (s ** (1.0 / p)).astype(x.dtype)


_reg("lp_pool2d", lp_pool2d)


def _fractional_pool(x, output_size, random_u, n):
    """Fractional max pooling (reference fractional_max_pool2d/3d,
    Graham 2014): pseudo-random region boundaries
    a_i = ceil(alpha*(i+u)) - ceil(alpha*u)."""
    spatial = x.shape[2:2 + n]
    outs = _tup(output_size, n)
    u = float(random_u) if random_u else 0.5

    def bounds(in_s, out_s):
        alpha = in_s / out_s
        i = np.arange(out_s + 1)
        b = np.ceil(alpha * (i + u)) - math.ceil(alpha * u)
        b = np.clip(b.astype(np.int64), 0, in_s)
        b[-1] = in_s
        return b

    bs = [bounds(spatial[d], outs[d]) for d in range(n)]
    # per-cell slice + argmax (region boundaries are static and the output
    # grid small, so the loop unrolls under jit); the argmax gives the true
    # flat input index the unpool op scatters by.
    import itertools

    cells_v, cells_i = {}, {}
    for cell in itertools.product(*[range(o) for o in outs]):
        lo = [int(bs[d][cell[d]]) for d in range(n)]
        hi = [int(max(bs[d][cell[d] + 1], bs[d][cell[d]] + 1))
              for d in range(n)]
        region = x
        for d in range(n):
            region = jax.lax.slice_in_dim(region, lo[d], hi[d], axis=2 + d)
        rs = region.shape[2:]
        flat = region.reshape(region.shape[:2] + (-1,))
        cells_v[cell] = jnp.max(flat, axis=-1)
        a = jnp.argmax(flat, axis=-1)
        pos, rem = None, a
        for d in range(n):
            inner = int(np.prod(rs[d + 1:]))
            p_d = rem // inner + lo[d]
            rem = rem % inner
            pos = p_d if pos is None else pos * spatial[d] + p_d
        cells_i[cell] = pos
    shape = x.shape[:2] + outs
    out = jnp.stack([cells_v[c] for c in sorted(cells_v)], axis=-1)
    flat_idx = jnp.stack([cells_i[c] for c in sorted(cells_i)], axis=-1)
    return out.reshape(shape), flat_idx.reshape(shape).astype(jnp.int32)


def fractional_max_pool2d(x, output_size, kernel_size=None, random_u=0.0,
                          return_mask=False):
    return _fractional_pool(x, output_size, random_u, 2)


def fractional_max_pool3d(x, output_size, kernel_size=None, random_u=0.0,
                          return_mask=False):
    return _fractional_pool(x, output_size, random_u, 3)


_reg("fractional_max_pool2d", fractional_max_pool2d)
_reg("fractional_max_pool3d", fractional_max_pool3d)

_reg("fold", _adapter("fold"))


# ---------------------------------------------------------------------------
# norms
# ---------------------------------------------------------------------------
def layer_norm(x, weight=None, bias=None, epsilon=1e-5,
               begin_norm_axis=1):
    shape = x.shape[begin_norm_axis:]
    return _uw(_F().layer_norm(x, shape, weight, bias, epsilon))


_reg("layer_norm", layer_norm)
_reg("rms_norm", _adapter("rms_norm"))
_reg("group_norm", _adapter("group_norm"))


def instance_norm(x, scale=None, bias=None, epsilon=1e-5):
    return _uw(_F().instance_norm(x, None, None, scale, bias,
                                  eps=epsilon))


_reg("instance_norm", instance_norm)


def spectral_norm(weight, u, v, dim=0, power_iters=1, eps=1e-12):
    """reference spectral_norm op: normalize weight by its largest
    singular value, estimated by power iteration on (u, v)."""
    w = jnp.moveaxis(weight, dim, 0)
    w_mat = w.reshape(w.shape[0], -1)
    for _ in range(max(int(power_iters), 0)):
        v = w_mat.T @ u
        v = v / (jnp.linalg.norm(v) + eps)
        u = w_mat @ v
        u = u / (jnp.linalg.norm(u) + eps)
    sigma = u @ w_mat @ v
    out = w_mat / sigma
    return jnp.moveaxis(out.reshape(w.shape), 0, dim)


_reg("spectral_norm", spectral_norm)


def fused_batch_norm_act(x, scale, bias, mean, variance, momentum=0.9,
                         epsilon=1e-5, act_type="relu"):
    F = _F()
    y = _uw(F.batch_norm(x, mean, variance, scale, bias, training=False,
                         momentum=momentum, epsilon=epsilon))
    act = getattr(F, act_type, F.relu)
    return _uw(act(y))


def fused_bn_add_activation(x, z, scale, bias, mean, variance,
                            momentum=0.9, epsilon=1e-5, act_type="relu"):
    F = _F()
    y = _uw(F.batch_norm(x, mean, variance, scale, bias, training=False,
                         momentum=momentum, epsilon=epsilon))
    return _uw(getattr(F, act_type, F.relu)(y + z))


_reg("fused_batch_norm_act", fused_batch_norm_act)
_reg("fused_bn_add_activation", fused_bn_add_activation)


def sync_batch_norm_(x, mean, variance, scale, bias, is_test=False,
                     momentum=0.9, epsilon=1e-5, data_format="NCHW",
                     use_global_stats=False, trainable_statistics=False,
                     axis_name=None):
    """reference sync_batch_norm_: batch norm with cross-replica batch
    statistics. Inside shard_map/pmap pass axis_name to reduce moments
    over the data axis; outside it's plain batch norm."""
    red = tuple(i for i in range(x.ndim)
                if i != (1 if data_format == "NCHW" else x.ndim - 1))
    if is_test or use_global_stats:
        m, v = mean, variance
    else:
        m = jnp.mean(x, axis=red)
        msq = jnp.mean(x * x, axis=red)
        if axis_name is not None:
            m = jax.lax.pmean(m, axis_name)
            msq = jax.lax.pmean(msq, axis_name)
        v = msq - m * m
    shape = [1] * x.ndim
    shape[1 if data_format == "NCHW" else -1] = -1
    xn = (x - m.reshape(shape)) * jax.lax.rsqrt(v.reshape(shape) + epsilon)
    out = xn * scale.reshape(shape) + bias.reshape(shape)
    new_mean = momentum * mean + (1 - momentum) * m
    new_var = momentum * variance + (1 - momentum) * v
    saved_inv = jax.lax.rsqrt(v + epsilon)
    return out, new_mean, new_var, m, saved_inv, None


_reg("sync_batch_norm_", sync_batch_norm_)


# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------
_reg("bce_loss", _adapter("binary_cross_entropy", reduction="none"))
_reg("kldiv_loss", _adapter("kl_div"))
_reg("nll_loss", _adapter("nll_loss"))
_reg("log_loss", _adapter("log_loss"))
_reg("warpctc", _adapter("ctc_loss"))


def huber_loss(input, label, delta=1.0):
    """reference huber_loss op (returns per-element loss + residual)."""
    r = input - label
    a = jnp.abs(r)
    loss = jnp.where(a <= delta, 0.5 * r * r, delta * (a - 0.5 * delta))
    return loss, r


_reg("huber_loss", huber_loss)


def sigmoid_cross_entropy_with_logits(x, label, pos_weight=None,
                                      normalize=False, ignore_index=-100):
    loss = jnp.maximum(x, 0.0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    if pos_weight is not None:
        log_weight = (pos_weight - 1.0) * label + 1.0
        loss = loss * log_weight
    mask = (label != ignore_index)
    loss = jnp.where(mask, loss, 0.0)
    if normalize:
        loss = loss / jnp.maximum(jnp.sum(mask.astype(x.dtype)), 1.0)
    return loss


_reg("sigmoid_cross_entropy_with_logits", sigmoid_cross_entropy_with_logits)


def cross_entropy_with_softmax(logits, label, soft_label=False,
                               use_softmax=True, numeric_stable_mode=True,
                               ignore_index=-100, axis=-1):
    logp = jax.nn.log_softmax(logits, axis=axis) if use_softmax \
        else jnp.log(jnp.clip(logits, 1e-30))
    softmax = jnp.exp(logp)
    if soft_label:
        loss = -jnp.sum(label * logp, axis=axis, keepdims=True)
    else:
        lab = label.astype(jnp.int32)
        if lab.ndim == logp.ndim:
            lab = jnp.squeeze(lab, axis=axis)
        picked = jnp.take_along_axis(
            logp, jnp.expand_dims(jnp.clip(lab, 0), axis), axis=axis)
        loss = -jnp.where(
            jnp.expand_dims(lab, axis) == ignore_index, 0.0, picked)
    return softmax, loss


_reg("cross_entropy_with_softmax", cross_entropy_with_softmax)


def identity_loss(x, reduction=1):
    """reference identity_loss: 0=sum, 1=mean, 2=none."""
    if reduction in (0, "sum"):
        return jnp.sum(x)
    if reduction in (1, "mean"):
        return jnp.mean(x)
    return x


_reg("identity_loss", identity_loss)


def hsigmoid_loss(x, label, w, bias=None, num_classes=2, path_table=None,
                  path_code=None, is_sparse=False):
    """Hierarchical sigmoid loss (reference hsigmoid_loss op). Default
    tree: complete binary tree over num_classes leaves; codes are the
    bits of (label + num_classes) walked from the root."""
    B = x.shape[0]
    depth = max(int(math.ceil(math.log2(max(num_classes, 2)))), 1)
    if path_table is None:
        # node ids along the path for each label (complete-tree layout)
        lab = label.astype(jnp.int32).reshape(B)
        node = lab + num_classes          # leaf id in heap order
        tables, codes = [], []
        for _ in range(depth):
            codes.append((node % 2).astype(jnp.float32))
            node = node // 2
            tables.append(node)
        path_table = jnp.stack(tables[::-1], axis=1) - 1   # row in w
        path_code = jnp.stack(codes[::-1], axis=1)
    pt = jnp.clip(path_table.astype(jnp.int32), 0, w.shape[0] - 1)
    pc = path_code.astype(x.dtype)
    w_rows = w[pt]                        # [B, depth, feat]
    logits = jnp.einsum("bdf,bf->bd", w_rows, x)
    if bias is not None:
        logits = logits + bias.reshape(-1)[pt]
    # label bit 1 -> sigmoid(logit), 0 -> 1-sigmoid
    loss = jnp.maximum(logits, 0.0) - logits * pc \
        + jnp.log1p(jnp.exp(-jnp.abs(logits)))
    return jnp.sum(loss, axis=1, keepdims=True)


_reg("hsigmoid_loss", hsigmoid_loss)


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, return_softmax=False,
                         ring_id=0, rank=0, nranks=1):
    """ArcFace-style margin softmax (reference margin_cross_entropy op):
    cos(m1*theta + m2) - m3 applied to the target logit, then scaled CE."""
    lab = label.astype(jnp.int32).reshape(-1)
    onehot = jax.nn.one_hot(lab, logits.shape[-1], dtype=logits.dtype)
    cos_t = jnp.clip(logits, -1.0, 1.0)
    theta = jnp.arccos(cos_t)
    target = jnp.cos(margin1 * theta + margin2) - margin3
    adjusted = jnp.where(onehot > 0, target, cos_t) * scale
    logp = jax.nn.log_softmax(adjusted, axis=-1)
    loss = -jnp.take_along_axis(logp, lab[:, None], axis=-1)
    return jnp.exp(logp), loss


_reg("margin_cross_entropy", margin_cross_entropy)


# ---------------------------------------------------------------------------
# interpolation (reference *_interp ops -> one interpolate kernel)
# ---------------------------------------------------------------------------
def _interp(mode):
    def kernel(x, size=None, scale_factor=None, align_corners=False,
               data_format=None):
        n = x.ndim - 2
        if data_format is None:
            data_format = {1: "NCW", 2: "NCHW", 3: "NCDHW"}[n]
        return _uw(_F().interpolate(x, size=size,
                                    scale_factor=scale_factor, mode=mode,
                                    align_corners=align_corners,
                                    data_format=data_format))
    kernel.__name__ = mode + "_interp"
    return kernel


_reg("nearest_interp", _interp("nearest"))
_reg("bilinear_interp", _interp("bilinear"))
_reg("bicubic_interp", _interp("bicubic"))
_reg("linear_interp", _interp("linear"))
_reg("trilinear_interp", _interp("trilinear"))


# ---------------------------------------------------------------------------
# misc nn
# ---------------------------------------------------------------------------
_reg("affine_grid", _adapter("affine_grid"))
_reg("grid_sample", _adapter("grid_sample"))
_reg("pixel_shuffle", _adapter("pixel_shuffle"))
_reg("pixel_unshuffle", _adapter("pixel_unshuffle"))
_reg("channel_shuffle", _adapter("channel_shuffle"))
_reg("temporal_shift", _adapter("temporal_shift"))
_reg("sequence_mask", _adapter("sequence_mask"), differentiable=False)


def shuffle_channel(x, group=1):
    return _uw(_F().channel_shuffle(x, group))


_reg("shuffle_channel", shuffle_channel)


def pad3d(x, paddings, mode="constant", pad_value=0.0,
          data_format="NCDHW"):
    """reference pad3d op: paddings = [l, r, t, b, front, back] on the
    spatial dims of a 5-D tensor."""
    p = [int(i) for i in np.asarray(paddings).reshape(-1)]
    if data_format == "NCDHW":
        full = [(0, 0), (0, 0), (p[4], p[5]), (p[2], p[3]), (p[0], p[1])]
    else:
        full = [(0, 0), (p[4], p[5]), (p[2], p[3]), (p[0], p[1]), (0, 0)]
    jmode = {"constant": "constant", "reflect": "reflect",
             "replicate": "edge", "circular": "wrap"}[mode]
    if jmode == "constant":
        return jnp.pad(x, full, mode=jmode, constant_values=pad_value)
    return jnp.pad(x, full, mode=jmode)


_reg("pad3d", pad3d)


def bilinear(x, y, weight, bias=None):
    """reference bilinear op: out[b, k] = x[b]^T W[k] y[b] + bias."""
    out = jnp.einsum("bi,kij,bj->bk", x, weight, y)
    if bias is not None:
        out = out + bias.reshape(1, -1)
    return out


_reg("bilinear", bilinear)


def swiglu(x, y=None):
    if y is None:
        x, y = jnp.split(x, 2, axis=-1)
    return jax.nn.silu(x) * y


_reg("swiglu", swiglu)


def fused_softmax_mask(x, mask):
    return jax.nn.softmax(x + mask, axis=-1)


def fused_softmax_mask_upper_triangle(x):
    S = x.shape[-1]
    causal = jnp.tril(jnp.ones((S, S), bool))
    masked = jnp.where(causal, x, jnp.finfo(x.dtype).min)
    return jax.nn.softmax(masked, axis=-1)


_reg("fused_softmax_mask", fused_softmax_mask)
_reg("fused_softmax_mask_upper_triangle", fused_softmax_mask_upper_triangle)
