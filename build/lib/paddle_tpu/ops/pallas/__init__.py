"""Pallas TPU kernels — the fused-op layer.

Reference analog: paddle/phi/kernels/fusion/gpu/ (hand-written CUDA fused
kernels: flash attention, fused_rms_norm, fused_rope, ...). On TPU, XLA
already fuses elementwise chains into matmuls, so only the ops XLA fuses
poorly get hand kernels: attention (online-softmax blockwise over the KV
axis) and rmsnorm-style HBM-bound reductions. Every kernel has a pure-jnp
fallback (used on CPU test meshes and as the custom_vjp backward).
"""
import os

import jax


def use_pallas() -> bool:
    flag = os.environ.get("PT_USE_PALLAS", "auto")
    if flag in ("0", "false", "off"):
        return False
    if flag in ("1", "true", "on"):
        return True
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False
