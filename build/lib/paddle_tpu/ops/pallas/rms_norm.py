"""Fused RMSNorm Pallas kernel.

Reference analog: fused_rms_norm (paddle/phi/kernels/fusion/gpu/, python
surface incubate/nn/functional/fused_rms_norm). RMSNorm is HBM-bound: one
read + one write of the activation. The kernel tiles rows into VMEM blocks,
does the reduction in fp32 on the VPU, and writes back in the input dtype —
one pass over HBM. Backward is the analytic jnp formula (XLA fuses it).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from . import use_pallas

_BLOCK_ROWS = 256


def _rms_norm_ref(x, weight, eps):
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    out = xf * jax.lax.rsqrt(var + eps)
    if weight is not None:
        out = out * weight.astype(jnp.float32)
    return out.astype(x.dtype)


def _kernel(x_ref, w_ref, o_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    out = x * jax.lax.rsqrt(var + eps)
    out = out * w_ref[:].astype(jnp.float32)
    o_ref[:] = out.astype(o_ref.dtype)


def _kernel_nw(x_ref, o_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    o_ref[:] = (x * jax.lax.rsqrt(var + eps)).astype(o_ref.dtype)


def _pallas_forward(x, weight, eps):
    orig_shape = x.shape
    h = orig_shape[-1]
    x2 = x.reshape(-1, h)
    n = x2.shape[0]
    block = min(_BLOCK_ROWS, n)
    if n % block != 0:
        # row-count not tileable; XLA path handles the remainder fine
        return _rms_norm_ref(x, weight, eps)
    grid = (n // block,)
    if weight is not None:
        out = pl.pallas_call(
            functools.partial(_kernel, eps=eps),
            out_shape=jax.ShapeDtypeStruct((n, h), x.dtype),
            grid=grid,
            in_specs=[
                pl.BlockSpec((block, h), lambda i: (i, 0)),
                pl.BlockSpec((h,), lambda i: (0,)),
            ],
            out_specs=pl.BlockSpec((block, h), lambda i: (i, 0)),
        )(x2, weight)
    else:
        out = pl.pallas_call(
            functools.partial(_kernel_nw, eps=eps),
            out_shape=jax.ShapeDtypeStruct((n, h), x.dtype),
            grid=grid,
            in_specs=[pl.BlockSpec((block, h), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((block, h), lambda i: (i, 0)),
        )(x2)
    return out.reshape(orig_shape)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _rms_norm(x, weight, eps, has_weight):
    if use_pallas():
        return _pallas_forward(x, weight if has_weight else None, eps)
    return _rms_norm_ref(x, weight if has_weight else None, eps)


def _fwd(x, weight, eps, has_weight):
    return _rms_norm(x, weight, eps, has_weight), (x, weight)


def _bwd(eps, has_weight, res, g):
    x, weight = res
    xf = x.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    xhat = xf * inv
    if has_weight:
        wf = weight.astype(jnp.float32)
        gw = jnp.sum(gf * xhat, axis=tuple(range(x.ndim - 1)))
        gxhat = gf * wf
    else:
        gw = jnp.zeros_like(weight, dtype=jnp.float32)
        gxhat = gf
    h = x.shape[-1]
    gx = inv * (gxhat - xhat * jnp.mean(gxhat * xhat, axis=-1, keepdims=True))
    return gx.astype(x.dtype), gw.astype(weight.dtype)


_rms_norm.defvjp(_fwd, _bwd)


def rms_norm(x, weight=None, eps: float = 1e-6):
    """rms_norm over the last axis. weight=None -> pure normalization."""
    if weight is None:
        w = jnp.ones((x.shape[-1],), x.dtype)
        return _rms_norm(x, w, eps, False)
    return _rms_norm(x, weight, eps, True)
