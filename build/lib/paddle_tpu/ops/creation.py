"""Tensor creation ops (reference: python/paddle/tensor/creation.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dtype import convert_dtype
from ..core.place import Place
from ..core.tensor import Tensor
from ..core.dispatch import apply
from . import registry

__all__ = [
    "to_tensor", "zeros", "ones", "full", "empty", "zeros_like", "ones_like",
    "full_like", "empty_like", "arange", "linspace", "logspace", "eye",
    "tril", "triu", "diag", "diagflat", "meshgrid", "assign", "clone",
    "tril_indices", "triu_indices", "one_hot", "complex",
]


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.numpy())
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s._value) if isinstance(s, Tensor) else int(s)
                 for s in shape)


def to_tensor(data, dtype=None, place: Place = None, stop_gradient=True):
    return Tensor(data, dtype=dtype, place=place, stop_gradient=stop_gradient)


def zeros(shape, dtype="float32", name=None):
    return Tensor(jnp.zeros(_shape(shape), convert_dtype(dtype) or jnp.float32))


def ones(shape, dtype="float32", name=None):
    return Tensor(jnp.ones(_shape(shape), convert_dtype(dtype) or jnp.float32))


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    if dtype is None:
        dtype = "float32"
    return Tensor(jnp.full(_shape(shape), fill_value, convert_dtype(dtype)))


def empty(shape, dtype="float32", name=None):
    return zeros(shape, dtype)


def zeros_like(x, dtype=None, name=None):
    return apply(lambda a: jnp.zeros_like(a, dtype=convert_dtype(dtype)), x,
                 op_name="zeros_like", differentiable=False)


def ones_like(x, dtype=None, name=None):
    return apply(lambda a: jnp.ones_like(a, dtype=convert_dtype(dtype)), x,
                 op_name="ones_like", differentiable=False)


def full_like(x, fill_value, dtype=None, name=None):
    return apply(
        lambda a: jnp.full_like(a, fill_value, dtype=convert_dtype(dtype)), x,
        op_name="full_like", differentiable=False)


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    def conv(v):
        return v.item() if isinstance(v, Tensor) else v
    start, end, step = conv(start), conv(end), conv(step)
    if end is None:
        start, end = 0, start
    d = convert_dtype(dtype)
    if d is None:
        if all(isinstance(v, (int, np.integer)) for v in (start, end, step)):
            d = np.dtype(np.int64)
        else:
            d = np.dtype(np.float32)
    return Tensor(jnp.arange(start, end, step, dtype=d))


def linspace(start, stop, num, dtype=None, name=None):
    def conv(v):
        return v.item() if isinstance(v, Tensor) else v
    return Tensor(jnp.linspace(conv(start), conv(stop), int(conv(num)),
                               dtype=convert_dtype(dtype) or jnp.float32))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    def conv(v):
        return v.item() if isinstance(v, Tensor) else v
    return Tensor(jnp.logspace(conv(start), conv(stop), int(conv(num)),
                               base=conv(base),
                               dtype=convert_dtype(dtype) or jnp.float32))


def eye(num_rows, num_columns=None, dtype="float32", name=None):
    return Tensor(jnp.eye(int(num_rows),
                          int(num_columns) if num_columns is not None else None,
                          dtype=convert_dtype(dtype)))


def tril(x, diagonal=0, name=None):
    return apply(lambda a: jnp.tril(a, k=int(diagonal)), x, op_name="tril")


def triu(x, diagonal=0, name=None):
    return apply(lambda a: jnp.triu(a, k=int(diagonal)), x, op_name="triu")


def diag(x, offset=0, padding_value=0, name=None):
    def fn(a):
        if a.ndim == 1:
            out = jnp.diag(a, k=int(offset))
            if padding_value != 0:
                mask = jnp.diag(jnp.ones_like(a), k=int(offset))
                out = out + (1 - mask) * padding_value
            return out
        return jnp.diagonal(a, offset=int(offset), axis1=-2, axis2=-1)
    return apply(fn, x, op_name="diag")


def diagflat(x, offset=0, name=None):
    return apply(lambda a: jnp.diagflat(a, k=int(offset)), x,
                 op_name="diagflat")


def meshgrid(*args, **kwargs):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = tuple(args[0])
    outs = apply(lambda *xs: jnp.meshgrid(*xs, indexing="ij"), *args,
                 op_name="meshgrid")
    return list(outs)


def assign(x, output=None):
    src = Tensor(x) if not isinstance(x, Tensor) else x
    out = apply(lambda a: a + 0 if jnp.issubdtype(a.dtype, jnp.inexact)
                else jnp.asarray(a), src, op_name="assign")
    if output is not None:
        output.set_value(out)
        return output
    return out


def clone(x, name=None):
    return x.clone()


def tril_indices(row, col, offset=0, dtype="int64"):
    r, c = np.tril_indices(int(row), int(offset), int(col))
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=convert_dtype(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    col = row if col is None else col
    r, c = np.triu_indices(int(row), int(offset), int(col))
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=convert_dtype(dtype)))


def one_hot(x, num_classes, name=None):
    return apply(
        lambda a: jax.nn.one_hot(a, int(num_classes), dtype=jnp.float32), x,
        op_name="one_hot", differentiable=False)


def complex(real, imag, name=None):
    return apply(lambda r, i: jax.lax.complex(r, i), real, imag,
                 op_name="complex")


for _n in __all__:
    registry.register(_n, globals()[_n], tags=("creation",))
