"""Random sampling ops (reference: python/paddle/tensor/random.py).

Every draw pulls a key from framework.random.next_key(), so randomness is
deterministic given paddle_tpu.seed() and trace-safe under rng_guard."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply
from ..core.dtype import convert_dtype
from ..core.tensor import Tensor
from ..framework.random import next_key
from . import registry

__all__ = [
    "uniform", "uniform_", "normal", "normal_", "standard_normal", "randn",
    "rand", "randint", "randint_like", "randperm", "bernoulli", "poisson",
    "multinomial", "gaussian", "exponential_", "binomial", "standard_gamma",
    "log_normal", "cauchy_", "geometric_",
]


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.numpy())
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s._value) if isinstance(s, Tensor) else int(s)
                 for s in shape)


def uniform(shape, dtype="float32", min=-1.0, max=1.0, seed=0, name=None):
    d = convert_dtype(dtype) or jnp.float32
    key = next_key() if not seed else jax.random.key(seed)
    return Tensor(jax.random.uniform(key, _shape(shape), d, min, max))


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    x._value = jax.random.uniform(
        next_key() if not seed else jax.random.key(seed),
        x._value.shape, x._value.dtype, min, max)
    return x


def gaussian(shape, mean=0.0, std=1.0, seed=0, dtype="float32", name=None):
    d = convert_dtype(dtype) or jnp.float32
    key = next_key() if not seed else jax.random.key(seed)
    return Tensor(mean + std * jax.random.normal(key, _shape(shape), d))


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._value if isinstance(mean, Tensor) else mean
        s = std._value if isinstance(std, Tensor) else std
        out_shape = jnp.broadcast_shapes(
            np.shape(m), np.shape(s)
        )
        return Tensor(m + s * jax.random.normal(next_key(), out_shape,
                                                jnp.float32))
    return gaussian(shape if shape is not None else [1], mean, std)


def normal_(x, mean=0.0, std=1.0, name=None):
    x._value = (mean + std * jax.random.normal(
        next_key(), x._value.shape, x._value.dtype))
    return x


def standard_normal(shape, dtype="float32", name=None):
    return gaussian(shape, 0.0, 1.0, dtype=dtype)


def randn(shape, dtype="float32", name=None):
    return standard_normal(shape, dtype)


def rand(shape, dtype="float32", name=None):
    return uniform(shape, dtype, 0.0, 1.0)


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    d = convert_dtype(dtype) or jnp.int64
    return Tensor(jax.random.randint(next_key(), _shape(shape), low, high,
                                     dtype=d))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    if high is None:
        low, high = 0, low
    d = convert_dtype(dtype) or x.dtype
    return Tensor(jax.random.randint(next_key(), tuple(x.shape), low, high)
                  .astype(d))


def randperm(n, dtype="int64", name=None):
    d = convert_dtype(dtype) or jnp.int64
    return Tensor(jax.random.permutation(next_key(), int(n)).astype(d))


def bernoulli(x, p=None, name=None):
    def fn(a):
        return jax.random.bernoulli(next_key(), a).astype(a.dtype)
    return apply(fn, x, op_name="bernoulli", differentiable=False)


def poisson(x, name=None):
    def fn(a):
        return jax.random.poisson(next_key(), a).astype(a.dtype)
    return apply(fn, x, op_name="poisson", differentiable=False)


def binomial(count, prob, name=None):
    def fn(n, p):
        return jax.random.binomial(next_key(), n.astype(jnp.float32),
                                   p).astype(jnp.int64)
    return apply(fn, count, prob, op_name="binomial", differentiable=False)


def multinomial(x, num_samples=1, replacement=False, name=None):
    def _sample(a):
        if a.ndim == 1:
            return jax.random.choice(
                next_key(), a.shape[0], shape=(num_samples,),
                replace=replacement, p=a / a.sum()).astype(jnp.int64)
        rows = []
        for i in range(a.shape[0]):
            rows.append(jax.random.choice(
                next_key(), a.shape[1], shape=(num_samples,),
                replace=replacement, p=a[i] / a[i].sum()))
        return jnp.stack(rows).astype(jnp.int64)
    return apply(_sample, x, op_name="multinomial", differentiable=False)


def standard_gamma(x, name=None):
    def fn(a):
        return jax.random.gamma(next_key(), a)
    return apply(fn, x, op_name="standard_gamma", differentiable=False)


def exponential_(x, lam=1.0, name=None):
    x._value = jax.random.exponential(
        next_key(), x._value.shape, x._value.dtype) / lam
    return x


def log_normal(mean=1.0, std=2.0, shape=None, name=None):
    g = gaussian(shape if shape is not None else [1], mean, std)
    return Tensor(jnp.exp(g._value))


def cauchy_(x, loc=0, scale=1, name=None):
    x._value = loc + scale * jax.random.cauchy(
        next_key(), x._value.shape, x._value.dtype)
    return x


def geometric_(x, probs, name=None):
    u = jax.random.uniform(next_key(), x._value.shape, jnp.float32, 1e-7, 1.0)
    x._value = (jnp.ceil(jnp.log(u) / jnp.log1p(-probs))).astype(
        x._value.dtype)
    return x


for _n in __all__:
    registry.register(_n, globals()[_n], tags=("random",))
