"""paddle.linalg namespace (reference: python/paddle/linalg.py re-exports)."""
from .ops.linalg import (cholesky, cholesky_inverse, cholesky_solve, cond, corrcoef, cov, det,
                         eig, eigh, eigvals, eigvalsh, householder_product,
                         inv, lstsq, lu, lu_unpack, matmul, matrix_power,
                         matrix_rank, multi_dot, norm, pca_lowrank, pinv, qr,
                         matrix_exp, matrix_norm, ormqr, slogdet, solve,
                         svd, svd_lowrank, triangular_solve, vander,
                         vector_norm)
from .ops.math import cross, dot
