"""Global flag registry.

Reference analog: paddle/common/flags.cc (~1800 lines of
PHI_DEFINE_EXPORTED_* gflags with FLAGS_* env override) surfaced as
paddle.get_flags/set_flags (python/paddle/base/framework.py:109,134).
Flags here follow the same contract: declared with a default + help string,
overridable by FLAGS_<name> env vars at import, queryable/settable at
runtime.
"""
from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Any, Dict

__all__ = ["define_flag", "get_flags", "set_flags", "flag"]


@dataclass
class _Flag:
    name: str
    default: Any
    value: Any
    help: str


_REGISTRY: Dict[str, _Flag] = {}
_lock = threading.Lock()


def _coerce(default, raw: str):
    if isinstance(default, bool):
        return raw.lower() in ("1", "true", "yes", "on")
    if isinstance(default, int):
        return int(raw)
    if isinstance(default, float):
        return float(raw)
    return raw


def define_flag(name: str, default, help: str = ""):
    env = os.environ.get("FLAGS_" + name)
    value = _coerce(default, env) if env is not None else default
    with _lock:
        _REGISTRY[name] = _Flag(name, default, value, help)
    return value


def flag(name: str):
    f = _REGISTRY.get(name)
    return f.value if f is not None else None


def get_flags(flags=None):
    if flags is None:
        return {name: f.value for name, f in _REGISTRY.items()}
    if isinstance(flags, str):
        flags = [flags]
    out = {}
    for name in flags:
        key = name[6:] if name.startswith("FLAGS_") else name
        if key not in _REGISTRY:
            raise ValueError(f"unknown flag {name}")
        out[name] = _REGISTRY[key].value
    return out


def set_flags(flags: Dict[str, Any]):
    for name, value in flags.items():
        key = name[6:] if name.startswith("FLAGS_") else name
        with _lock:
            if key not in _REGISTRY:
                _REGISTRY[key] = _Flag(key, value, value, "")
            else:
                _REGISTRY[key].value = value


# core flags (mirroring the reference's most-used ones)
define_flag("check_nan_inf", False,
            "check outputs of every op for NaN/Inf (debug)")
define_flag("check_nan_inf_level", 0, "0: error on nan/inf; 3: report only")
define_flag("use_pallas", True, "use Pallas kernels for fused ops on TPU")
define_flag("benchmark", False, "sync after every op for timing")
define_flag("eager_jit_threshold", 0, "reserved: per-op jit cache policy")
define_flag("allocator_strategy", "xla",
            "memory allocator (XLA BFC is authoritative on TPU)")
define_flag("fraction_of_gpu_memory_to_use", 0.92,
            "accepted for compat; XLA preallocation controls TPU HBM")
