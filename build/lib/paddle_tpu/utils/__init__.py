from . import flags
