"""DataParallel (reference: python/paddle/distributed/parallel.py:202 +
EagerReducer reducer.h:88).

TPU-native: in the compiled path DP is a mesh axis — the batch is sharded,
params replicated, and XLA inserts+overlaps the gradient psum (that IS the
EagerReducer's bucketed-overlap job, done by the compiler). This wrapper
keeps the reference API: it broadcasts initial params across the dp group
and registers grad hooks that allreduce in eager multi-controller mode."""
from __future__ import annotations

import jax

from ..core.tensor import Tensor
from ..nn.layer.layers import Layer
from . import collective, env

__all__ = ["DataParallel"]


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self.group = group
        self.find_unused_parameters = find_unused_parameters
        self._world = collective.get_world_size(group)
        if self._world > 1:
            self._sync_params()
            self._register_hooks()

    def _sync_params(self):
        for p in self._layers.parameters():
            collective.broadcast(p, src=0, group=self.group)

    def _register_hooks(self):
        world = self._world
        group = self.group

        def make_hook():
            def hook(grad):
                collective.all_reduce(grad, group=group)
                return grad / world
            return hook

        for p in self._layers.parameters():
            if not p.stop_gradient:
                p.register_hook(make_hook())

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def scale_loss(self, loss):
        return loss

    @property
    def _inner_layers(self):
        return self._layers

    def no_sync(self):
        import contextlib

        @contextlib.contextmanager
        def guard():
            yield
        return guard()
