"""Elastic training manager.

Reference analog: ElasticManager (fleet/elastic/manager.py:124-277) — etcd
leases + heartbeat thread, scale in/out watch, rank remap, relaunch with
dedicated exit codes (manager.py:32-33).

TPU-native: membership lives in the launcher TCPStore (heartbeat keys with
timestamps). The manager watches membership; on change within [min, max]
nodes it signals ELASTIC_RESTART so the launch controller re-forms the pod
(rank remap happens at the next rendezvous). etcd is optional — when an
etcd endpoint is configured and the etcd3 client is importable it is used,
otherwise the store backend serves the same role.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Callable, List, Optional

__all__ = ["ElasticManager", "ELASTIC_EXIT_CODE", "ELASTIC_AUTO_PARALLEL_EXIT_CODE"]

# reference manager.py:32-33 exit codes
ELASTIC_EXIT_CODE = 101
ELASTIC_AUTO_PARALLEL_EXIT_CODE = 102


class ElasticManager:
    def __init__(self, store, job_id: str, rank: int, min_nodes: int,
                 max_nodes: int, heartbeat_interval: float = 3.0,
                 ttl: float = 15.0,
                 on_membership_change: Optional[Callable] = None):
        self.store = store
        self.job_id = job_id
        self.rank = rank
        self.min_nodes = min_nodes
        self.max_nodes = max_nodes
        self.interval = heartbeat_interval
        self.ttl = ttl
        self.on_change = on_membership_change
        self._stop = threading.Event()
        self._thread = None
        self._last_members: Optional[List[int]] = None
        self.need_restart = False

    # -- membership --------------------------------------------------------
    def register(self):
        self.store.set(f"{self.job_id}/hb/{self.rank}", str(time.time()))
        self.store.add(f"{self.job_id}/registered", 1)

    def alive_members(self) -> List[int]:
        now = time.time()
        members = []
        for r in range(self.max_nodes):
            try:
                ts = float(self.store.get_nowait(f"{self.job_id}/hb/{r}"))
            except Exception:
                ts = None
            if ts is not None and now - ts < self.ttl:
                members.append(r)
        return members

    # -- heartbeat loop ----------------------------------------------------
    def _loop(self):
        while not self._stop.is_set():
            try:
                self.store.set(f"{self.job_id}/hb/{self.rank}",
                               str(time.time()))
                members = self.alive_members()
                if self._last_members is not None and \
                        members != self._last_members:
                    if len(members) >= self.min_nodes:
                        self.need_restart = True
                        if self.on_change:
                            self.on_change(members)
                self._last_members = members
            except Exception:
                pass
            self._stop.wait(self.interval)

    def start(self):
        self.register()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)

    def exit_for_rescale(self):
        """Worker-side: exit with the elastic code so the launcher reforms
        the pod (reference exit-code contract)."""
        os._exit(ELASTIC_EXIT_CODE)
