"""fleet.layers — reference namespace parity
(python/paddle/distributed/fleet/layers/)."""
from . import mpu

__all__ = ["mpu"]
