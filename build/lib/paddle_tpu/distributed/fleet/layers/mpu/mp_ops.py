"""mp_ops — collective ops used by TP layers (reference
fleet/layers/mpu/mp_ops.py: _c_identity/_c_concat/_c_split/_mp_allreduce).
On TPU these are the mesh collectives from paddle_tpu.distributed."""
from ....collective import (all_gather, all_reduce, reduce_scatter,
                            scatter)  # noqa: F401
from ....topology import get_mesh  # noqa: F401


def _c_identity(tensor, group=None):
    """Identity forward / allreduce backward (reference mp_ops.py). Under
    GSPMD the backward allreduce is inserted by XLA from the shardings."""
    return tensor


def _mp_allreduce(tensor, group=None, use_calc_stream=True,
                  use_model_parallel=True):
    all_reduce(tensor, group=group)
    return tensor
