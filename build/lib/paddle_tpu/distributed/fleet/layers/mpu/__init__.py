"""fleet.layers.mpu — model-parallel utility layers.

Reference: python/paddle/distributed/fleet/layers/mpu/mp_layers.py:47,334,
541,742 (VocabParallelEmbedding / ColumnParallelLinear / RowParallelLinear /
ParallelCrossEntropy). Implementations live in
paddle_tpu.distributed.meta_parallel.mp_layers (GSPMD placements instead of
hand-rolled NCCL collectives); this package is the import-path parity shim.
"""
from ....meta_parallel.mp_layers import (  # noqa: F401
    ColumnParallelLinear, ParallelCrossEntropy, RowParallelLinear,
    VocabParallelEmbedding)
from . import mp_ops  # noqa: F401

__all__ = ["VocabParallelEmbedding", "ColumnParallelLinear",
           "RowParallelLinear", "ParallelCrossEntropy", "mp_ops"]
