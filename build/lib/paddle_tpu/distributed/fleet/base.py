"""DistributedStrategy (reference:
python/paddle/distributed/fleet/base/distributed_strategy.py:175 over the
distributed_strategy.proto). Plain-python config object with the same field
names Fleet scripts set."""
from __future__ import annotations

__all__ = ["DistributedStrategy", "PaddleCloudRoleMaker", "UserDefinedRoleMaker"]


class _Dotted(dict):
    def __getattr__(self, k):
        try:
            return self[k]
        except KeyError:
            raise AttributeError(k)

    def __setattr__(self, k, v):
        self[k] = v


class DistributedStrategy:
    def __init__(self):
        self.hybrid_configs = {
            "dp_degree": 1,
            "mp_degree": 1,
            "pp_degree": 1,
            "sharding_degree": 1,
            "sep_degree": 1,
            "order": ["dp", "pp", "sharding", "sep", "mp"],
            "mp_configs": _Dotted(),
            "pp_configs": _Dotted(
                micro_batch_size=1,
                accumulate_steps=1,
                schedule_mode="1F1B",
            ),
            "sharding_configs": _Dotted(stage=1, offload=False),
        }
        self.amp = False
        self.amp_configs = {"init_loss_scaling": 32768.0,
                            "use_pure_fp16": False,
                            "use_bf16": True}
        self.recompute = False
        self.recompute_configs = {"checkpoints": []}
        self.sharding = False
        self.sharding_configs = {"stage": 1, "sharding_degree": 1}
        self.gradient_merge = False
        self.gradient_merge_configs = {"k_steps": 1, "avg": True}
        self.pipeline = False
        self.pipeline_configs = {"accumulate_steps": 1,
                                 "micro_batch_size": 1}
        self.tensor_parallel = False
        self.tensor_parallel_configs = {"tensor_parallel_degree": 1}
        self.lamb = False
        self.dgc = False
        self.find_unused_parameters = False
        self.fuse_all_reduce_ops = True
        self.fuse_grad_size_in_MB = 32
        self.nccl_comm_num = 1
        self.sync_nccl_allreduce = False
        self.heter_ccl_mode = False
        self.auto_search = False
        self.a_sync = False
        self.without_graph_optimization = True

    def __repr__(self):
        return f"DistributedStrategy(hybrid={self.hybrid_configs})"


class PaddleCloudRoleMaker:
    """reference: fleet/base/role_maker.py — reads the launcher env.

    Collective mode: rank/world from the collective env. PS mode
    (is_collective=False): reads the reference's PS env contract —
    TRAINING_ROLE (TRAINER|PSERVER), PADDLE_PSERVERS_IP_PORT_LIST,
    PADDLE_TRAINERS_NUM, PADDLE_TRAINER_ID, POD_IP, PADDLE_PORT."""

    def __init__(self, is_collective=True, **kwargs):
        import os

        self._is_collective = is_collective
        self._role = os.environ.get("TRAINING_ROLE", "TRAINER").upper()
        eps = os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "")
        self._server_eps = [e for e in eps.split(",") if e]
        self._trainers_num = int(os.environ.get("PADDLE_TRAINERS_NUM",
                                                "1") or 1)
        self._trainer_id = int(os.environ.get("PADDLE_TRAINER_ID", "0") or 0)
        self._pod_ip = os.environ.get("POD_IP", "127.0.0.1")
        self._port = os.environ.get("PADDLE_PORT", "")

    def _worker_num(self):
        if not self._is_collective:
            return self._trainers_num
        from .. import env

        return env.get_world_size()

    def _worker_index(self):
        if not self._is_collective:
            return self._trainer_id
        from .. import env

        return env.global_rank()

    def _is_worker(self):
        return self._is_collective or self._role == "TRAINER"

    def _is_server(self):
        return not self._is_collective and self._role == "PSERVER"

    def _server_num(self):
        return len(self._server_eps)

    def _server_endpoints(self):
        return list(self._server_eps)

    def _server_endpoint(self):
        """This PSERVER node's own endpoint (must be one of the
        advertised endpoints or clients will never route to it)."""
        me = f"{self._pod_ip}:{self._port}"
        if not self._port or (self._server_eps
                              and me not in self._server_eps):
            raise RuntimeError(
                f"PSERVER endpoint {me!r} not in "
                f"PADDLE_PSERVERS_IP_PORT_LIST={self._server_eps}; set "
                "POD_IP/PADDLE_PORT to one of the advertised endpoints")
        return me


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    """Programmatic role maker (reference fleet/base/role_maker.py
    UserDefinedRoleMaker): pass role/endpoints directly instead of env."""

    def __init__(self, is_collective=False, current_id=0, role="TRAINER",
                 worker_num=1, server_endpoints=None, **kwargs):
        super().__init__(is_collective=is_collective, **kwargs)
        self._role = role.upper()
        self._trainer_id = current_id
        self._trainers_num = worker_num
        self._server_eps = list(server_endpoints or [])
        if self._role == "PSERVER" and self._server_eps:
            ep = self._server_eps[current_id % len(self._server_eps)]
            self._pod_ip, self._port = ep.rsplit(":", 1)
