"""Fleet facade (reference: python/paddle/distributed/fleet/fleet.py —
init:167, distributed_model via model.py:32, distributed_optimizer:1326).

fleet.init builds the hybrid topology over the device mesh; distributed_model
picks the engine by parallel mode (TensorParallel / PipelineParallel /
ShardingParallel / SegmentParallel / DataParallel wrapper), and
distributed_optimizer wraps with HybridParallelOptimizer. Same dispatch
shape as the reference, engines re-designed for XLA SPMD.
"""
from __future__ import annotations

from typing import Optional

from .. import env as _env
from .. import topology as _topology
from ..topology import CommunicateTopology, HybridCommunicateGroup
from .base import DistributedStrategy

_fleet_state = {
    "initialized": False,
    "strategy": None,
    "hcg": None,
}


def init(role_maker=None, is_collective=False, strategy=None, log_level="INFO"):
    # PS mode (reference fleet.init(role) / fleet.init(is_collective=False)
    # under the PS env contract): stand up TheOnePs instead of the
    # collective topology
    import os as _os

    if (role_maker is None and not is_collective
            and _os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST")):
        from .base import PaddleCloudRoleMaker

        role_maker = PaddleCloudRoleMaker(is_collective=False)
    if role_maker is not None and not getattr(
            role_maker, "_is_collective", True):
        from ..ps.the_one_ps import TheOnePs, set_runtime

        rt = TheOnePs(role_maker)
        set_runtime(rt)
        _fleet_state.update(initialized=True,
                            strategy=strategy or DistributedStrategy(),
                            hcg=None, role_maker=role_maker, ps_runtime=rt)
        return None
    _env.init_parallel_env()
    strategy = strategy or DistributedStrategy()
    hc = strategy.hybrid_configs
    import jax

    n_dev = len(jax.devices())
    degrees = {
        "dp": hc.get("dp_degree", 1) or 1,
        "pp": hc.get("pp_degree", 1) or 1,
        "sharding": hc.get("sharding_degree", 1) or 1,
        "sep": hc.get("sep_degree", 1) or 1,
        "mp": hc.get("mp_degree", 1) or 1,
    }
    import numpy as np

    specified = int(np.prod(list(degrees.values())))
    if degrees["dp"] == -1 or (specified < n_dev and degrees["dp"] == 1
                               and specified > 1):
        degrees["dp"] = max(n_dev // (specified // max(degrees["dp"], 1)), 1)
    topo = CommunicateTopology(
        ["dp", "pp", "sharding", "sep", "mp"],
        [degrees["dp"], degrees["pp"], degrees["sharding"], degrees["sep"],
         degrees["mp"]])
    hcg = HybridCommunicateGroup(topo)
    if topo.world_size() <= n_dev:
        hcg.build_mesh()
    _topology.set_hybrid_communicate_group(hcg)
    _fleet_state.update(initialized=True, strategy=strategy, hcg=hcg,
                        role_maker=None, ps_runtime=None)
    return None


def is_initialized():
    return _fleet_state["initialized"]


def _ps_runtime():
    rt = _fleet_state.get("ps_runtime")
    if rt is None:
        raise RuntimeError("fleet is not in parameter-server mode; "
                           "init with a PS role maker first")
    return rt


def is_server():
    rm = _fleet_state.get("role_maker")
    return bool(rm is not None and rm._is_server())


def is_worker():
    rm = _fleet_state.get("role_maker")
    return rm is None or rm._is_worker()


def server_num():
    rm = _fleet_state.get("role_maker")
    return rm._server_num() if rm is not None else 0


def init_server(*args, **kwargs):
    _ps_runtime().init_server(*args, **kwargs)


def run_server():
    _ps_runtime().run_server()


def stop_server():
    _ps_runtime().stop_server()


def init_worker():
    _ps_runtime().init_worker()


def stop_worker(stop_servers=False):
    _ps_runtime().stop_worker(stop_servers=stop_servers)


def get_hybrid_communicate_group() -> Optional[HybridCommunicateGroup]:
    return _fleet_state["hcg"]


def _hcg() -> HybridCommunicateGroup:
    if _fleet_state["hcg"] is None:
        init(is_collective=True)
    return _fleet_state["hcg"]


def distributed_model(model):
    """reference: fleet/model.py:32 — dispatch on parallel mode."""
    from ..meta_parallel import (PipelineParallel, SegmentParallel,
                                 ShardingParallel, TensorParallel)
    from ..parallel import DataParallel

    hcg = _hcg()
    strategy = _fleet_state["strategy"]
    mode = hcg.get_parallel_mode()
    if mode == "single":
        return model
    if mode == "data_parallel":
        return DataParallel(model, group=hcg.get_data_parallel_group())
    if mode == "tensor_parallel":
        return TensorParallel(model, hcg, strategy=strategy)
    if mode == "segment_parallel":
        return SegmentParallel(model, hcg, strategy=strategy)
    if mode == "sharding_parallel":
        return ShardingParallel(model, hcg, strategy=strategy)
    if mode == "pipeline":
        from ..meta_parallel.pipeline_parallel import (
            PipelineParallelWithInterleave, PipelineParallelZeroBubble)
        from ..meta_parallel.pp_layers import PipelineLayer

        pp_cfg = dict(strategy.hybrid_configs.get("pp_configs", {}) or {}) \
            if strategy is not None else {}
        sched = str(pp_cfg.get("schedule_mode", "1F1B")).upper()
        v = 1
        if isinstance(model, PipelineLayer):
            v = model.get_num_virtual_stages()
        if sched in ("ZBH1", "ZB-H1", "ZERO_BUBBLE"):
            return PipelineParallelZeroBubble(model, hcg, strategy=strategy)
        if v > 1 or sched == "VPP":
            return PipelineParallelWithInterleave(
                model, hcg, strategy=strategy,
                num_virtual_pipeline_stages=max(v, 1))
        return PipelineParallel(model, hcg, strategy=strategy)
    return model


def distributed_optimizer(optimizer, strategy=None):
    if _fleet_state.get("ps_runtime") is not None:
        from ..ps.the_one_ps import PSOptimizer

        return PSOptimizer(optimizer, _fleet_state["ps_runtime"])
    """reference: fleet.py:1326 -> HybridParallelOptimizer."""
    from ..meta_parallel.hybrid_optimizer import HybridParallelOptimizer

    hcg = _hcg()
    return HybridParallelOptimizer(
        optimizer, hcg, _fleet_state["strategy"] or strategy)


def distributed_scaler(scaler):
    return scaler


# info APIs (reference fleet.py worker_num etc.)
def worker_num():
    rm = _fleet_state.get("role_maker")
    return rm._worker_num() if rm is not None else _env.get_world_size()


def worker_index():
    rm = _fleet_state.get("role_maker")
    return rm._worker_index() if rm is not None else _env.global_rank()


def is_first_worker():
    return is_worker() and worker_index() == 0

def worker_endpoints(to_string=False):
    eps = _env.ParallelEnv().trainer_endpoints
    return ",".join(eps) if to_string else eps


def barrier_worker():
    if _fleet_state.get("ps_runtime") is not None:
        _ps_runtime().barrier_worker()
        return
    from .. import collective

    collective.barrier()
