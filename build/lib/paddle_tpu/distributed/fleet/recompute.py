"""Recompute / activation checkpointing.

Reference analog: RecomputeFunction PyLayer + recompute_sequential
(fleet/recompute/recompute.py:109,403,567) with an RNG-state tracker for TP
determinism. TPU-native: the segment is traced as a pure function of
(explicit tensor args + every parameter the segment touches — discovered via
the dispatcher's param-capture hook) and wrapped in jax.checkpoint, so its
vjp recomputes the forward instead of keeping residuals. RNG determinism
between the two passes comes from replaying the same functional key — no
CUDA RNG state juggling."""
from __future__ import annotations

import jax

from ...core import autograd
from ...core.dispatch import apply, param_capture
from ...core.tensor import Tensor
from ...framework.random import next_key, rng_guard

__all__ = ["recompute", "recompute_sequential"]


def recompute(function, *args, **kwargs):
    kwargs.pop("preserve_rng_state", True)
    kwargs.pop("use_reentrant", True)

    if not autograd.is_grad_enabled():
        return function(*args, **kwargs)

    in_tensors = [a for a in args if isinstance(a, Tensor)]
    key = next_key()

    # discovery pass: find closure-captured parameters (runs the segment
    # once without recording; its FLOPs are the price of recompute anyway)
    with autograd.no_grad(), rng_guard(key), param_capture() as cap:
        function(*args, **kwargs)
    params = cap.params
    # exclude explicit inputs from the captured set
    explicit = {id(t) for t in in_tensors}
    params = [p for p in params if id(p) not in explicit]

    all_inputs = in_tensors + params

    def pure(*arrays):
        arg_arrays = arrays[: len(in_tensors)]
        param_arrays = arrays[len(in_tensors):]
        it = iter(arg_arrays)
        new_args = [Tensor(next(it), stop_gradient=True)
                    if isinstance(a, Tensor) else a for a in args]
        originals = [p._value for p in params]
        try:
            for p, arr in zip(params, param_arrays):
                p._value = arr
            with autograd.no_grad(), rng_guard(key):
                out = function(*new_args, **kwargs)
        finally:
            for p, orig in zip(params, originals):
                p._value = orig
        if isinstance(out, (tuple, list)):
            return tuple(o._value if isinstance(o, Tensor) else o
                         for o in out)
        return out._value

    ckpt_fn = jax.checkpoint(pure)
    return apply(ckpt_fn, *all_inputs, op_name="recompute")


def recompute_sequential(ctx, functions, *args, **kwargs):
    """reference :567 — recompute over a Sequential in segments."""
    segments = ctx.get("segments", 1) if isinstance(ctx, dict) else 1
    if hasattr(functions, "_sub_layers"):
        functions = list(functions._sub_layers.values())
    n = len(functions)
    seg_size = max(n // max(segments, 1), 1)

    def run_segment(lo, hi):
        def seg_fn(x):
            for f in functions[lo:hi]:
                x = f(x)
            return x
        return seg_fn

    x = args[0]
    lo = 0
    while lo < n:
        hi = min(lo + seg_size, n)
        x = recompute(run_segment(lo, hi), x, **kwargs)
        lo = hi
    return x
