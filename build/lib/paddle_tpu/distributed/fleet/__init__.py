"""paddle_tpu.distributed.fleet (reference: python/paddle/distributed/fleet/)."""
from . import base
from .base import DistributedStrategy, PaddleCloudRoleMaker, UserDefinedRoleMaker
from .fleet import (barrier_worker, distributed_model, distributed_optimizer,
                    distributed_scaler, get_hybrid_communicate_group, init,
                    init_server, init_worker, is_first_worker, is_initialized,
                    is_server, is_worker, run_server, server_num, stop_server,
                    stop_worker, worker_endpoints, worker_index, worker_num)
from . import recompute as _recompute_mod
from .recompute import recompute, recompute_sequential
from . import sequence_parallel_utils

from .. import meta_parallel
from . import layers
from ..meta_parallel import (ColumnParallelLinear, ParallelCrossEntropy,
                             RowParallelLinear, VocabParallelEmbedding)

# reference exposes fleet.meta_parallel.* via fleet namespace in places
from ..topology import CommunicateTopology, HybridCommunicateGroup
