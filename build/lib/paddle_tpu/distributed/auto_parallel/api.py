"""Auto-parallel (semi-auto) API.

Reference analog: dist.shard_tensor / reshard / shard_layer /
shard_optimizer / to_static
(/root/reference/python/paddle/distributed/auto_parallel/api.py:131,579,678,
1353,2345) over DistTensor + per-op SPMD rules + reshard functions.

TPU-native collapse: a DistTensor is a jax.Array with a NamedSharding; SPMD
rule propagation, reshard planning, and collective insertion are XLA GSPMD's
job. shard_tensor = device_put with a NamedSharding; reshard = device_put to
a new sharding (XLA emits the collective); inside jit, sharding constraints
via lax.with_sharding_constraint. This one file replaces the reference's
SPMD-rule library (phi/infermeta/spmd_rules/) + reshard funcs
(auto_parallel/reshard/) because the compiler owns propagation.
"""
from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from ...core.tensor import Parameter, Tensor
from .placement import Partial, Placement, Replicate, Shard
from .process_mesh import ProcessMesh

__all__ = ["shard_tensor", "reshard", "shard_layer", "shard_optimizer",
           "to_static", "dtensor_from_fn", "unshard_dtensor",
           "placements_to_spec", "DistAttr"]


class DistAttr:
    def __init__(self, mesh, placements):
        self.process_mesh = mesh
        self.placements = placements


def placements_to_spec(mesh: ProcessMesh,
                       placements: List[Placement]) -> PartitionSpec:
    """[Shard(0), Replicate()] over mesh dims -> PartitionSpec.
    placements are per-MESH-dim (reference convention); the produced spec is
    per-TENSOR-dim."""
    # tensor_dim -> list of mesh axis names sharding it
    dim_axes = {}
    for mesh_dim, placement in enumerate(placements):
        if isinstance(placement, Shard):
            dim_axes.setdefault(placement.get_dim(), []).append(
                mesh.dim_names[mesh_dim])
    if not dim_axes:
        return PartitionSpec()
    max_dim = max(dim_axes) + 1
    entries = []
    for d in range(max_dim):
        axes = dim_axes.get(d)
        if not axes:
            entries.append(None)
        elif len(axes) == 1:
            entries.append(axes[0])
        else:
            entries.append(tuple(axes))
    return PartitionSpec(*entries)


def _named_sharding(mesh: ProcessMesh, placements) -> NamedSharding:
    return NamedSharding(mesh.to_jax_mesh(),
                         placements_to_spec(mesh, placements))


class _DistMeta:
    __slots__ = ("process_mesh", "placements")

    def __init__(self, mesh, placements):
        self.process_mesh = mesh
        self.placements = placements


def _attach(t: Tensor, mesh, placements):
    # stored on the tensor itself (dedicated slot) — an id-keyed side table
    # would serve stale placements once ids are recycled by the allocator
    t._dist_attr = _DistMeta(mesh, placements)
    return t


def get_dist_meta(t: Tensor) -> Optional[_DistMeta]:
    return getattr(t, "_dist_attr", None)


def shard_tensor(data, mesh: ProcessMesh, placements, dtype=None,
                 place=None, stop_gradient=None):
    """Materialize `data` as a sharded global jax.Array on `mesh`."""
    t = data if isinstance(data, Tensor) else Tensor(data, dtype=dtype)
    sharding = _named_sharding(mesh, placements)
    if isinstance(t._value, jax.core.Tracer):
        arr = jax.lax.with_sharding_constraint(t._value, sharding)
    else:
        arr = jax.device_put(t._value, sharding)
    if isinstance(t, Parameter):
        t._value = arr
        out = t
    else:
        out = Tensor(arr, stop_gradient=t.stop_gradient
                     if stop_gradient is None else stop_gradient)
    _attach(out, mesh, list(placements))
    return out


def reshard(dist_tensor: Tensor, mesh: ProcessMesh, placements):
    """Change placements; XLA emits the collective that realizes the move
    (the C++ reshard-function library collapses to this one call)."""
    sharding = _named_sharding(mesh, placements)
    if isinstance(dist_tensor._value, jax.core.Tracer):
        arr = jax.lax.with_sharding_constraint(dist_tensor._value, sharding)
    else:
        # handle Partial -> materialize reduction first (XLA handles inside
        # jit; eagerly a Partial never escapes our APIs)
        arr = jax.device_put(dist_tensor._value, sharding)
    out = Tensor(arr, stop_gradient=dist_tensor.stop_gradient)
    _attach(out, mesh, list(placements))
    return out


def dtensor_from_fn(fn, mesh: ProcessMesh, placements, *args, **kwargs):
    t = fn(*args, **kwargs)
    return shard_tensor(t, mesh, placements)


def unshard_dtensor(dist_tensor: Tensor) -> Tensor:
    arr = dist_tensor._value
    if not isinstance(arr, jax.core.Tracer):
        devs = jax.devices()
        arr = jax.device_put(
            jax.device_get(arr), devs[0])
    return Tensor(arr, stop_gradient=dist_tensor.stop_gradient)


def shard_layer(layer, process_mesh: ProcessMesh, shard_fn=None,
                input_fn=None, output_fn=None):
    """reference api.py:678. Default: replicate all params on the mesh."""
    if shard_fn is None:
        def shard_fn(name, lyr, mesh):
            for pname, p in lyr._parameters.items():
                if p is not None:
                    shard_tensor(p, mesh,
                                 [Replicate()] * len(mesh.shape))
    for name, sub in layer.named_sublayers(include_self=True):
        shard_fn(name, sub, process_mesh)
    if input_fn is not None:
        layer.register_forward_pre_hook(
            lambda lyr, inputs: input_fn(inputs, process_mesh))
    if output_fn is not None:
        layer.register_forward_post_hook(
            lambda lyr, inputs, outputs: output_fn(outputs, process_mesh))
    return layer


def shard_optimizer(optimizer, shard_fn=None):
    """reference api.py:1353 — optimizer states inherit parameter shardings
    automatically (states are created jnp.zeros_like(param) inside the jitted
    step, so GSPMD places them with the param); shard_fn can override."""
    optimizer._shard_fn = shard_fn
    return optimizer


def to_static(layer, loader=None, loss=None, optimizer=None, strategy=None,
              input_spec=None, mesh=None):
    """reference api.py:2345 — compile `layer` for auto-parallel execution.
    Backed by the static Engine (static_engine.py): placement completion,
    GSPMD partitioning, donated whole-step executable, XLA cost model.

    NOTE (static-graph semantics, same as the reference DistModel): the
    engine owns the training state after this call; the eager `layer`'s
    weights are a snapshot. Call .state_dict() to sync trained weights
    back to the layer."""
    from .static_engine import Engine

    engine = Engine(layer, loss=loss, optimizer=optimizer, strategy=strategy)
    if mesh is not None or optimizer is not None or loss is not None:
        engine.prepare(mesh=mesh)

    class DistModel:
        def __init__(self):
            self.network = layer
            self.engine = engine
            self._mode = "train"

        def train(self):
            self._mode = "train"
            layer.train()

        def eval(self):
            self._mode = "eval"
            layer.eval()

        def __call__(self, *args):
            if self._mode == "train" and optimizer is not None:
                return engine.run_step(*args)
            if loss is not None:
                # loss-only (no optimizer) or eval mode: forward + loss
                return engine.run_eval_step(*args)
            outs = engine.predict([tuple(args)])
            return jax.tree_util.tree_map(Tensor, outs[0])

        def state_dict(self, mode="all"):
            return engine.state_dict(mode)

        def dist_main_program(self, mode="train", *sample_batch):
            if not sample_batch:
                return None
            return engine.dist_main_program(mode, *sample_batch)

    return DistModel()
