"""Auto-parallel static Engine.

Reference analog: python/paddle/distributed/auto_parallel/static/engine.py:68
(`Engine`, fit at :1213) with its completion pass (static/completion.py),
partitioner (static/partitioner.py), reshard (static/reshard.py) and cost
model (static/cost/).

TPU-native redesign — the four reference stages collapse onto the XLA
compilation pipeline:

- **completion**: user placements (dist.shard_tensor / shard_layer) are
  collected per parameter; every unannotated tensor is *completed* by GSPMD
  sharding propagation at compile time. Materialized here as: annotated
  params keep their NamedSharding, unannotated params enter replicated, and
  XLA propagates through every op (the reference walks ops forward/backward
  applying SPMD rules — phi/infermeta/spmd_rules — to do the same thing).
- **partitioner**: GSPMD partitions the traced whole-step program over the
  mesh; per-rank programs never exist as Python objects (SPMD, one program).
- **reshard**: XLA inserts collectives where producer/consumer shardings
  disagree.
- **cost model**: the compiled executable's own `cost_analysis()` /
  `memory_analysis()` — measured from the real HLO rather than estimated
  from an op-cost table — surfaced via `Engine.cost_analysis()` for the
  auto-tuner.

The whole training step (forward + backward + optimizer) is ONE donated XLA
executable per mode, the same primitive the flagship HybridTrainer uses.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ...core.tensor import Tensor
from ...framework.random import next_key, rng_guard
from ...jit import functional as FB
from .api import get_dist_meta
from .process_mesh import ProcessMesh

__all__ = ["Engine", "Strategy"]


class Strategy:
    """reference: dist.Strategy (auto_parallel/strategy.py). Knobs that
    change numerics/placement are honored; pass-selection knobs the XLA
    pipeline owns are accepted for compatibility."""

    def __init__(self):
        self.amp = _Cfg(enable=False, dtype="bfloat16", level="O1")
        self.sharding = _Cfg(enable=False, stage=1, degree=1)
        self.pipeline = _Cfg(enable=False, schedule_mode="1F1B",
                             micro_batch_size=1, accumulate_steps=1)
        self.gradient_merge = _Cfg(enable=False, k_steps=1)


class _Cfg:
    def __init__(self, **kw):
        self.__dict__.update(kw)


def _first_axis(mesh: jax.sharding.Mesh) -> Optional[str]:
    names = list(mesh.axis_names)
    return names[0] if names else None


class Engine:
    """Compile-and-run harness: arbitrary Layer + mesh placements ->
    one donated SPMD training executable, no model-specific trainer code.

    Usage (mirrors reference Engine):
        engine = Engine(model, loss, optimizer)
        engine.prepare(mesh=pm)                  # or inferred from params
        engine.fit(loader, epochs=1)             # or engine.run_step(x, y)
    """

    def __init__(self, model, loss=None, optimizer=None, metrics=None,
                 cluster=None, strategy: Optional[Strategy] = None):
        self.model = model
        self.loss = loss
        self.optimizer = optimizer
        self.metrics = metrics
        self.strategy = strategy or Strategy()
        self._mesh: Optional[jax.sharding.Mesh] = None
        self._params: Optional[Dict[str, jax.Array]] = None
        self._opt_states: Optional[Dict[str, Dict[str, jax.Array]]] = None
        self._buffers: Optional[Dict[str, jax.Array]] = None
        self._train_step = None
        self._eval_step = None
        self._pred_step = None
        self._lowered = {}
        self._compiled_cache = {}
        self.history: List[float] = []

    # -- completion --------------------------------------------------------
    def _param_sharding(self, param) -> NamedSharding:
        meta = get_dist_meta(param)
        if meta is not None:
            from .api import placements_to_spec

            return NamedSharding(meta.process_mesh.to_jax_mesh(),
                                 placements_to_spec(meta.process_mesh,
                                                    meta.placements))
        sh = getattr(param._value, "sharding", None)
        if isinstance(sh, NamedSharding) and sh.mesh == self._mesh:
            return sh
        # completion fallback: replicate; GSPMD propagates the annotated
        # neighbors through the program
        return NamedSharding(self._mesh, PartitionSpec())

    def prepare(self, inputs_spec=None, labels_spec=None, mode: str = "train",
                mesh: Optional[ProcessMesh] = None):
        """Collect placements (completion inputs) and stage params/opt
        states onto the mesh. Reference Engine.prepare."""
        if mesh is not None:
            self._mesh = mesh.to_jax_mesh() \
                if isinstance(mesh, ProcessMesh) else mesh
        else:
            for _, p in self.model.named_parameters():
                meta = get_dist_meta(p)
                if meta is not None:
                    self._mesh = meta.process_mesh.to_jax_mesh()
                    break
            if self._mesh is None:
                from ..topology import get_mesh

                self._mesh = get_mesh()
        if self._mesh is None:
            dev = jax.devices()
            self._mesh = jax.sharding.Mesh(np.asarray(dev), ("dp",))

        def stage(v, sh):
            # device_put with the array's existing sharding aliases the
            # input buffer; the engine donates its buffers each step, which
            # would delete the eager model's own arrays — always copy
            return jax.device_put(jnp.array(v, copy=True), sh)

        params = FB.current_params(self.model)
        name_to_param = dict(self.model.named_parameters())
        self._params = {
            k: stage(v, self._param_sharding(name_to_param[k]))
            for k, v in params.items()
        }
        repl = NamedSharding(self._mesh, PartitionSpec())
        self._buffers = {
            k: stage(v, repl)
            for k, v in FB.current_buffers(self.model).items()
        }
        if self.optimizer is not None:
            self._opt_states = {}
            for k, p in name_to_param.items():
                st = self.optimizer._get_state(p)
                sh = self._params[k].sharding
                pshape = tuple(self._params[k].shape)
                self._opt_states[k] = {
                    sk: stage(jnp.asarray(sv), sh)
                    if tuple(np.shape(sv)) == pshape
                    else jnp.array(sv, copy=True)
                    for sk, sv in st.items()
                }
        return self

    # -- step builders -----------------------------------------------------
    def _data_sharding(self, arr) -> NamedSharding:
        ax = _first_axis(self._mesh)
        nd = getattr(arr, "ndim", 0)
        if ax is None or nd == 0 or self._mesh.shape[ax] == 1 \
                or arr.shape[0] % self._mesh.shape[ax] != 0:
            return NamedSharding(self._mesh, PartitionSpec())
        return NamedSharding(self._mesh,
                             PartitionSpec(ax, *([None] * (nd - 1))))

    def _build_train(self):
        from ...jit.api import build_train_step

        amp = self.strategy.amp
        amp_dtype = None
        if amp.enable:
            amp_dtype = jnp.bfloat16 if amp.dtype == "bfloat16" \
                else jnp.float16
        return build_train_step(self.model, self.loss, self.optimizer,
                                train=True, amp_dtype=amp_dtype)

    def _build_eval(self, with_loss: bool):
        model, loss_fn = self.model, self.loss

        def step(params, buffers, seed, *batch):
            with rng_guard(seed):
                out, _ = FB.call_functional(
                    model, params, buffers,
                    batch[:-1] if (loss_fn and with_loss) else batch,
                    train=False)
            if loss_fn is not None and with_loss:
                from ...core.autograd import no_grad

                with no_grad():
                    out_t = jax.tree.map(lambda x: Tensor(x), out)
                    return loss_fn(out_t, Tensor(batch[-1]))._value
            return out

        return jax.jit(step)

    # -- execution ---------------------------------------------------------
    def _ensure_prepared(self):
        if self._params is None:
            self.prepare()

    def _stage_batch(self, batch) -> List[jax.Array]:
        arrays = []
        for b in batch:
            a = b._value if isinstance(b, Tensor) else jnp.asarray(b)
            arrays.append(jax.device_put(a, self._data_sharding(a)))
        return arrays

    def run_step(self, *batch) -> Tensor:
        """One compiled train step (params/opt-state live on the mesh and
        are donated; write back to the eager model via state_dict/save).
        LR schedulers follow the eager convention: the caller steps them
        (fit() does it for you)."""
        self._ensure_prepared()
        if self._train_step is None:
            self._train_step = self._build_train()
        arrays = self._stage_batch(batch)
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        self.optimizer._step_count += 1
        step_i = jnp.asarray(self.optimizer._step_count, jnp.float32)
        self._params, self._opt_states, self._buffers, loss = \
            self._train_step(self._params, self._opt_states, self._buffers,
                             lr, step_i, next_key(), *arrays)
        return Tensor(loss)

    def fit(self, train_data, epochs: int = 1, steps_per_epoch=None,
            valid_data=None, log_freq: int = 10, verbose: int = 1):
        """reference Engine.fit (engine.py:1213)."""
        self._ensure_prepared()
        for epoch in range(epochs):
            for i, batch in enumerate(train_data):
                if steps_per_epoch is not None and i >= steps_per_epoch:
                    break
                batch = batch if isinstance(batch, (tuple, list)) else \
                    (batch,)
                loss = self.run_step(*batch)
                lr_sched = getattr(self.optimizer, "_learning_rate", None)
                if hasattr(lr_sched, "step"):
                    lr_sched.step()
                self.history.append(float(np.asarray(loss._value)))
                if verbose and i % log_freq == 0:
                    print(f"[auto_parallel.Engine] epoch {epoch} "
                          f"step {i} loss {self.history[-1]:.5f}")
            if valid_data is not None:
                self.evaluate(valid_data, verbose=verbose)
        return self.history

    def run_eval_step(self, *batch) -> Tensor:
        """One compiled forward(+loss when a loss_fn is set) step."""
        self._ensure_prepared()
        if self._eval_step is None:
            self._eval_step = self._build_eval(
                with_loss=self.loss is not None)
        out = self._eval_step(self._params, self._buffers, next_key(),
                              *self._stage_batch(batch))
        return jax.tree_util.tree_map(Tensor, out) \
            if self.loss is None else Tensor(out)

    def evaluate(self, eval_data, steps=None, verbose: int = 0):
        if self.loss is None:
            raise ValueError("Engine.evaluate requires a loss function; "
                             "use predict() for raw outputs")
        self._ensure_prepared()
        losses = []
        for i, batch in enumerate(eval_data):
            if steps is not None and i >= steps:
                break
            batch = batch if isinstance(batch, (tuple, list)) else (batch,)
            loss = self.run_eval_step(*batch)
            losses.append(float(np.asarray(loss._value)))
        mean = float(np.mean(losses)) if losses else float("nan")
        if verbose:
            print(f"[auto_parallel.Engine] eval loss {mean:.5f}")
        return {"loss": mean}

    def predict(self, test_data, steps=None):
        self._ensure_prepared()
        if self._pred_step is None:
            self._pred_step = self._build_eval(with_loss=False)
        outs = []
        for i, batch in enumerate(test_data):
            if steps is not None and i >= steps:
                break
            batch = batch if isinstance(batch, (tuple, list)) else (batch,)
            out = self._pred_step(self._params, self._buffers, next_key(),
                                  *self._stage_batch(batch))
            outs.append(jax.tree.map(lambda x: np.asarray(x), out))
        return outs

    # -- program/cost surface ---------------------------------------------
    def _lower(self, mode: str, *batch):
        """Lower the requested mode's step; results cached by batch
        shape/dtype (self._lowered)."""
        self._ensure_prepared()
        arrays = self._stage_batch(batch)
        key = (mode,) + tuple((tuple(a.shape), str(a.dtype))
                              for a in arrays)
        if key in self._lowered:
            return self._lowered[key]
        if mode == "train" and self.optimizer is not None:
            if self._train_step is None:
                self._train_step = self._build_train()
            lr = jnp.asarray(0.001, jnp.float32)
            si = jnp.asarray(1.0, jnp.float32)
            low = self._train_step.lower(
                self._params, self._opt_states, self._buffers, lr, si,
                next_key(), *arrays)
        else:
            with_loss = mode != "predict" and self.loss is not None
            step = self._build_eval(with_loss=with_loss)
            low = step.lower(self._params, self._buffers, next_key(),
                             *arrays)
        self._lowered[key] = low
        return low

    def dist_main_program(self, mode: str = "train", *batch) -> str:
        """The inspectable partitioned program (reference returns the
        completed+partitioned ProgramDesc; here: StableHLO text)."""
        if not batch:
            raise ValueError("pass a sample batch to lower the program")
        return self._lower(mode, *batch).as_text()

    def cost_analysis(self, *batch, mode: str = "train") -> Dict[str, Any]:
        """Measured cost/memory of the compiled step, for the auto-tuner
        (reference static/cost/ estimates these from op tables)."""
        key = ("c", mode) + tuple(
            (tuple(np.shape(a)), str(getattr(a, "dtype", type(a))))
            for a in ((b._value if isinstance(b, Tensor) else b)
                      for b in batch))
        if key in self._compiled_cache:
            compiled = self._compiled_cache[key]
        else:
            compiled = self._lower(mode, *batch).compile()
            self._compiled_cache[key] = compiled
        cost = compiled.cost_analysis() or {}
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        out = {"flops": float(cost.get("flops", 0.0)),
               "bytes_accessed": float(cost.get("bytes accessed", 0.0))}
        try:
            mem = compiled.memory_analysis()
            out["peak_memory_bytes"] = int(
                getattr(mem, "temp_size_in_bytes", 0)
                + getattr(mem, "argument_size_in_bytes", 0)
                + getattr(mem, "output_size_in_bytes", 0))
        except Exception:
            out["peak_memory_bytes"] = 0
        return out

    # -- state -------------------------------------------------------------
    def state_dict(self, mode: str = "all") -> Dict[str, Tensor]:
        """Sync the engine's (donation-owned) state back into the eager
        model and return its state dict. COPIES are written back — the
        engine keeps donating its own buffers, so aliasing them into the
        model would leave the model holding deleted arrays after the next
        run_step."""
        self._ensure_prepared()
        FB.write_back(
            self.model,
            {k: jnp.array(v, copy=True) for k, v in self._params.items()},
            {k: jnp.array(v, copy=True) for k, v in self._buffers.items()})
        name_to_param = dict(self.model.named_parameters())
        for k, st in (self._opt_states or {}).items():
            p = name_to_param.get(k)
            if p is not None:
                self.optimizer._accumulators[id(p)] = {
                    sk: jnp.array(sv, copy=True) for sk, sv in st.items()}
        return self.model.state_dict()

    def save(self, path: str, training: bool = True):
        from ...framework.io import save as fsave

        blob = {"state_dict": {
            k: np.asarray(v._value if isinstance(v, Tensor) else v)
            for k, v in self.state_dict().items()}}
        if training and self._opt_states is not None:
            # training-resumable checkpoint carries the optimizer moments
            # (reference Engine.save(training=True))
            blob["opt_states"] = {
                k: {sk: np.asarray(sv) for sk, sv in st.items()}
                for k, st in self._opt_states.items()}
            blob["opt_step_count"] = int(self.optimizer._step_count)
        fsave(blob, path + ".pdparams")

    def load(self, path: str):
        from ...framework.io import load as fload

        data = fload(path + ".pdparams")
        self.model.set_state_dict(data["state_dict"])
        if self._params is not None or self.optimizer is not None:
            # re-stage now so a checkpointed optimizer state can be
            # restored below (loading before prepare() must not silently
            # drop the moments)
            self.prepare()
        if "opt_states" in data and self._opt_states is not None:
            for k, st in data["opt_states"].items():
                if k in self._opt_states:
                    sh = self._params[k].sharding
                    self._opt_states[k] = {
                        sk: jax.device_put(jnp.asarray(sv), sh)
                        if tuple(np.shape(sv)) == tuple(
                            self._params[k].shape)
                        else jnp.asarray(sv)
                        for sk, sv in st.items()}
            self.optimizer._step_count = int(
                data.get("opt_step_count", self.optimizer._step_count))
