"""ProcessMesh (reference: python/paddle/distributed/auto_parallel/
process_mesh.py + phi process_mesh.h). Thin, hashable wrapper that resolves
to a jax.sharding.Mesh over the job's devices."""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh

__all__ = ["ProcessMesh"]


class ProcessMesh:
    def __init__(self, mesh: Sequence, dim_names: Optional[List[str]] = None,
                 process_ids=None):
        self._mesh_arr = np.asarray(mesh)
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(self._mesh_arr.ndim)]
        self._dim_names = list(dim_names)
        self._jax_mesh = None

    @property
    def shape(self):
        return list(self._mesh_arr.shape)

    @property
    def ndim(self):
        return self._mesh_arr.ndim

    @property
    def dim_names(self):
        return self._dim_names

    @property
    def process_ids(self):
        return self._mesh_arr.reshape(-1).tolist()

    @property
    def mesh(self):
        return self._mesh_arr

    def get_dim_size(self, dim_name):
        return self._mesh_arr.shape[self._dim_names.index(dim_name)]

    def get_rank_by_dim_and_process_id(self, dim_name, process_id):
        coord = np.argwhere(self._mesh_arr == process_id)
        if coord.size == 0:
            return -1
        return int(coord[0][self._dim_names.index(dim_name)])

    def to_jax_mesh(self) -> Mesh:
        if self._jax_mesh is None:
            devices = jax.devices()
            ids = self._mesh_arr.reshape(-1)
            dev_arr = np.asarray(
                [devices[int(i) % len(devices)] for i in ids]
            ).reshape(self._mesh_arr.shape)
            self._jax_mesh = Mesh(dev_arr, tuple(self._dim_names))
        return self._jax_mesh

    def __eq__(self, other):
        return (isinstance(other, ProcessMesh)
                and np.array_equal(self._mesh_arr, other._mesh_arr)
                and self._dim_names == other._dim_names)

    def __hash__(self):
        return hash((self._mesh_arr.tobytes(), tuple(self._dim_names)))

    def __repr__(self):
        return f"ProcessMesh(shape={self.shape}, dims={self._dim_names})"
