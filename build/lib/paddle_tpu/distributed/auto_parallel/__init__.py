from . import api
from .api import (dtensor_from_fn, reshard, shard_layer, shard_optimizer,
                  shard_tensor, to_static, unshard_dtensor)
from .placement import Partial, Placement, Replicate, Shard
from .process_mesh import ProcessMesh
from .static_engine import Engine, Strategy
