"""Process/topology environment.

Reference analog: role-maker env contract (PADDLE_TRAINER_ID /
PADDLE_TRAINER_ENDPOINTS, fleet/base/role_maker.py) + ParallelEnv
(python/paddle/distributed/parallel.py).

TPU-native execution model: JAX is single-controller-per-host SPMD. A
"rank" is a host process (jax.process_index()); each process drives several
local TPU chips, and collectives are XLA ops over the global device mesh.
Multi-host rendezvous uses the JAX coordination service (the TCPStore
analog), initialized from the same env contract the reference launcher sets.
"""
from __future__ import annotations

import os

import jax

_initialized = False


def _env_int(name, default=0):
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def init_parallel_env():
    """reference: paddle.distributed.init_parallel_env. Brings up the JAX
    distributed runtime when launched multi-process (coordinator address from
    the launcher env), no-op single-process."""
    global _initialized
    if _initialized:
        return ParallelEnv()
    n_procs = _env_int("PADDLE_TRAINERS_NUM", 1)
    endpoints = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
    rank = _env_int("PADDLE_TRAINER_ID", 0)
    use_jax_dist = os.environ.get("PADDLE_JAX_DISTRIBUTED", "1") != "0"
    if n_procs > 1 and endpoints and use_jax_dist:
        coordinator = endpoints.split(",")[0]
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=n_procs,
                process_id=rank,
            )
        except Exception as e:  # already initialized or single-node sim
            if "already" not in str(e).lower():
                raise
    if n_procs > 1:
        # Eager cross-process tensor path (ProcessGroupGloo analog); the
        # in-graph XLA collectives stay the hot path.
        from .transport import init_transport

        init_transport(rank, n_procs)
    _initialized = True
    return ParallelEnv()


def is_initialized():
    return _initialized


def get_rank(group=None):
    if group is not None:
        return group.get_group_rank(global_rank())
    return global_rank()


def global_rank():
    env_n = _env_int("PADDLE_TRAINERS_NUM", 1)
    try:
        # When jax.distributed is up it is authoritative; when the job is
        # multi-process but only the TCP transport is live (CPU sim, tests),
        # jax reports a world of 1 — trust the launcher env instead.
        if jax.process_count() >= env_n:
            return jax.process_index()
    except Exception:
        pass
    return _env_int("PADDLE_TRAINER_ID", 0)


def get_world_size(group=None):
    if group is not None:
        return group.nranks
    env_n = _env_int("PADDLE_TRAINERS_NUM", 1)
    try:
        return max(jax.process_count(), env_n)
    except Exception:
        return env_n


def device_world_size():
    """Total number of chips in the job (the SPMD 'world' the mesh spans)."""
    try:
        return len(jax.devices())
    except Exception:
        return 1


class ParallelEnv:
    def __init__(self):
        self.rank = global_rank()
        self.world_size = get_world_size()
        self.device_id = 0
        self.current_endpoint = os.environ.get("PADDLE_CURRENT_ENDPOINT", "")
        self.trainer_endpoints = [
            e for e in os.environ.get(
                "PADDLE_TRAINER_ENDPOINTS", "").split(",") if e
        ]

    @property
    def local_rank(self):
        return self.rank

    @property
    def nranks(self):
        return self.world_size

    @property
    def dev_id(self):
        return self.device_id
