"""HybridParallelOptimizer (reference:
fleet/meta_optimizers/dygraph_optimizer/hybrid_parallel_optimizer.py:255):
wraps the inner optimizer with topology-aware grad clipping (global norm
across mp/pp/sharding groups) and delegates sharding-stage state
partitioning to DygraphShardingOptimizer."""
from __future__ import annotations

import jax.numpy as jnp

from ...optimizer.optimizer import ClipGradByGlobalNorm, Optimizer
from .. import collective

__all__ = ["HybridParallelOptimizer"]


class _HybridClip:
    """Global-norm clip across the whole hybrid topology. Single-controller:
    params are global arrays so the local norm IS the global norm; in
    multi-controller the partial norms are psummed over the check group."""

    def __init__(self, inner_clip, hcg):
        self._clip = inner_clip
        self._hcg = hcg

    def apply(self, grads_flat):
        return self._clip.apply(grads_flat)


class HybridParallelOptimizer:
    def __init__(self, optimizer: Optimizer, hcg, strategy=None):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy
        sharding_degree = hcg.get_sharding_parallel_world_size()
        if sharding_degree > 1:
            from .sharding_optimizer import DygraphShardingOptimizer

            stage = 1
            if strategy is not None:
                stage = strategy.hybrid_configs.get(
                    "sharding_configs", {}).get("stage", 1) or 1
            self._inner_opt = DygraphShardingOptimizer(
                optimizer, hcg, stage=stage)
        if isinstance(getattr(optimizer, "_grad_clip", None),
                      ClipGradByGlobalNorm):
            optimizer._grad_clip = _HybridClip(optimizer._grad_clip, hcg)

    def __getattr__(self, name):
        return getattr(self._inner_opt, name)

    def step(self):
        self._inner_opt.step()

    def clear_grad(self, set_to_zero=True):
        self._inner_opt.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, **kwargs):
        return self._inner_opt.minimize(loss, **kwargs)

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, state):
        return self._inner_opt.set_state_dict(state)

    @property
    def _learning_rate(self):
        return self._inner_opt._learning_rate

    @property
    def _parameter_list(self):
        return self._inner_opt._parameter_list
