"""Pipeline schedule generators: FThenB, 1F1B, interleaved-VPP, zero-bubble.

Reference analogs:
- 1F1B + interleaved runtime schedules:
  fleet/meta_parallel/pipeline_parallel.py:459 (forward_backward_pipeline),
  :1010 (PipelineParallelWithInterleave)
- static-graph schedule passes (instruction-list builders per stage):
  distributed/passes/pipeline_scheduler_pass/ (FThenB, 1F1B, VPP,
  pipeline_zero_bubble.py ZB-H1)

Design: schedules are pure data — per-stage lists of instructions
``(kind, micro, chunk)`` with kind in {"F", "B", "W"}:

  F: forward of one micro-batch through one model chunk
  B: backward-for-inputs (dx) of that chunk          (ZB splits B/W;
  W: backward-for-weights (dw) of that chunk          classic schedules
                                                      fuse W into B)

A clock-driven simulator (`simulate`) validates cross-stage dependencies
(F needs the previous virtual stage's F of the same micro; B needs the
next virtual stage's B; W needs its own B) and measures makespan, from
which bubble ratios are computed — the property tests pin the textbook
bubble formulas. The same instruction streams drive the eager executors
in pipeline_parallel.py, mirroring how the reference's scheduler passes
feed its static interpreter.

Virtual-stage numbering: chunk c on stage s is global virtual stage
``gv = c * num_stages + s`` (Megatron/VPP convention; reference
pp_layers.py interleave segmentation).
"""
from __future__ import annotations

from typing import List, Tuple

__all__ = [
    "gen_fthenb", "gen_1f1b", "gen_interleave_1f1b", "gen_zero_bubble_h1",
    "simulate", "bubble_ratio",
]

Instr = Tuple[str, int, int]   # (kind, micro, chunk)


def gen_fthenb(stage: int, num_stages: int, num_micro: int) -> List[Instr]:
    """All forwards, then all backwards (reference FThenB pass)."""
    return ([("F", m, 0) for m in range(num_micro)]
            + [("B", m, 0) for m in range(num_micro)])


def gen_1f1b(stage: int, num_stages: int, num_micro: int) -> List[Instr]:
    """Classic 1F1B (reference forward_backward_pipeline :459): stage s
    runs (P-1-s) warmup forwards, then alternates F/B, then drains."""
    warmup = min(num_stages - 1 - stage, num_micro)
    sched: List[Instr] = [("F", m, 0) for m in range(warmup)]
    nf, nb = warmup, 0
    while nf < num_micro:
        sched.append(("F", nf, 0)); nf += 1
        sched.append(("B", nb, 0)); nb += 1
    while nb < num_micro:
        sched.append(("B", nb, 0)); nb += 1
    return sched


def gen_interleave_1f1b(stage: int, num_stages: int, num_micro: int,
                        num_chunks: int) -> List[Instr]:
    """Interleaved/VPP 1F1B (reference :1010; Megatron-style). Each stage
    owns `num_chunks` model chunks; micro-batches are issued in groups of
    P so chunk (c) of group g runs before chunk (c+1). Requires
    num_micro % num_stages == 0 (the reference asserts the same)."""
    p, v, m = num_stages, num_chunks, num_micro
    if v == 1:
        return gen_1f1b(stage, p, m)
    if m % p != 0:
        raise ValueError(
            f"interleaved schedule needs num_micro % num_stages == 0 "
            f"(got {m} % {p})")
    total = m * v
    group = p * v

    def f_micro_chunk(k):          # k-th forward on this stage
        g, r = divmod(k % (group), p)
        return (k // group) * p + r, g

    def b_micro_chunk(k):          # k-th backward on this stage
        g, r = divmod(k % (group), p)
        return (k // group) * p + r, v - 1 - g

    warmup = min((p - stage - 1) * 2 + (v - 1) * p, total)
    sched: List[Instr] = []
    nf = nb = 0
    for _ in range(warmup):
        mi, c = f_micro_chunk(nf); nf += 1
        sched.append(("F", mi, c))
    while nf < total:
        mi, c = f_micro_chunk(nf); nf += 1
        sched.append(("F", mi, c))
        mi, c = b_micro_chunk(nb); nb += 1
        sched.append(("B", mi, c))
    while nb < total:
        mi, c = b_micro_chunk(nb); nb += 1
        sched.append(("B", mi, c))
    return sched


def gen_zero_bubble_h1(stage: int, num_stages: int,
                       num_micro: int) -> List[Instr]:
    """ZB-H1 (reference pipeline_zero_bubble.py): backward is split into
    B (input grads, on the critical path) and W (weight grads, fillable).
    Built by greedy list-scheduling with priority B > F > W under the
    1F1B warmup structure — W instructions slot into what would otherwise
    be bubbles, and the drain phase becomes B...B W...W."""
    scheds = _zb_h1_all_stages(num_stages, num_micro)
    return scheds[stage]


def _zb_h1_all_stages(p: int, m: int) -> List[List[Instr]]:
    # global greedy simulation, one tick per op (F=B=W=1 as in ZB-H1)
    warmup = [min(p - s, m) for s in range(p)]   # one extra vs 1F1B
    f_done = [[None] * m for _ in range(p)]      # completion ticks
    b_done = [[None] * m for _ in range(p)]
    nf = [0] * p
    nb = [0] * p
    nw = [0] * p
    out: List[List[Instr]] = [[] for _ in range(p)]
    t = 0
    while any(nw[s] < m for s in range(p)):
        progressed = False
        for s in range(p):
            # B ready: own F done, downstream B done (strictly before t)
            can_b = (nb[s] < nf[s]
                     and f_done[s][nb[s]] is not None
                     and f_done[s][nb[s]] <= t
                     and (s == p - 1
                          or (b_done[s + 1][nb[s]] is not None
                              and b_done[s + 1][nb[s]] <= t)))
            # F ready: upstream F done; hold 1F1B-style pacing after warmup
            can_f = (nf[s] < m
                     and (s == 0 or (f_done[s - 1][nf[s]] is not None
                                     and f_done[s - 1][nf[s]] <= t))
                     and (nf[s] < warmup[s] or nb[s] + warmup[s] > nf[s]
                          or can_b is False))
            if can_b:
                out[s].append(("B", nb[s], 0))
                b_done[s][nb[s]] = t + 1
                nb[s] += 1
                progressed = True
            elif can_f:
                out[s].append(("F", nf[s], 0))
                f_done[s][nf[s]] = t + 1
                nf[s] += 1
                progressed = True
            elif nw[s] < nb[s]:
                out[s].append(("W", nw[s], 0))
                nw[s] += 1
                progressed = True
        t += 1
        if not progressed and t > 10 * (2 * m + 2 * p) + 100:
            raise RuntimeError("zero-bubble scheduler wedged")
    return out


# ---------------------------------------------------------------------------
# validation / simulation
# ---------------------------------------------------------------------------

def simulate(scheds: List[List[Instr]], num_stages: int, num_micro: int,
             num_chunks: int = 1) -> int:
    """Clock-simulate per-stage instruction streams; raise on any
    dependency violation or deadlock; return the makespan in ticks
    (each instruction costs 1 tick; stages run concurrently).

    Dependencies enforced:
      F(m, gv)  needs F(m, gv-1)                  [gv = c*P + s]
      B(m, gv)  needs F(m, gv) and B(m, gv+1)
      W(m, gv)  needs B(m, gv)
    """
    p, v = num_stages, num_chunks
    q = p * v
    f_done = {}
    b_done = {}
    ptr = [0] * p
    clock = [0] * p
    pending = sum(len(s) for s in scheds)
    while pending:
        progressed = False
        for s in range(p):
            if ptr[s] >= len(scheds[s]):
                continue
            kind, mi, c = scheds[s][ptr[s]]
            gv = c * p + s
            t = clock[s]
            if kind == "F":
                dep = 0 if gv == 0 else f_done.get((mi, gv - 1))
                if dep is None or dep > t:
                    continue
                f_done[(mi, gv)] = t + 1
            elif kind == "B":
                own = f_done.get((mi, gv))
                dn = 0 if gv == q - 1 else b_done.get((mi, gv + 1))
                if own is None or own > t or dn is None or dn > t:
                    continue
                b_done[(mi, gv)] = t + 1
            else:  # W
                own = b_done.get((mi, gv))
                if own is None or own > t:
                    continue
            ptr[s] += 1
            clock[s] = t + 1
            pending -= 1
            progressed = True
        if not progressed:
            # all stages blocked: advance blocked stages' clocks to the
            # earliest dependency-completion (idle/bubble time)
            nxt = None
            for s in range(p):
                if ptr[s] >= len(scheds[s]):
                    continue
                kind, mi, c = scheds[s][ptr[s]]
                gv = c * p + s
                need = []
                if kind == "F" and gv > 0:
                    need.append(f_done.get((mi, gv - 1)))
                elif kind == "B":
                    need.append(f_done.get((mi, gv)))
                    if gv < q - 1:
                        need.append(b_done.get((mi, gv + 1)))
                elif kind == "W":
                    need.append(b_done.get((mi, gv)))
                if any(n is None for n in need):
                    continue   # producer not even scheduled yet this pass
                t_ready = max([0] + [n for n in need if n is not None])
                if t_ready > clock[s]:
                    nxt = t_ready if nxt is None else min(nxt, t_ready)
            if nxt is None:
                raise RuntimeError(
                    f"pipeline schedule deadlock: ptr={ptr}")
            for s in range(p):
                if ptr[s] < len(scheds[s]) and clock[s] < nxt:
                    clock[s] = nxt
    return max(clock)


def bubble_ratio(makespan: int, num_stages: int, num_micro: int,
                 num_chunks: int = 1, has_w: bool = False) -> float:
    """Fraction of stage-time idle: (makespan - work_per_stage)/makespan."""
    per_stage = num_micro * num_chunks * (3 if has_w else 2)
    return (makespan - per_stage) / makespan
