from .engines import (MetaParallelBase, SegmentParallel, ShardingParallel,
                      TensorParallel)
from .hybrid_optimizer import HybridParallelOptimizer
from .mp_layers import (ColumnParallelLinear, ParallelCrossEntropy,
                        RowParallelLinear, VocabParallelEmbedding)
from . import pipeline_schedules
from .pipeline_parallel import (PipelineParallel,
                                PipelineParallelWithInterleave,
                                PipelineParallelZeroBubble, spmd_pipeline,
                                spmd_pipeline_interleaved)
from .pp_layers import LayerDesc, PipelineLayer, SharedLayerDesc
from .sharding_optimizer import (DygraphShardingOptimizer,
                                 DygraphShardingOptimizerV2,
                                 GroupShardedOptimizerStage2,
                                 GroupShardedStage2, GroupShardedStage3,
                                 group_sharded_parallel)
