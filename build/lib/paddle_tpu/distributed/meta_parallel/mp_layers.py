"""Tensor-parallel layers.

Reference analog: VocabParallelEmbedding / ColumnParallelLinear /
RowParallelLinear / ParallelCrossEntropy
(/root/reference/python/paddle/distributed/fleet/layers/mpu/mp_layers.py:47,
334,541,742) — Megatron-style layers with hand-written NCCL
allreduce/allgather in forward/backward.

TPU-native redesign (GSPMD): parameters are FULL-logical-shape global
jax.Arrays sharded over the 'mp' mesh axis (column layers shard the output
dim, row layers the input dim, vocab embedding the vocab dim). Forward is a
plain matmul/gather; XLA's SPMD partitioner inserts and overlaps the
collectives the reference codes by hand. User scripts keep full shapes —
no per-rank slicing — which is exactly how the reference's semi-auto path
behaves, with zero Python collective code in the hot path.

Inside shard_map regions (the explicit-collective expert path), the same
layers lower to lax.psum on the 'mp' axis via the mp group's axis name.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from ...core.tensor import Parameter, Tensor
from ... import nn
from ...nn import functional as F
from .. import collective
from ..topology import get_hybrid_communicate_group, get_mesh

__all__ = ["VocabParallelEmbedding", "ColumnParallelLinear",
           "RowParallelLinear", "ParallelCrossEntropy"]


def _mp_info():
    hcg = get_hybrid_communicate_group()
    if hcg is None:
        return 1, None, None
    return (hcg.get_model_parallel_world_size(),
            hcg.get_model_parallel_group(), get_mesh())


def _shard_param(param, spec_entries):
    """Attach a NamedSharding over the global mesh to a parameter."""
    mesh = get_mesh()
    if mesh is None or isinstance(param._value, jax.core.Tracer):
        return param
    spec = PartitionSpec(*spec_entries)
    try:
        param._value = jax.device_put(
            param._value, NamedSharding(mesh, spec))
        param.split_axis = next(
            (i for i, e in enumerate(spec_entries) if e is not None), None)
        param.is_distributed = True
    except Exception:
        pass
    return param


class VocabParallelEmbedding(nn.Layer):
    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        world, group, mesh = _mp_info()
        self.world_size = world
        self.mp_group = mp_group or group
        from ...nn.initializer import XavierNormal

        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=XavierNormal())
        if world > 1:
            _shard_param(self.weight, ["mp", None])

    def forward(self, x):
        out = F.embedding(x, self.weight)
        mesh = get_mesh()
        if self.world_size > 1 and mesh is not None and isinstance(
                out._value, jax.core.Tracer):
            # keep activations replicated over mp after the sharded gather
            from ...core.dispatch import apply

            out = apply(
                lambda a: jax.lax.with_sharding_constraint(
                    a, NamedSharding(mesh, PartitionSpec())),
                out, op_name="vp_embedding_constraint")
        return out


class ColumnParallelLinear(nn.Layer):
    """Output-dim sharded linear. gather_output=False leaves activations
    sharded on mp (fed to a RowParallelLinear), True re-replicates."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=None, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        world, group, mesh = _mp_info()
        self.world_size = world
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr)
        self.bias = self.create_parameter(
            [out_features], is_bias=True) if has_bias else None
        if world > 1:
            _shard_param(self.weight, [None, "mp"])
            if self.bias is not None:
                _shard_param(self.bias, ["mp"])

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        mesh = get_mesh()
        if self.world_size > 1 and mesh is not None and isinstance(
                out._value, jax.core.Tracer):
            from ...core.dispatch import apply

            spec = PartitionSpec() if self.gather_output else PartitionSpec(
                *([None] * (out.ndim - 1) + ["mp"]))
            out = apply(
                lambda a: jax.lax.with_sharding_constraint(
                    a, NamedSharding(mesh, spec)),
                out, op_name="colp_constraint")
        return out


class RowParallelLinear(nn.Layer):
    """Input-dim sharded linear; partial results psum over mp (XLA inserts
    it from the shardings)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        world, group, mesh = _mp_info()
        self.world_size = world
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr)
        self.bias = self.create_parameter(
            [out_features], is_bias=True) if has_bias else None
        if world > 1:
            _shard_param(self.weight, ["mp", None])

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        mesh = get_mesh()
        if self.world_size > 1 and mesh is not None and isinstance(
                out._value, jax.core.Tracer):
            from ...core.dispatch import apply

            out = apply(
                lambda a: jax.lax.with_sharding_constraint(
                    a, NamedSharding(mesh, PartitionSpec())),
                out, op_name="rowp_constraint")
        return out


class ParallelCrossEntropy(nn.Layer):
    """Vocab-sharded softmax CE (reference mp_layers.py:742). With GSPMD the
    plain fused CE partitions correctly over the sharded vocab dim."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        loss = F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)
        from ...ops.manipulation import unsqueeze

        return unsqueeze(loss, -1)
