"""Pipeline model partitioning (reference:
fleet/meta_parallel/parallel_layers/pp_layers.py — LayerDesc:56,
PipelineLayer:257)."""
from __future__ import annotations

from typing import Callable, List, Optional

import numpy as np

from ...nn.layer.layers import Layer, LayerList, Sequential

__all__ = ["LayerDesc", "SharedLayerDesc", "PipelineLayer"]


class LayerDesc:
    def __init__(self, layer_cls, *inputs, **kwargs):
        self.layer_cls = layer_cls
        self.inputs = inputs
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_cls(*self.inputs, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    def __init__(self, key, layer_cls, forward_func=None,
                 shared_weight_attr="weight", *inputs, **kwargs):
        super().__init__(layer_cls, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(Layer):
    """Partition a layer list into pp stages. In single-controller SPMD all
    stages are materialized (they run on different mesh slices under the
    compiled pipeline); stage boundaries drive the spmd_pipeline schedule.
    """

    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn=None, seg_method="uniform", recompute_interval=0,
                 recompute_ctx=None, num_virtual_pipeline_stages=None):
        super().__init__()
        self._loss_fn = loss_fn
        self._topo = topology
        from ..topology import get_hybrid_communicate_group

        hcg = get_hybrid_communicate_group()
        if num_stages is None:
            num_stages = hcg.get_pipe_parallel_world_size() if hcg else 1
        self._num_stages = max(num_stages, 1)
        self._num_virtual_pipeline_stages = max(
            num_virtual_pipeline_stages or 1, 1)
        self._recompute_interval = recompute_interval

        descs = list(layers)
        built = []
        self._shared = {}
        for d in descs:
            if isinstance(d, SharedLayerDesc):
                if d.layer_name in self._shared:
                    built.append(("shared", d.layer_name, d.forward_func))
                else:
                    layer = d.build_layer()
                    self._shared[d.layer_name] = layer
                    built.append(("shared_first", d.layer_name,
                                  d.forward_func, layer))
            elif isinstance(d, LayerDesc):
                built.append(("layer", d.build_layer()))
            elif isinstance(d, Layer):
                built.append(("layer", d))
            elif callable(d):
                built.append(("func", d))
            else:
                raise TypeError(f"bad pipeline item {d!r}")

        self.run_function = []
        all_layers = LayerList()
        for item in built:
            if item[0] == "layer":
                all_layers.append(item[1])
                self.run_function.append(item[1])
            elif item[0] == "shared_first":
                all_layers.append(item[3])
                fwd = item[2]
                layer = item[3]
                self.run_function.append(
                    (lambda l, f: (lambda x: f(l, x) if f else l(x)))(
                        layer, fwd))
            elif item[0] == "shared":
                layer = self._shared[item[1]]
                fwd = item[2]
                self.run_function.append(
                    (lambda l, f: (lambda x: f(l, x) if f else l(x)))(
                        layer, fwd))
            else:
                self.run_function.append(item[1])
        self.layers_list = all_layers

        # stage segmentation (uniform by count; "layer:<Cls>" counts class
        # instances like the reference seg_method)
        n = len(self.run_function)
        if isinstance(seg_method, str) and seg_method.startswith("layer:"):
            cls_name = seg_method.split(":", 1)[1]
            marks = [i for i, f in enumerate(self.run_function)
                     if type(f).__name__ == cls_name]
            if len(marks) >= self._num_stages:
                per = len(marks) // self._num_stages
                bounds = [0]
                for s in range(1, self._num_stages):
                    bounds.append(marks[s * per])
                bounds.append(n)
            else:
                bounds = np.linspace(0, n, self._num_stages + 1,
                                     dtype=int).tolist()
        else:
            bounds = np.linspace(0, n, self._num_stages + 1,
                                 dtype=int).tolist()
        self._stage_bounds = bounds

    @property
    def num_stages(self):
        return self._num_stages

    def get_num_virtual_stages(self):
        return self._num_virtual_pipeline_stages

    def stage_fns(self, stage_id: int) -> List[Callable]:
        lo, hi = self._stage_bounds[stage_id], self._stage_bounds[stage_id + 1]
        return self.run_function[lo:hi]

    def forward_stage(self, x, stage_id: int):
        for fn in self.stage_fns(stage_id):
            x = fn(x)
        return x

    def forward(self, x):
        for fn in self.run_function:
            x = fn(x)
        return x
