"""Hybrid-parallel engines (reference: fleet/meta_parallel/
tensor_parallel.py, sharding_parallel.py, segment_parallel.py —
MetaParallelBase wrappers that sync params and scope the model for the
topology)."""
from __future__ import annotations

from ...nn.layer.layers import Layer
from .. import collective

__all__ = ["MetaParallelBase", "TensorParallel", "ShardingParallel",
           "SegmentParallel"]


class MetaParallelBase(Layer):
    def __init__(self, layers, hcg, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        self._prepare_for_model()

    def _prepare_for_model(self):
        pass

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def parameters(self, *args, **kwargs):
        return self._layers.parameters(*args, **kwargs)

    def named_parameters(self, *args, **kwargs):
        return self._layers.named_parameters(*args, **kwargs)

    def sublayers(self, include_self=False):
        return self._layers.sublayers(include_self)

    def train(self):
        self._layers.train()
        return self

    def eval(self):
        self._layers.eval()
        return self


class TensorParallel(MetaParallelBase):
    """reference: fleet/meta_parallel/tensor_parallel.py. Param broadcast
    within mp group happens implicitly: mp-sharded params are global arrays;
    replicated ones are single-copy by construction (single controller)."""

    def _prepare_for_model(self):
        # in multi-controller mode, broadcast non-distributed params so all
        # mp ranks agree (reference broadcast_mp_parameters)
        if collective.get_world_size(self._hcg.get_model_parallel_group()) \
                > 1 and not _single_controller():
            for p in self._layers.parameters():
                if not getattr(p, "is_distributed", False):
                    collective.broadcast(
                        p, src=self._hcg.get_model_parallel_group().ranks[0],
                        group=self._hcg.get_model_parallel_group())


def _single_controller():
    import jax

    try:
        return jax.process_count() == 1
    except Exception:
        return True


class ShardingParallel(MetaParallelBase):
    """Model wrapper for sharding-only topology (the optimizer does the
    actual state partitioning — see sharding_optimizer.py)."""


class SegmentParallel(MetaParallelBase):
    """Context/sequence parallel engine (reference:
    fleet/meta_parallel/segment_parallel.py:26). Inputs arrive with the
    sequence dim sharded over the 'sep' axis; attention uses ring attention
    over sep (paddle_tpu.ops.pallas.ring_attention via
    nn.functional.scaled_dot_product_attention when inside shard_map)."""
