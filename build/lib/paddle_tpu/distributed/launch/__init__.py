from . import main
