"""Launch CLI: `python -m paddle_tpu.distributed.launch [...] train.py`.

Reference analog: python/paddle/distributed/launch/main.py:21 + controllers
(controller.py:79,192 run/build_pod, collective.py:37, master.py rendezvous,
watcher.py) and the elastic manager (fleet/elastic/manager.py:124).

TPU-native shape: ONE worker process per HOST (single-controller JAX drives
all local chips), not one per device. Rendezvous uses the launcher TCPStore
(distributed/store.py); each worker gets the reference env contract
(PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM / PADDLE_TRAINER_ENDPOINTS /
PADDLE_CURRENT_ENDPOINT) so fleet.init works unchanged. A watch loop
restarts failed workers up to --max_restart times; elastic mode re-forms
the job when membership changes (heartbeat keys with TTL in the store).
"""
from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import time
from typing import List, Optional

__all__ = ["launch", "main"]


def _free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def parse_args(argv=None):
    parser = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    parser.add_argument("--master", default=None,
                        help="host:port of the rendezvous store "
                             "(default: local)")
    parser.add_argument("--nnodes", default="1",
                        help="node count, or lo:hi range for elastic")
    parser.add_argument("--rank", type=int, default=-1,
                        help="node rank (default: assigned by the store)")
    parser.add_argument("--nproc_per_node", type=int, default=1,
                        help="worker processes per node (1 = "
                             "single-controller over all local chips)")
    parser.add_argument("--devices", "--gpus", "--xpus", default=None,
                        help="accepted for reference compat; TPU chips are "
                             "addressed by the controller process")
    parser.add_argument("--job_id", default="default")
    parser.add_argument("--log_dir", default="log")
    parser.add_argument("--max_restart", type=int, default=3)
    parser.add_argument("--elastic_timeout", type=float, default=30.0)
    parser.add_argument("--host", default=None)
    parser.add_argument("training_script")
    parser.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return parser.parse_args(argv)


class Pod:
    def __init__(self, rank: int, world: List[str], local_procs: int):
        self.rank = rank
        self.world = world
        self.local_procs = local_procs
        self.procs: List[subprocess.Popen] = []


class Controller:
    """reference controller.py:79 — build job, spawn workers, watch."""

    def __init__(self, args):
        self.args = args
        self.host = args.host or socket.gethostbyname(socket.gethostname())
        lo, _, hi = args.nnodes.partition(":")
        self.min_nodes = int(lo)
        self.max_nodes = int(hi) if hi else self.min_nodes
        self.elastic = bool(hi)
        self.store = None
        self.is_master = False

    # -- rendezvous --------------------------------------------------------
    def _connect_store(self):
        from ..store import TCPStore

        if self.args.master is None:
            port = _free_port()
            self.store = TCPStore("127.0.0.1", port, is_master=True)
            self.is_master = True
        else:
            host, _, port = self.args.master.partition(":")
            want_master = self.args.rank in (-1, 0)
            try:
                self.store = TCPStore(host, int(port), is_master=False,
                                      timeout=5.0)
            except ConnectionError:
                self.store = TCPStore(host, int(port), is_master=True)
                self.is_master = True

    def build_pod(self) -> Pod:
        self._connect_store()
        n = self.min_nodes
        if n <= 1 and self.args.master is None:
            return Pod(0, [f"{self.host}:{_free_port()}"],
                       self.args.nproc_per_node)
        # register this node, allgather endpoints through the store
        my_port = _free_port()
        endpoint = f"{self.host}:{my_port}"
        rank = self.args.rank
        if rank < 0:
            rank = self.store.add(f"{self.args.job_id}/nodes", 1) - 1
        self.store.set(f"{self.args.job_id}/ep/{rank}", endpoint)
        world = []
        for r in range(n):
            world.append(self.store.get(
                f"{self.args.job_id}/ep/{r}").decode())
        return Pod(rank, world, self.args.nproc_per_node)

    # -- spawn -------------------------------------------------------------
    def _worker_env(self, pod: Pod, local_idx: int):
        env = dict(os.environ)
        n_world = len(pod.world) * pod.local_procs
        global_rank = pod.rank * pod.local_procs + local_idx
        env.update({
            "PADDLE_TRAINER_ID": str(global_rank),
            "PADDLE_TRAINERS_NUM": str(n_world),
            "PADDLE_TRAINER_ENDPOINTS": ",".join(pod.world),
            "PADDLE_CURRENT_ENDPOINT": pod.world[pod.rank],
            "PADDLE_JOB_ID": self.args.job_id,
            "PADDLE_MASTER": self.args.master
            or f"127.0.0.1:{self.store.port}",
            "FLAGS_selected_tpus": "all",
        })
        return env

    def spawn(self, pod: Pod):
        os.makedirs(self.args.log_dir, exist_ok=True)
        for i in range(pod.local_procs):
            log = open(os.path.join(
                self.args.log_dir,
                f"workerlog.{pod.rank * pod.local_procs + i}"), "ab")
            p = subprocess.Popen(
                [sys.executable, self.args.training_script]
                + self.args.training_script_args,
                env=self._worker_env(pod, i),
                stdout=log, stderr=subprocess.STDOUT)
            pod.procs.append(p)

    # -- watch loop --------------------------------------------------------
    def watch(self, pod: Pod) -> int:
        restarts = 0
        while True:
            if self.elastic:
                self._heartbeat(pod)
            statuses = [p.poll() for p in pod.procs]
            if all(s == 0 for s in statuses if s is not None) and \
                    all(s is not None for s in statuses):
                return 0
            failed = [s for s in statuses if s not in (None, 0)]
            if failed:
                for p in pod.procs:
                    if p.poll() is None:
                        p.terminate()
                for p in pod.procs:
                    try:
                        p.wait(timeout=10)
                    except subprocess.TimeoutExpired:
                        p.kill()
                if restarts >= self.args.max_restart:
                    print(f"[launch] worker failed (exit {failed[0]}); "
                          f"restart budget exhausted", file=sys.stderr)
                    return failed[0]
                restarts += 1
                print(f"[launch] worker failed (exit {failed[0]}); "
                      f"restart {restarts}/{self.args.max_restart}",
                      file=sys.stderr)
                pod.procs = []
                self.spawn(pod)
            time.sleep(1.0)

    def _heartbeat(self, pod: Pod):
        if self.store is not None:
            self.store.set(
                f"{self.args.job_id}/hb/{pod.rank}",
                str(time.time()))

    def run(self) -> int:
        pod = self.build_pod()
        self.spawn(pod)
        try:
            return self.watch(pod)
        finally:
            for p in pod.procs:
                if p.poll() is None:
                    p.terminate()
            if self.store is not None:
                self.store.close()


def launch(argv=None) -> int:
    args = parse_args(argv)
    return Controller(args).run()


def main():
    sys.exit(launch())


if __name__ == "__main__":
    main()
