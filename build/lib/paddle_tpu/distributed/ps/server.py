"""Parameter-server service.

Reference analog: BrpcPsServer (paddle/fluid/distributed/ps/service/
brpc_ps_server.h) — a PS node hosting table shards and serving
pull/push/save/load RPCs from trainer clients.

TPU-native: brpc is replaced by the framework's TCP message framing (the
TCPStore/rpc layer); the protocol is safe JSON+ndarray messages
(op, table_id, payload — see wire.py), matching the reference's use of
non-executable protobuf payloads. One server == one shard; clients route
sparse keys by ``key % num_servers`` (the reference's hash routing in
BrpcPsClient). The listener binds to the advertised pod IP
(POD_IP / PADDLE_LOCAL_IP) rather than all interfaces unless the caller
asks for 0.0.0.0 explicitly.
"""
from __future__ import annotations

import os
import socket
import threading
from typing import Dict, Optional

import numpy as np

from ..store import _recv_msg, _send_msg
from .table import DenseTable, SparseTable
from .wire import decode_msg, dump_obj, encode_msg, load_obj

__all__ = ["PsServer", "default_bind_host"]


def default_bind_host() -> str:
    """Bind address for PS/RPC listeners: the pod's advertised IP when the
    launcher set one, else loopback — never 0.0.0.0 implicitly."""
    return os.environ.get("POD_IP") or os.environ.get("PADDLE_LOCAL_IP") \
        or "127.0.0.1"


class PsServer:
    """Hosts this shard's tables and serves client RPCs on a TCP port."""

    def __init__(self, host: str = "", port: int = 0):
        host = host or default_bind_host()
        self._sock = socket.socket()
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self.port = self._sock.getsockname()[1]
        self._sock.listen(128)
        self._tables: Dict[int, object] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # name -> [generation, arrival_count]; only the latest generation
        # per name is kept (clients hit barriers in program order, so an
        # arrival at gen k proves every gen < k completed) — bounded memory
        self._barriers: Dict[str, list] = {}

    # -- lifecycle --------------------------------------------------------
    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"ps_server:{self.port}")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    def run(self):
        """Blocking serve (reference: run_server); returns on stop()."""
        if self._thread is None:
            self.start()
        self._stop.wait()

    # -- serving ----------------------------------------------------------
    def _loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                break
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        try:
            while True:
                parts = _recv_msg(conn)
                try:
                    req = decode_msg(parts)
                    resp = self._handle(req)
                except Exception as e:      # fault isolation per request
                    req = {}
                    resp = {"err": f"{type(e).__name__}: {e}"}
                _send_msg(conn, *encode_msg(resp))
                if req.get("op") == "stop":
                    break
        except (ConnectionError, EOFError, OSError):
            pass
        finally:
            conn.close()

    def _handle(self, req):
        op = req["op"]
        if op == "create_table":
            tid, kind = req["table_id"], req["kind"]
            with self._lock:
                if tid not in self._tables:
                    if kind == "sparse":
                        self._tables[tid] = SparseTable(**req["cfg"])
                    else:
                        self._tables[tid] = DenseTable(**req["cfg"])
            return {"ok": True}
        if op == "pull_sparse":
            return {"rows": self._tables[req["table_id"]].pull(req["keys"])}
        if op == "push_sparse":
            self._tables[req["table_id"]].push(req["keys"], req["grads"])
            return {"ok": True}
        if op == "pull_dense":
            return {"value": self._tables[req["table_id"]].pull()}
        if op == "set_dense":
            self._tables[req["table_id"]].set(req["value"])
            return {"ok": True}
        if op == "push_dense":
            self._tables[req["table_id"]].push(req["grad"])
            return {"ok": True}
        if op == "table_size":
            return {"size": self._tables[req["table_id"]].size()}
        if op == "save":
            state = {tid: t.state() for tid, t in self._tables.items()}
            dump_obj(state, req["path"])
            return {"ok": True}
        if op == "load":
            state = load_obj(req["path"])
            for tid, st in state.items():
                if tid in self._tables:
                    self._tables[tid].load_state(st)
            return {"ok": True}
        if op == "barrier":
            # counting barrier: nth arrival of (name, gen) releases when
            # count reaches world; clients poll. A poll/arrival for an
            # older generation than the stored one answers done=True (its
            # caller could only have advanced past it), so only one entry
            # per name ever lives on the server.
            name, world = req["name"], req["world"]
            gen = int(req.get("gen", 0))
            with self._lock:
                cur = self._barriers.get(name)
                if cur is None or gen > cur[0]:
                    cur = self._barriers[name] = [gen, 0]
                if gen < cur[0]:
                    return {"done": True}
                if req.get("arrive"):
                    cur[1] += 1
                done = cur[1] >= world
            return {"done": done}
        if op == "stop":
            self._stop.set()
            # unblock the accept loop
            try:
                self._sock.close()
            except OSError:
                pass
            return {"ok": True}
        raise ValueError(f"unknown ps op {op!r}")
