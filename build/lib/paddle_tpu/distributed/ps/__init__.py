"""Parameter-server stack (reference: paddle/fluid/distributed/ps/ C++ +
python/paddle/distributed/ps/ — brpc services, sharded tables, async
communicator, the_one_ps runtime), rebuilt host-native for TPU clusters:
TCP services over the framework's socket framing, numpy host tables, and a
DistributedEmbedding whose device side only ever sees the batch's unique
rows (the TPU-friendly contract — HBM never holds the table)."""
from .client import AsyncCommunicator, PsClient
from .server import PsServer
from .table import DenseTable, SparseTable
from .the_one_ps import (DistributedEmbedding, PSOptimizer, TheOnePs,
                         get_runtime)

__all__ = ["PsServer", "PsClient", "AsyncCommunicator", "SparseTable",
           "DenseTable", "TheOnePs", "DistributedEmbedding", "PSOptimizer",
           "get_runtime"]
