"""Safe PS wire codec — JSON header + raw numpy buffers.

Reference analog: the PS service's protobuf messages
(paddle/fluid/distributed/ps/service/sendrecv.proto) — structured,
non-executable payloads. The round-1 protocol used pickle, which lets any
host that can reach the port execute code on the server; this codec keeps
the same (op, table_id, payload) request shape but serializes it as a JSON
header whose ndarray fields are replaced by {"__nd__": i} placeholders,
with the raw array bytes appended as framed binary parts. Nothing on the
wire can construct arbitrary Python objects.
"""
from __future__ import annotations

import json
from typing import Any, List, Tuple

import numpy as np

__all__ = ["encode_msg", "decode_msg", "dump_obj", "load_obj"]

# dtypes allowed on the wire (all the PS tables use); anything else raises
_DTYPES = {"float32", "float64", "float16", "bfloat16", "int8", "uint8",
           "int16", "int32", "int64", "uint32", "uint64", "bool"}


def _pack(obj: Any, bufs: List[bytes]) -> Any:
    if isinstance(obj, np.ndarray):
        name = str(obj.dtype)
        if name not in _DTYPES:
            raise TypeError(f"dtype {name} not wire-safe")
        idx = len(bufs)
        bufs.append(np.ascontiguousarray(obj).tobytes())
        return {"__nd__": idx, "dtype": name, "shape": list(obj.shape)}
    if isinstance(obj, np.generic):
        return _pack(np.asarray(obj), bufs)
    if isinstance(obj, dict):
        # JSON keys must be strings; the tables key rows by int id, so
        # encode every dict as an item list to round-trip key types
        return {"__map__": [[_pack(k, bufs), _pack(v, bufs)]
                            for k, v in obj.items()]}
    if isinstance(obj, (list, tuple)):
        return [_pack(x, bufs) for x in obj]
    if obj is None or isinstance(obj, (str, int, float, bool)):
        return obj
    raise TypeError(f"type {type(obj).__name__} not wire-safe")


def _unpack(obj: Any, bufs: List[bytes]) -> Any:
    if isinstance(obj, dict):
        if "__nd__" in obj:
            name = obj["dtype"]
            if name not in _DTYPES:
                raise TypeError(f"dtype {name} not wire-safe")
            arr = np.frombuffer(bufs[obj["__nd__"]], dtype=np.dtype(name))
            return arr.reshape(obj["shape"]).copy()
        if "__map__" in obj:
            return {_freeze(_unpack(k, bufs)): _unpack(v, bufs)
                    for k, v in obj["__map__"]}
        raise TypeError("unexpected wire object")
    if isinstance(obj, list):
        return [_unpack(x, bufs) for x in obj]
    return obj


def _freeze(k):
    # dict keys decoded from the wire must be hashable
    if isinstance(k, np.ndarray):
        return k.tobytes()
    return k


def encode_msg(obj: Any) -> Tuple[bytes, ...]:
    """obj -> (json_header, raw_buf_0, raw_buf_1, ...)."""
    bufs: List[bytes] = []
    header = json.dumps(_pack(obj, bufs)).encode()
    return (header, *bufs)


def decode_msg(parts) -> Any:
    header, *bufs = parts
    return _unpack(json.loads(header.decode()), list(bufs))


def dump_obj(obj: Any, path: str):
    """Serialize to disk with the same safe framing (replaces pickle for
    table save/load: length-prefixed parts, no executable payload)."""
    import struct
    parts = encode_msg(obj)
    with open(path, "wb") as f:
        f.write(struct.pack("!I", len(parts)))
        for p in parts:
            f.write(struct.pack("!Q", len(p)))
            f.write(p)


def load_obj(path: str) -> Any:
    import struct
    with open(path, "rb") as f:
        (n,) = struct.unpack("!I", f.read(4))
        parts = []
        for _ in range(n):
            (ln,) = struct.unpack("!Q", f.read(8))
            parts.append(f.read(ln))
    return decode_msg(parts)
