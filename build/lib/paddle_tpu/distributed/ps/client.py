"""Parameter-server client + async communicator.

Reference analogs:
- BrpcPsClient (paddle/fluid/distributed/ps/service/brpc_ps_client.h):
  routes keys to table shards, batches pull/push RPCs.
- Communicator (paddle/fluid/distributed/ps/service/communicator/
  communicator.h) — the async-SGD engine: trainer-side background thread
  aggregating gradients and flushing them to servers on an interval
  (a_sync mode), or accumulating local deltas and syncing every k steps
  (geo mode).

Key routing is ``key % num_servers`` over the sorted endpoint list — all
clients and the embedding layer agree on the layout.
"""
from __future__ import annotations

import queue
import socket
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..store import _recv_msg, _send_msg
from .wire import decode_msg, encode_msg

__all__ = ["PsClient", "AsyncCommunicator"]


class _Conn:
    """One persistent connection; a lock serializes request/response."""

    def __init__(self, host: str, port: int, timeout: float = 60.0):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.lock = threading.Lock()

    def call(self, req: dict) -> dict:
        with self.lock:
            _send_msg(self.sock, *encode_msg(req))
            parts = _recv_msg(self.sock)
        resp = decode_msg(parts)
        if isinstance(resp, dict) and "err" in resp:
            raise RuntimeError(f"ps server error: {resp['err']}")
        return resp

    def close(self):
        try:
            self.sock.close()
        except OSError:
            pass


class PsClient:
    def __init__(self, endpoints: Sequence[str]):
        self.endpoints = sorted(endpoints)
        self._conns: List[_Conn] = []
        for ep in self.endpoints:
            host, port = ep.rsplit(":", 1)
            self._conns.append(_Conn(host, int(port)))
        self.n = len(self._conns)
        self._barrier_seq: Dict[str, int] = {}

    # -- table management -------------------------------------------------
    def create_sparse_table(self, table_id: int, dim: int, rule="sgd",
                            **rule_kw):
        cfg = {"dim": dim, "rule": rule, **rule_kw}
        for c in self._conns:
            c.call({"op": "create_table", "table_id": table_id,
                    "kind": "sparse", "cfg": cfg})

    def create_dense_table(self, table_id: int, shape, rule="sgd",
                           **rule_kw):
        # dense tables live whole on server 0 (reference: dense params are
        # range-sharded; a single block keeps the host copy authoritative)
        self._conns[0].call({"op": "create_table", "table_id": table_id,
                             "kind": "dense",
                             "cfg": {"shape": tuple(shape), "rule": rule,
                                     **rule_kw}})

    # -- sparse ------------------------------------------------------------
    def _route(self, keys: np.ndarray):
        keys = np.asarray(keys, np.int64).ravel()
        shard = (keys % self.n).astype(np.int64)
        return keys, shard

    def pull_sparse(self, table_id: int, keys) -> np.ndarray:
        keys, shard = self._route(keys)
        out: Optional[np.ndarray] = None
        for s in range(self.n):
            idx = np.nonzero(shard == s)[0]
            if idx.size == 0:
                continue
            rows = self._conns[s].call(
                {"op": "pull_sparse", "table_id": table_id,
                 "keys": keys[idx]})["rows"]
            if out is None:
                out = np.empty((len(keys), rows.shape[1]), np.float32)
            out[idx] = rows
        return out if out is not None \
            else np.empty((0, 0), np.float32)

    def push_sparse(self, table_id: int, keys, grads: np.ndarray):
        keys, shard = self._route(keys)
        grads = np.asarray(grads, np.float32)
        for s in range(self.n):
            idx = np.nonzero(shard == s)[0]
            if idx.size == 0:
                continue
            self._conns[s].call(
                {"op": "push_sparse", "table_id": table_id,
                 "keys": keys[idx], "grads": grads[idx]})

    def table_size(self, table_id: int) -> int:
        return sum(c.call({"op": "table_size",
                           "table_id": table_id})["size"]
                   for c in self._conns)

    # -- dense -------------------------------------------------------------
    def pull_dense(self, table_id: int) -> np.ndarray:
        return self._conns[0].call(
            {"op": "pull_dense", "table_id": table_id})["value"]

    def set_dense(self, table_id: int, value: np.ndarray):
        self._conns[0].call({"op": "set_dense", "table_id": table_id,
                             "value": np.asarray(value, np.float32)})

    def push_dense(self, table_id: int, grad: np.ndarray):
        self._conns[0].call({"op": "push_dense", "table_id": table_id,
                             "grad": np.asarray(grad, np.float32)})

    # -- control ------------------------------------------------------------
    def save(self, path_prefix: str):
        for i, c in enumerate(self._conns):
            c.call({"op": "save", "path": f"{path_prefix}.shard{i}"})

    def load(self, path_prefix: str):
        for i, c in enumerate(self._conns):
            c.call({"op": "load", "path": f"{path_prefix}.shard{i}"})

    def barrier(self, name: str, world: int, timeout: float = 60.0):
        # per-name generation counter: every participant calls barriers in
        # program order, so the k-th barrier of `name` on every worker maps
        # to the same server-side key (fresh counter per generation)
        seq = self._barrier_seq.get(name, 0) + 1
        self._barrier_seq[name] = seq
        self._conns[0].call({"op": "barrier", "name": name, "gen": seq,
                             "world": world, "arrive": True})
        t0 = time.time()
        while True:
            if self._conns[0].call({"op": "barrier", "name": name,
                                    "gen": seq, "world": world})["done"]:
                return
            if time.time() - t0 > timeout:
                raise TimeoutError(f"ps barrier {name!r} timed out")
            time.sleep(0.01)

    def stop_servers(self):
        for c in self._conns:
            try:
                c.call({"op": "stop"})
            except (RuntimeError, ConnectionError, OSError):
                pass

    def close(self):
        for c in self._conns:
            c.close()


class AsyncCommunicator:
    """Trainer-side async-SGD engine (reference Communicator::Start —
    send-queue draining thread). push_sparse calls enqueue; the worker
    aggregates by (table, key) within a send window and flushes every
    `send_interval_s` or `send_queue_size` batches — the a_sync mode knobs
    from the reference's DistributedStrategy."""

    def __init__(self, client: PsClient, send_interval_s: float = 0.01,
                 send_queue_size: int = 16):
        self.client = client
        self.interval = send_interval_s
        self.max_batch = send_queue_size
        self._q: "queue.Queue" = queue.Queue()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self):
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="ps_communicator")
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self.flush()

    def push_sparse(self, table_id: int, keys, grads):
        self._q.put((table_id, np.asarray(keys, np.int64).ravel(),
                     np.asarray(grads, np.float32)))
        if self._q.qsize() >= self.max_batch:
            self.flush()       # backpressure: send on the caller thread

    def flush(self):
        """Drain + aggregate + send everything queued (synchronous)."""
        pending: Dict[int, list] = {}
        while True:
            try:
                tid, keys, grads = self._q.get_nowait()
            except queue.Empty:
                break
            pending.setdefault(tid, []).append((keys, grads))
        for tid, items in pending.items():
            keys = np.concatenate([k for k, _ in items])
            grads = np.concatenate([g for _, g in items])
            # pre-aggregate duplicates so the wire carries unique keys
            uniq, inv = np.unique(keys, return_inverse=True)
            agg = np.zeros((len(uniq), grads.shape[1]), np.float32)
            np.add.at(agg, inv, grads)
            self.client.push_sparse(tid, uniq, agg)

    def _loop(self):
        while not self._stop.wait(self.interval):
            if self._q.qsize() >= 1:
                self.flush()
        self.flush()
