"""TheOnePs runtime — PS-mode fleet glue.

Reference analog: python/paddle/distributed/ps/the_one_ps.py — the runtime
fleet selects when the role maker says parameter-server mode: builds table
configs from the model's sparse lookups, starts servers on PSERVER nodes,
creates clients + the async communicator on TRAINER nodes, and rewires the
optimizer so sparse updates happen server-side (a_sync SGD).

TPU-native flow per train step on a worker:
  1. DistributedEmbedding.forward pulls the batch's unique rows from the
     PS shards into one dense [n_unique, dim] host array, uploads it as a
     leaf Tensor, and gathers per-position rows on device (TPU math only
     ever sees dense minibatch rows).
  2. loss.backward() accumulates the gather-scatter VJP into the leaf's
     .grad = per-unique-id gradients.
  3. PSOptimizer.step() hands those grads to the AsyncCommunicator, which
     aggregates and pushes them; the server applies the table's update
     rule (async SGD — the reference's a_sync mode). Dense params remain
     locally optimized (hybrid, as in the reference's default a_sync).
"""
from __future__ import annotations

import os
import threading
import weakref
from typing import Dict, List, Optional

import numpy as np

from ...nn.layer.layers import Layer
from .client import AsyncCommunicator, PsClient
from .server import PsServer

__all__ = ["TheOnePs", "DistributedEmbedding", "PSOptimizer", "get_runtime"]


class TheOnePs:
    """Process-wide PS runtime (one per trainer/server process)."""

    def __init__(self, role_maker):
        self.role = role_maker
        self.server: Optional[PsServer] = None
        self.client: Optional[PsClient] = None
        self.communicator: Optional[AsyncCommunicator] = None
        self._next_table_id = 0
        self._lock = threading.Lock()

    # -- server side -------------------------------------------------------
    def init_server(self, *args, **kwargs):
        ep = self.role._server_endpoint()
        host, port = ep.rsplit(":", 1)
        # bind the advertised endpoint host, not all interfaces; NATed /
        # port-mapped deployments where that host is not a local interface
        # fall back to 0.0.0.0 (trusted-network assumption, logged)
        try:
            self.server = PsServer(host, int(port))
        except OSError:
            import warnings
            warnings.warn(
                f"PS endpoint host {host!r} is not a local interface; "
                "binding 0.0.0.0 — ensure the network is trusted")
            self.server = PsServer("0.0.0.0", int(port))
        self.server.start()

    def run_server(self):
        if self.server is None:
            self.init_server()
        self.server.run()

    def stop_server(self):
        if self.server is not None:
            self.server.stop()

    # -- worker side -------------------------------------------------------
    def init_worker(self):
        self.client = PsClient(self.role._server_endpoints())
        self.communicator = AsyncCommunicator(self.client).start()
        for emb in _embeddings:
            emb._bind(self)

    def stop_worker(self, stop_servers: bool = False):
        if self.communicator is not None:
            self.communicator.stop()
        if self.client is not None:
            if stop_servers:
                self.client.stop_servers()
            self.client.close()

    def barrier_worker(self, name: str = "worker"):
        if self.client is not None:
            self.client.barrier(name, self.role._worker_num())

    def alloc_table_id(self) -> int:
        with self._lock:
            tid = self._next_table_id
            self._next_table_id += 1
            return tid

    def save(self, path_prefix: str):
        if self.client is not None:
            self.communicator.flush()
            self.client.save(path_prefix)

    def load(self, path_prefix: str):
        if self.client is not None:
            self.client.load(path_prefix)


_runtime: Optional[TheOnePs] = None
_embeddings: "weakref.WeakSet" = weakref.WeakSet()


def set_runtime(rt: Optional[TheOnePs]):
    global _runtime
    _runtime = rt


def get_runtime() -> Optional[TheOnePs]:
    return _runtime


class DistributedEmbedding(Layer):
    """Sparse lookup backed by a PS SparseTable (reference:
    paddle.static.nn.sparse_embedding / distributed lookup-table op).

    The table never materializes on device; each forward pulls only the
    batch's unique rows."""

    def __init__(self, num_embeddings: int, embedding_dim: int,
                 rule: str = "adagrad", lr: float = 0.05,
                 table_id: Optional[int] = None, name: str = "emb"):
        super().__init__()
        self.num_embeddings = num_embeddings   # advisory (hash tables grow)
        self.embedding_dim = embedding_dim
        self.rule = rule
        self.lr = lr
        self.table_id = table_id
        self._name = name
        self._rt: Optional[TheOnePs] = None
        self._pulled = []         # [(leaf rows Tensor, unique keys)] per
                                  # forward since the last flush
        _embeddings.add(self)

    def _bind(self, rt: TheOnePs):
        self._rt = rt
        if self.table_id is None:
            self.table_id = rt.alloc_table_id()
        rt.client.create_sparse_table(
            self.table_id, self.embedding_dim, rule=self.rule, lr=self.lr)

    def forward(self, ids):
        import paddle_tpu as paddle
        from ...ops.manipulation import gather, reshape

        if self._rt is None or self._rt.client is None:
            raise RuntimeError(
                "DistributedEmbedding used before fleet.init_worker()")
        from ...core.autograd import is_grad_enabled

        ids_np = np.asarray(ids._value).astype(np.int64)
        shape = ids_np.shape
        uniq, inv = np.unique(ids_np.ravel(), return_inverse=True)
        rows_np = self._rt.client.pull_sparse(self.table_id, uniq)
        rows = paddle.to_tensor(rows_np)
        if is_grad_enabled():
            # track only when a backward can produce row grads — eval /
            # inference forwards would otherwise pin every pulled row
            rows.stop_gradient = False
            self._pulled.append((rows, uniq))
        inv_t = paddle.to_tensor(inv.astype(np.int64).reshape(-1))
        out = gather(rows, inv_t, axis=0)
        return reshape(out, list(shape) + [self.embedding_dim])

    def flush_gradients(self):
        """Push every pull's accumulated row grads since the last flush
        (called by PSOptimizer.step after backward) — multiple forwards
        per step (shared lookups, grad accumulation) all contribute."""
        for rows, keys in self._pulled:
            if rows.grad is None:
                continue
            self._rt.communicator.push_sparse(
                self.table_id, keys, np.asarray(rows.grad._value))
        self._pulled = []


class PSOptimizer:
    """Wraps a local optimizer for a_sync PS training (reference:
    fleet.distributed_optimizer in PS mode + ParameterServerOptimizer):
    step() first ships sparse grads to the servers, then steps the local
    optimizer over the dense params it owns."""

    def __init__(self, inner, runtime: TheOnePs):
        self.inner = inner
        self.rt = runtime

    def step(self):
        for emb in _embeddings:
            if emb._rt is self.rt:
                emb.flush_gradients()
        if self.inner is not None:
            self.inner.step()

    def clear_grad(self):
        if self.inner is not None:
            self.inner.clear_grad()

    def __getattr__(self, k):
        return getattr(self.inner, k)
