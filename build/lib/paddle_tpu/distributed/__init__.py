"""paddle_tpu.distributed (reference: python/paddle/distributed/)."""
from __future__ import annotations

from . import collective
from . import env
from . import topology
from .collective import (P2POp, ReduceOp, all_gather, all_gather_object,
                         all_reduce, all_to_all, all_to_all_single, barrier,
                         batch_isend_irecv, broadcast, broadcast_object_list,
                         destroy_process_group, gather, get_backend,
                         get_group, irecv, isend, new_group, recv, reduce,
                         reduce_scatter, scatter, scatter_object_list, send,
                         stream, wait)
from .env import (ParallelEnv, get_rank, get_world_size, init_parallel_env,
                  is_initialized)
from .topology import (build_mesh, get_hybrid_communicate_group, get_mesh,
                       HybridCommunicateGroup)

from . import fleet
from . import auto_parallel
from .auto_parallel.api import (shard_tensor, reshard, shard_layer,
                                shard_optimizer, to_static, dtensor_from_fn,
                                unshard_dtensor)
from .auto_parallel.process_mesh import ProcessMesh
from .auto_parallel.placement import (Placement, Partial, Replicate, Shard)
from . import checkpoint
from .checkpoint import load_state_dict, save_state_dict
from .parallel import DataParallel
from . import utils
from . import auto_tuner
from . import elastic
from .watchdog import (comm_task_manager, disable_comm_watchdog,
                       enable_comm_watchdog)
from . import launch
from .store import TCPStore
from . import rpc
from . import ps


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """reference: paddle.distributed.spawn. Single-controller JAX drives all
    local chips from one process, so spawn runs func once in-process with the
    env already initialized; multi-host jobs use the launch CLI."""
    init_parallel_env()
    return func(*args)


def get_trainer_endpoints():
    return ParallelEnv().trainer_endpoints


def get_current_endpoint():
    return ParallelEnv().current_endpoint
