"""paddle.audio (reference: python/paddle/audio/ — features + functional).
Spectrogram/MelSpectrogram/MFCC built on paddle_tpu.signal.stft."""
from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from .. import nn
from ..core.dispatch import apply
from ..core.tensor import Tensor
from .. import signal as _signal

__all__ = ["features", "functional"]


class functional:
    @staticmethod
    def hz_to_mel(freq, htk=False):
        if htk:
            return 2595.0 * np.log10(1.0 + np.asarray(freq) / 700.0)
        f = np.asarray(freq, np.float64)
        mel = 3 * f / 200.0
        min_log_hz = 1000.0
        min_log_mel = 15.0
        logstep = math.log(6.4) / 27.0
        return np.where(f >= min_log_hz,
                        min_log_mel + np.log(f / min_log_hz) / logstep, mel)

    @staticmethod
    def mel_to_hz(mel, htk=False):
        if htk:
            return 700.0 * (10.0 ** (np.asarray(mel) / 2595.0) - 1.0)
        m = np.asarray(mel, np.float64)
        f = 200.0 * m / 3.0
        min_log_hz = 1000.0
        min_log_mel = 15.0
        logstep = math.log(6.4) / 27.0
        return np.where(m >= min_log_mel,
                        min_log_hz * np.exp(logstep * (m - min_log_mel)), f)

    @staticmethod
    def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                             htk=False, norm="slaney"):
        f_max = f_max or sr / 2
        n_freqs = n_fft // 2 + 1
        freqs = np.linspace(0, sr / 2, n_freqs)
        mel_pts = np.linspace(functional.hz_to_mel(f_min, htk),
                              functional.hz_to_mel(f_max, htk), n_mels + 2)
        hz_pts = functional.mel_to_hz(mel_pts, htk)
        fb = np.zeros((n_mels, n_freqs))
        for i in range(n_mels):
            lo, c, hi = hz_pts[i], hz_pts[i + 1], hz_pts[i + 2]
            up = (freqs - lo) / max(c - lo, 1e-10)
            down = (hi - freqs) / max(hi - c, 1e-10)
            fb[i] = np.maximum(0, np.minimum(up, down))
        if norm == "slaney":
            enorm = 2.0 / (hz_pts[2:] - hz_pts[:-2])
            fb *= enorm[:, None]
        return Tensor(fb.astype(np.float32))

    @staticmethod
    def create_dct(n_mfcc, n_mels, norm="ortho"):
        n = np.arange(n_mels)
        k = np.arange(n_mfcc)[:, None]
        dct = np.cos(math.pi / n_mels * (n + 0.5) * k)
        if norm == "ortho":
            dct[0] *= 1.0 / math.sqrt(2)
            dct *= math.sqrt(2.0 / n_mels)
        return Tensor(dct.astype(np.float32).T)

    @staticmethod
    def power_to_db(spect, ref_value=1.0, amin=1e-10, top_db=80.0):
        def fn(s):
            db = 10.0 * jnp.log10(jnp.maximum(s, amin))
            db = db - 10.0 * jnp.log10(jnp.maximum(ref_value, amin))
            if top_db is not None:
                db = jnp.maximum(db, db.max() - top_db)
            return db
        return apply(fn, spect, op_name="power_to_db")


class features:
    class Spectrogram(nn.Layer):
        def __init__(self, n_fft=512, hop_length=None, win_length=None,
                     window="hann", power=2.0, center=True,
                     pad_mode="reflect", dtype="float32"):
            super().__init__()
            self.n_fft = n_fft
            self.hop_length = hop_length or n_fft // 4
            self.power = power
            self.center = center
            self.pad_mode = pad_mode
            wl = win_length or n_fft
            if window == "hann":
                w = np.hanning(wl + 1)[:-1]
            elif window == "hamming":
                w = np.hamming(wl + 1)[:-1]
            else:
                w = np.ones(wl)
            self.register_buffer("window", Tensor(w.astype(np.float32)))

        def forward(self, x):
            spec = _signal.stft(x, self.n_fft, self.hop_length,
                                window=self.window, center=self.center,
                                pad_mode=self.pad_mode)
            return apply(lambda s: jnp.abs(s) ** self.power, spec,
                         op_name="spec_power")

    class MelSpectrogram(nn.Layer):
        def __init__(self, sr=22050, n_fft=512, hop_length=None,
                     win_length=None, window="hann", power=2.0, center=True,
                     pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                     htk=False, norm="slaney", dtype="float32"):
            super().__init__()
            self.spectrogram = features.Spectrogram(
                n_fft, hop_length, win_length, window, power, center,
                pad_mode)
            self.register_buffer("fbank", functional.compute_fbank_matrix(
                sr, n_fft, n_mels, f_min, f_max, htk, norm))

        def forward(self, x):
            spec = self.spectrogram(x)
            return apply(lambda s, fb: jnp.einsum("...ft,mf->...mt", s, fb),
                         spec, self.fbank, op_name="mel_spec")

    class MFCC(nn.Layer):
        def __init__(self, sr=22050, n_mfcc=40, n_fft=512, hop_length=None,
                     n_mels=64, f_min=50.0, f_max=None, top_db=80.0,
                     dtype="float32", **kw):
            super().__init__()
            self.melspectrogram = features.MelSpectrogram(
                sr=sr, n_fft=n_fft, hop_length=hop_length, n_mels=n_mels,
                f_min=f_min, f_max=f_max)
            self.register_buffer("dct", functional.create_dct(n_mfcc,
                                                              n_mels))
            self.top_db = top_db

        def forward(self, x):
            mel = self.melspectrogram(x)
            db = functional.power_to_db(mel, top_db=self.top_db)
            return apply(lambda s, d: jnp.einsum("...mt,mk->...kt", s, d),
                         db, self.dct, op_name="mfcc")


class datasets:
    """Offline env: no downloadable audio datasets in-tree."""
