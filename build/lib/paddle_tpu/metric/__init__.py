"""paddle_tpu.metric (reference: python/paddle/metric/metrics.py)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


class Metric:
    def __init__(self):
        pass

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def compute(self, pred, label, *args):
        p = pred.numpy() if isinstance(pred, Tensor) else np.asarray(pred)
        l = label.numpy() if isinstance(label, Tensor) else np.asarray(label)
        if l.ndim == p.ndim and l.shape[-1] > 1:  # one-hot
            l = l.argmax(-1)
        l = l.reshape(-1)
        topk_idx = np.argsort(-p, axis=-1)[..., : self.maxk].reshape(
            -1, self.maxk)
        correct = topk_idx == l[:, None]
        return Tensor(correct.astype(np.float32))

    def update(self, correct, *args):
        c = correct.numpy() if isinstance(correct, Tensor) else \
            np.asarray(correct)
        accs = []
        for i, k in enumerate(self.topk):
            num = c[:, :k].sum()
            self.total[i] += num
            self.count[i] += c.shape[0]
            accs.append(num / c.shape[0])
        return accs[0] if len(accs) == 1 else accs

    def accumulate(self):
        out = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return out[0] if len(out) == 1 else out

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision"):
        super().__init__()
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = (preds.numpy() if isinstance(preds, Tensor)
             else np.asarray(preds)).reshape(-1)
        l = (labels.numpy() if isinstance(labels, Tensor)
             else np.asarray(labels)).reshape(-1)
        pred_pos = p > 0.5
        self.tp += int(np.sum(pred_pos & (l == 1)))
        self.fp += int(np.sum(pred_pos & (l == 0)))

    def accumulate(self):
        d = self.tp + self.fp
        return self.tp / d if d else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        super().__init__()
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = (preds.numpy() if isinstance(preds, Tensor)
             else np.asarray(preds)).reshape(-1)
        l = (labels.numpy() if isinstance(labels, Tensor)
             else np.asarray(labels)).reshape(-1)
        pred_pos = p > 0.5
        self.tp += int(np.sum(pred_pos & (l == 1)))
        self.fn += int(np.sum(~pred_pos & (l == 1)))

    def accumulate(self):
        d = self.tp + self.fn
        return self.tp / d if d else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        super().__init__()
        self.num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        p = preds.numpy() if isinstance(preds, Tensor) else np.asarray(preds)
        l = (labels.numpy() if isinstance(labels, Tensor)
             else np.asarray(labels)).reshape(-1)
        pos_prob = p[:, 1] if p.ndim == 2 else p.reshape(-1)
        bins = np.round(pos_prob * self.num_thresholds).astype(int)
        bins = np.clip(bins, 0, self.num_thresholds)
        for b, y in zip(bins, l):
            if y:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if not tot_pos or not tot_neg:
            return 0.0
        area = 0.0
        pos = neg = 0.0
        for i in range(self.num_thresholds, -1, -1):
            area += self._stat_pos[i] * (neg + self._stat_neg[i] / 2)
            pos += self._stat_pos[i]
            neg += self._stat_neg[i]
        return area / (tot_pos * tot_neg)

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    import jax.numpy as jnp

    from ..core.dispatch import apply

    def fn(p, l):
        topk = jnp.argsort(-p, axis=-1)[..., :k]
        ll = l.reshape(-1, 1)
        c = jnp.any(topk == ll, axis=-1)
        return jnp.mean(c.astype(jnp.float32))
    return apply(fn, input, label, op_name="accuracy", differentiable=False)
