"""paddle.save / paddle.load (reference: python/paddle/framework/io.py:743,985
— pickle-based nested state dicts)."""
from __future__ import annotations

import os
import pickle

import numpy as np

from ..core.tensor import Tensor

__all__ = ["save", "load"]

_PROTO = 4


def _to_storable(obj):
    if isinstance(obj, Tensor):
        return ("__tensor__", np.asarray(obj.numpy()))
    if isinstance(obj, dict):
        return {k: _to_storable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_to_storable(v) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    return obj


def _from_storable(obj, return_numpy=False):
    if isinstance(obj, tuple) and len(obj) == 2 and obj[0] == "__tensor__":
        return obj[1] if return_numpy else Tensor(obj[1])
    if isinstance(obj, dict):
        return {k: _from_storable(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_from_storable(v, return_numpy) for v in obj]
    if isinstance(obj, tuple):
        return tuple(_from_storable(v, return_numpy) for v in obj)
    return obj


def save(obj, path, protocol=_PROTO, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_storable(obj), f, protocol=protocol)


def load(path, return_numpy=False, **configs):
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _from_storable(obj, return_numpy=return_numpy)
