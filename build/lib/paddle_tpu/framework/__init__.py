from . import random
from .random import seed, get_rng_state, set_rng_state
