"""Default dtype registry (reference: paddle.set_default_dtype)."""
from __future__ import annotations

import numpy as np

from ..core.dtype import convert_dtype

_default_dtype = np.dtype(np.float32)


def get_default_dtype():
    return _default_dtype.name


def set_default_dtype(d):
    global _default_dtype
    _default_dtype = convert_dtype(d)
    return _default_dtype.name
