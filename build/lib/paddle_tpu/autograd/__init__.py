"""paddle_tpu.autograd (reference: python/paddle/autograd/)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.autograd import (backward, enable_grad, grad, is_grad_enabled,
                             no_grad, set_grad_enabled)
from ..core.dispatch import apply
from ..core.tensor import Tensor

__all__ = ["backward", "grad", "no_grad", "enable_grad", "set_grad_enabled",
           "is_grad_enabled", "PyLayer", "PyLayerContext", "jacobian",
           "hessian", "vjp", "jvp", "saved_tensors_hooks"]


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self.not_inplace_tensors = ()

    def save_for_backward(self, *tensors):
        self._saved = tensors

    @property
    def saved_tensor(self):
        return self._saved

    def saved_tensors(self):
        return self._saved

    def mark_not_inplace(self, *args):
        self.not_inplace_tensors = args


class PyLayerMeta(type):
    pass


class PyLayer(metaclass=PyLayerMeta):
    """User-defined autograd op (reference:
    python/paddle/autograd/py_layer.py). forward/backward are written against
    Tensors; the tape records a node whose pullback calls the user backward.

    This is the hook mechanism the distributed stack uses for TP/SP
    scatter-gather ops (reference mp_ops.py / sequence_parallel_utils.py)."""

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        from ..core import autograd as ag

        ctx = PyLayerContext()
        with ag.no_grad():
            outputs = cls.forward(ctx, *args, **kwargs)
        single = not isinstance(outputs, (tuple, list))
        out_list = [outputs] if single else list(outputs)

        record = ag.is_grad_enabled() and any(
            isinstance(a, Tensor) and not a.stop_gradient
            for a in jax.tree.leaves(
                (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor))
        )
        if not record:
            return outputs

        in_tensors = [
            a for a in jax.tree.leaves(
                (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor))
            if isinstance(a, Tensor) and not a.stop_gradient
        ]

        tensor_outs = [o for o in out_list if isinstance(o, Tensor)]

        def vjp_fn(cot_tree):
            cots = jax.tree.leaves(cot_tree)
            grad_in = cls.backward(
                ctx, *[Tensor(c, stop_gradient=True) for c in cots])
            if not isinstance(grad_in, (tuple, list)):
                grad_in = (grad_in,)
            flat = [g._value if isinstance(g, Tensor) else g
                    for g in grad_in if g is not None or True]
            # align with in_tensors: user returns one grad per forward
            # tensor input (reference contract)
            out = []
            gi = [g for g in grad_in]
            for i, t in enumerate(in_tensors):
                g = gi[i] if i < len(gi) else None
                out.append(None if g is None else
                           (g._value if isinstance(g, Tensor) else g))
            return tuple(out)

        out_avals = [jax.ShapeDtypeStruct(o._value.shape, o._value.dtype)
                     for o in tensor_outs]
        out_treedef = jax.tree.structure(
            [0] * len(tensor_outs))
        node = ag.GradNode(cls.__name__, vjp_fn, in_tensors, out_treedef,
                           out_avals)
        for i, o in enumerate(tensor_outs):
            o._grad_node = node
            o._out_index = i
            o.stop_gradient = False
            node.set_output(i, o)
        return outputs


class saved_tensors_hooks:
    def __init__(self, pack_hook, unpack_hook):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def _pure_fn(func, xs):
    """Build a pure jax function from a Tensor->Tensor callable."""
    def fn(*arrays):
        with no_grad():
            ins = [Tensor(a, stop_gradient=True) for a in arrays]
            out = func(*ins)
        if isinstance(out, (tuple, list)):
            return tuple(o._value for o in out)
        return out._value
    return fn


def jacobian(ys, xs, batch_axis=None):
    """Functional jacobian: ys is a function OR output tensors are not
    supported on the eager tape — use callable form (TPU-idiomatic)."""
    if callable(ys):
        func = ys
        single = isinstance(xs, Tensor)
        xs_list = [xs] if single else list(xs)
        fn = _pure_fn(func, xs_list)
        jac = jax.jacobian(fn, argnums=tuple(range(len(xs_list))))(
            *[x._value for x in xs_list])
        if single:
            return Tensor(jac[0])
        return [Tensor(j) for j in jac]
    raise NotImplementedError(
        "tensor-form jacobian requires create_graph; pass a callable instead")


def hessian(func, xs, batch_axis=None):
    single = isinstance(xs, Tensor)
    xs_list = [xs] if single else list(xs)
    fn = _pure_fn(func, xs_list)
    h = jax.hessian(fn, argnums=tuple(range(len(xs_list))))(
        *[x._value for x in xs_list])
    if single:
        return Tensor(h[0][0])
    return [[Tensor(hh) for hh in row] for row in h]


def vjp(func, xs, v=None):
    single = isinstance(xs, Tensor)
    xs_list = [xs] if single else list(xs)
    fn = _pure_fn(func, xs_list)
    out, vjp_fn = jax.vjp(fn, *[x._value for x in xs_list])
    if v is None:
        cot = jnp.ones_like(out) if not isinstance(out, tuple) else tuple(
            jnp.ones_like(o) for o in out)
    else:
        cot = v._value if isinstance(v, Tensor) else tuple(
            t._value for t in v)
    grads = vjp_fn(cot)
    outs = Tensor(out) if not isinstance(out, tuple) else [
        Tensor(o) for o in out]
    gs = [Tensor(g) for g in grads]
    return outs, (gs[0] if single else gs)


def jvp(func, xs, v=None):
    single = isinstance(xs, Tensor)
    xs_list = [xs] if single else list(xs)
    fn = _pure_fn(func, xs_list)
    primals = [x._value for x in xs_list]
    if v is None:
        tangents = [jnp.ones_like(p) for p in primals]
    else:
        vs = [v] if isinstance(v, Tensor) else list(v)
        tangents = [t._value for t in vs]
    out, tangent_out = jax.jvp(fn, tuple(primals), tuple(tangents))
    outs = Tensor(out) if not isinstance(out, tuple) else [
        Tensor(o) for o in out]
    touts = Tensor(tangent_out) if not isinstance(tangent_out, tuple) else [
        Tensor(t) for t in tangent_out]
    return outs, touts
