"""paddle_tpu.static — static-graph compatibility layer.

Reference analog: python/paddle/static/ (Program/Executor over the
PirInterpreter). On TPU, "static graph" IS the jit-compiled functional path
(paddle_tpu.jit), so this module provides the reference's static API surface
mapped onto it: InputSpec, name guards, and an Executor that runs compiled
StaticFunctions. Fleet-style static training scripts use
paddle.static.Executor(place).run(...) — supported for feed/fetch of
compiled programs.
"""
from __future__ import annotations

import contextlib

from ..core.place import CPUPlace, Place, TPUPlace
from ..core.tensor import Tensor
from ..jit.api import InputSpec

__all__ = ["InputSpec", "Program", "default_main_program",
           "default_startup_program", "program_guard", "Executor",
           "name_scope", "device_guard", "py_func", "nn", "gradients",
           "save", "load", "save_inference_model", "load_inference_model"]


class Program:
    """Compatibility shell. Captured computation lives in compiled
    StaticFunctions; Program tracks feed/fetch structure only."""

    def __init__(self):
        self.feed_targets = {}
        self.fetch_targets = []
        self._fn = None

    def global_block(self):
        return self

    def clone(self, for_test=False):
        return self


_main_program = Program()
_startup_program = Program()


def default_main_program():
    return _main_program


def default_startup_program():
    return _startup_program


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    global _main_program, _startup_program
    prev = (_main_program, _startup_program)
    _main_program = main_program
    if startup_program is not None:
        _startup_program = startup_program
    try:
        yield
    finally:
        _main_program, _startup_program = prev


@contextlib.contextmanager
def name_scope(prefix=None):
    yield


@contextlib.contextmanager
def device_guard(device=None):
    yield


class Executor:
    """reference: python/paddle/base/executor.py:1179. Runs compiled
    callables; `program` may be a Program shell, a StaticFunction, or any
    callable taking the feed dict."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None,
            return_numpy=True):
        feed = feed or {}
        target = program._fn if isinstance(program, Program) else program
        if target is None:
            return []
        inputs = [Tensor(v) for v in feed.values()]
        out = target(*inputs)
        outs = out if isinstance(out, (list, tuple)) else [out]
        if return_numpy:
            return [o.numpy() if isinstance(o, Tensor) else o for o in outs]
        return list(outs)

    def close(self):
        pass


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    from ..core.autograd import grad

    return grad(targets, inputs, grad_outputs=target_gradients,
                allow_unused=True)


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    raise NotImplementedError("py_func: wrap the python fn as an eager op")


def save(program, model_path, protocol=4):
    from ..framework.io import save as fsave

    fsave({"program": "static-shell"}, model_path)


def load(program, model_path, executor=None, var_list=None):
    from ..framework.io import load as fload

    return fload(model_path)


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor,
                         **kwargs):
    from ..framework.io import save as fsave

    fsave({"inference": True}, path_prefix + ".pdmodel")


def load_inference_model(path_prefix, executor, **kwargs):
    from ..framework.io import load as fload

    return fload(path_prefix + ".pdmodel"), [], []


class nn:
    """Minimal paddle.static.nn compat namespace."""

    @staticmethod
    def fc(x, size, num_flatten_dims=1, activation=None, name=None):
        raise NotImplementedError("use paddle_tpu.nn.Linear in 2.x style")
