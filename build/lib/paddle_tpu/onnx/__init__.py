"""paddle.onnx (reference: python/paddle/onnx/export.py + paddle2onnx).

TPU-native stance: the portable deploy interchange is StableHLO via
jax.export — the role the ONNX protobuf plays on the reference's CUDA
deployment path. `export` produces BOTH:

- the serving artifact (`<path>.pdmodel` / `.pdiparams` / `.pdconfig`) —
  the same multi-platform (cpu+tpu) serialized executable the inference
  Predictor loads in a fresh process (see paddle_tpu.inference), and
- human-readable StableHLO text (`<path>.stablehlo.mlir`) for inspection
  and for MLIR-based converters (StableHLO -> ONNX converters exist
  out-of-tree; classic in-process onnx protobuf emission needs the
  `onnx` package, which is not part of this environment).

Dynamic batch dims (InputSpec None dims) export as symbolic dimensions.
"""
from __future__ import annotations

__all__ = ["export", "load", "run"]


def export(layer, path, input_spec=None, opset_version=9,
           output_names=None, **configs):
    """Export `layer` for deployment; returns the artifact prefix."""
    if input_spec is None:
        raise ValueError("input_spec is required for export")
    from ..inference import save_inference_model

    save_inference_model(path, layer, input_spec,
                         output_names=output_names)
    # readable StableHLO text from the SAME lowering (no second trace):
    # deserialize the just-written artifact and dump its module
    from jax import export as jexport

    with open(path + ".pdmodel", "rb") as f:
        exported = jexport.deserialize(f.read())
    text = exported.mlir_module()
    with open(path + ".stablehlo.mlir", "w") as f:
        f.write(text if isinstance(text, str) else str(text))
    return path


def load(path):
    """Load an exported artifact; returns a Predictor (the fresh-process
    deploy contract — no model Python needed)."""
    from ..inference import Config, create_predictor

    return create_predictor(Config(path))


def run(path, inputs):
    """One-shot: load the artifact and run inference on numpy inputs."""
    return load(path).run(list(inputs))
