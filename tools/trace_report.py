"""Merge a host chrome-trace with a metrics snapshot into one report.

Inputs:
  --trace    chrome-trace JSON written by paddle.profiler.Profiler.export
             (traceEvents with ph="X" duration spans)
  --metrics  JSON snapshot written by paddle.profiler.metrics
             (snapshot_to_file / enable_periodic_flush / PT_METRICS_FLUSH_PATH)

Either input may be omitted; the report covers what it is given. Output
is a human-readable text report: a span summary table (calls, total,
avg, max per span name), the counters/gauges, and histogram summaries
with bucket-estimated p50/p95 — the triage view that answers "where did
the time go" without opening perfetto.

Usage:
  python tools/trace_report.py --trace /tmp/prof/worker.json \
      --metrics /tmp/metrics.json [-o report.txt]
"""
from __future__ import annotations

import argparse
import json
from collections import defaultdict

# The framework's metric-name inventory — the single known set shared by
# this report, the README "Observability" section, and the PT403 lint
# rule (paddle_tpu/analysis/registry_rules.py), which statically checks
# every literal metric name emitted in paddle_tpu/ against it. '*'
# entries cover dynamically-built families (f-string / concatenated
# names). Names outside this set render with an "(unknown)" marker below
# and fail ptlint at the emit site.
KNOWN_METRICS = (
    # op-dispatch funnel (core/dispatch.py, ops/registry.py)
    "dispatch/calls", "dispatch/cache_hit", "dispatch/cache_miss",
    "dispatch/uncacheable", "dispatch/cache_disabled_calls",
    "dispatch/cache_evictions", "dispatch/cache_fallbacks",
    # jit compile bridge (jit/api.py, jit/partial_capture.py)
    "jit/compile_count", "jit/compile_ms", "jit/retrace_count",
    "jit/retrace_cause/*", "jit/graph_break_count",
    "jit/partial_regions", "jit/partial_regions_installed",
    "jit/region_break_count",
    # collectives (distributed/collective.py)
    "comm/collective_count", "comm/collective_bytes", "comm/latency_ms",
    "comm/*_count", "comm/*_bytes",
    # collective-compute overlap (meta_parallel: stage-3 param prefetch,
    # latency-hidden pipeline sends / 1F1B hand-off windows)
    "comm/overlap_ms",
    # fusion compiler (static/passes.py auto_fuse + static/stablehlo.py)
    "compiler/fused_regions", "compiler/est_bytes_saved",
    "compiler/auto_fuse_ms", "compiler/stablehlo_emissions",
    # transport reliability + watchdog escalation
    # (distributed/transport.py, distributed/watchdog.py)
    "comm/retries", "comm/redials", "comm/corrupt_frames",
    "comm/dup_frames", "comm/watchdog_escalations",
    "comm/escalation_errors", "comm/escalation_store_errors",
    "comm/close_errors", "comm/peer_close_errors",
    "comm/recv_loop_close_errors",
    # elastic manager (distributed/elastic.py) + supervisor re-form
    "elastic/heartbeat_errors", "elastic/last_beat_ts",
    "elastic/membership_changes", "elastic/unhealthy_cleared",
    # host-level fault domains: quorum gate + generation fencing
    # (distributed/resilience/supervisor.py)
    "elastic/quorum_checks", "elastic/quorum_ok", "elastic/quorum_lost",
    "elastic/fenced_writes", "elastic/stale_snapshots_dropped",
    # replicated rendezvous store: hot standby + client failover
    # (distributed/store.py)
    "store/failovers", "store/redials", "store/tailer_drops",
    "store/replicated_records", "store/replication_naks",
    "store/standby_takeovers",
    # chaos injector (distributed/resilience/faults.py)
    "faults/injected", "faults/*",
    # self-healing training loop (distributed/resilience/supervisor.py
    # + guards.py): restarts/re-forms, recovery tiers, snapshot ring,
    # numerical-anomaly policy, SDC agreement probe
    "train/restarts", "train/reform_ms", "train/recovery_source/*",
    "train/steps", "train/snapshots", "train/snapshot_bytes",
    "train/replication_errors", "train/anomalies",
    "train/skipped_batches", "train/rollbacks", "train/sdc_flags",
    "train/step_ms",
    # checkpoint retention (distributed/resilience/recovery.py)
    "ckpt/pruned", "ckpt/swept_incomplete",
    # serving engine (inference/serving.py)
    "serving/ttft_ms", "serving/tpot_ms", "serving/steps",
    "serving/tokens_generated", "serving/requests",
    "serving/preemptions", "serving/batch_occupancy",
    "serving/kv_cache_utilization", "serving/deadline_evictions",
    "serving/load_shed",
    # fleet serving tier: shared-prefix KV reuse (inference/
    # prefix_cache.py), multi-replica routing (inference/router.py),
    # disaggregated prefill/decode hand-offs (inference/disagg.py)
    "serving/prefix_hit_rate", "serving/prefix_pages_reused",
    "serving/reroutes", "serving/requeues", "serving/migrations",
    # serving resilience tier (inference/fleet_supervisor.py + router
    # half-open circuit breaker + prefix-cache persistence)
    "serving/replica_failures", "serving/replica_restored",
    "serving/replica_restarts", "serving/drains",
    "serving/drain_requeues",
    # cross-host serving failover: off-host drain targets + real
    # TensorTransport KV hand-offs (inference/fleet_supervisor.py)
    "serving/cross_host_drains", "serving/cross_host_migrations",
    # bounded deadline-requeue retries (inference/router.py)
    "serving/requeue_exhausted",
    # overload-safe traffic tier: SLO-class admission, tenant fairness,
    # retry budget, brownout ladder (inference/gateway.py)
    "gateway/*",
    "serving/prefix_hits_restored", "serving/cache_restore_ms",
    "serving/cache_snapshots", "serving/cache_snapshots_swept",
    "serving/cache_snapshots_pruned",
    # speculative decoding (inference/speculative.py + serving.py
    # _spec_step): drafted/accepted token funnel + per-step yield
    "serving/spec_steps", "serving/spec_drafted_tokens",
    "serving/spec_accepted_tokens", "serving/spec_accept_rate",
    "serving/spec_tokens_per_step",
    # whole-iteration decode executables (decode windows + speculative
    # verify shapes) the engine compiled — the fused-decode region count
    "compiler/fused_decode_regions",
    # int8/int4 double-buffered weight streaming
    # (inference/weight_stream.py)
    "weights/stream_prefetch_ms",
    # live weight publishing (inference/weight_publish.py): per-engine
    # swap state + fleet rollout funnel (publishes / refusals / canary
    # verdicts / shipped bytes + wall time / restart catch-ups /
    # replicas that missed a rollout) and the speculative-drafter
    # hand-off across a swap (republish vs n-gram fallback, post-swap
    # accept-rate collapse alarms)
    "serving/weight_version", "serving/weight_swaps",
    "serving/weight_rollbacks", "serving/weight_publishes",
    "serving/publish_rejected", "serving/canary_failures",
    "serving/publish_bytes", "serving/publish_ms",
    "serving/publish_catchups", "serving/publish_missed",
    "serving/spec_drafter_republished", "serving/spec_drafter_fallbacks",
    "serving/spec_accept_alarms",
    # Executor-tier auto_fuse fallback (static/__init__.py)
    "compiler/executor_fuse_reverts",
    # IR-level program analyzer (paddle_tpu/analysis/program/)
    "analysis/programs_analyzed", "analysis/ops_analyzed",
    "analysis/findings", "analysis/peak_bytes",
    "analysis/verify_failures",
    # concurrency analyzer (ptrace: PT7xx races + PT8xx protocols)
    "analysis/conc_runs", "analysis/conc_findings",
    # sharding propagation (ptshard: PT9xx) + the static auto-tuner it
    # powers (distributed/auto_tuner/static_tuner.py)
    "analysis/shard_runs", "analysis/shard_findings",
    "analysis/tuner_configs_ranked", "analysis/tuner_rank_ms",
    # distributed tracing + crash flight recorder (profiler/tracing.py)
    "trace/*",
    # fleet metrics aggregation plane (profiler/aggregate.py):
    # snapshot shipping, replica census, clock-offset estimation
    "fleet/*", "fleet/stale_evictions",
    # SLO engine (profiler/timeline.py, slo.py, headroom.py): sampling
    # ring + spill, outcome accounting, burn alerts, scale advisories
    "timeline/*", "slo/*",
    # reason-coded gateway terminal outcomes (inference/gateway.py)
    "gateway/outcome/*",
    # elastic fleet resizing (inference/autoscaler.py): resize actions,
    # spawn retries, catch-up/drain latencies, freeze accounting
    "autoscale/actions", "autoscale/spawn_failures",
    "autoscale/catchup_ms", "autoscale/drain_ms",
    "autoscale/frozen_evals", "autoscale/fleet_size",
    # process-isolated replicas (inference/remote_replica.py): child
    # spawns, heartbeat-declared process deaths, orphan-sweep reaps
    "serving/replica_spawns", "serving/replica_process_deaths",
    "serving/orphans_reaped",
)


def _known(name: str) -> bool:
    import fnmatch

    return any(name == p or ("*" in p and fnmatch.fnmatchcase(name, p))
               for p in KNOWN_METRICS)


def _tag(name: str) -> str:
    return name if _known(name) else name + " (unknown)"


def summarize_trace(trace: dict) -> str:
    events = trace.get("traceEvents", [])
    agg = defaultdict(lambda: [0, 0.0, 0.0])        # calls, total_us, max_us
    for ev in events:
        if ev.get("ph") != "X":
            continue
        name = ev.get("name", "?")
        dur = float(ev.get("dur", 0.0))
        a = agg[name]
        a[0] += 1
        a[1] += dur
        if dur > a[2]:
            a[2] = dur
    if not agg:
        return "  (no duration spans in trace)"
    lines = [f"  {'Span':<44} {'Calls':>8} {'Total(ms)':>11} "
             f"{'Avg(ms)':>9} {'Max(ms)':>9}"]
    for name, (calls, total, mx) in sorted(agg.items(),
                                           key=lambda kv: -kv[1][1]):
        lines.append(f"  {name[:44]:<44} {calls:>8} {total / 1e3:>11.3f} "
                     f"{total / calls / 1e3:>9.3f} {mx / 1e3:>9.3f}")
    if trace.get("xplane_dir"):
        lines.append(f"  device XPlane dir: {trace['xplane_dir']}")
    return "\n".join(lines)


def _hist_quantile(h: dict, q: float):
    """Digest quantile when the snapshot carries one (exact-ish, the
    t-digest value computed registry-side), else bucket-estimated
    (upper bound of the covering bucket)."""
    key = {0.5: "p50", 0.95: "p95", 0.99: "p99"}.get(q)
    if key is not None and h.get(key) is not None:
        return h[key]
    total = h.get("count", 0)
    if not total:
        return None
    target = q * total
    acc = 0
    for bound, c in sorted(h.get("buckets", {}).items(),
                           key=lambda kv: float(kv[0])):
        acc += c
        if acc >= target:
            return float(bound)
    return h.get("max")


def summarize_metrics(snap: dict) -> str:
    lines = []
    counters = snap.get("counters", {})
    gauges = snap.get("gauges", {})
    hists = snap.get("histograms", {})
    if counters:
        lines.append("  Counters:")
        for name in sorted(counters):
            lines.append(f"    {_tag(name):<44} {counters[name]}")
    if gauges:
        lines.append("  Gauges:")
        for name in sorted(gauges):
            v = gauges[name]
            v = f"{v:.4f}" if isinstance(v, float) else v
            lines.append(f"    {_tag(name):<44} {v}")
    if hists:
        lines.append("  Histograms:")
        lines.append(f"    {'Name':<34} {'Count':>7} {'Avg':>10} "
                     f"{'Min':>10} {'~p50':>10} {'~p95':>10} {'Max':>10}")
        for name in sorted(hists):
            h = hists[name]

            def fmt(v):
                return f"{v:.3f}" if isinstance(v, (int, float)) else "-"

            lines.append(
                f"    {name[:34]:<34} {h.get('count', 0):>7} "
                f"{fmt(h.get('avg')):>10} {fmt(h.get('min')):>10} "
                f"{fmt(_hist_quantile(h, 0.5)):>10} "
                f"{fmt(_hist_quantile(h, 0.95)):>10} "
                f"{fmt(h.get('max')):>10}")
    return "\n".join(lines) if lines else "  (empty snapshot)"


def merge_traces(traces, offsets=None) -> dict:
    """Merge per-host chrome traces onto one timeline.

    `offsets` (seconds, one per trace; see
    paddle_tpu.profiler.aggregate.estimate_clock_offset) is ADDED to
    each trace's timestamps to land them on the reference host's clock.
    Span ids/trace ids pass through untouched — a request migrated
    between hosts keeps one trace id across the merged file."""
    out = {"traceEvents": [], "displayTimeUnit": "ms"}
    for i, tr in enumerate(traces):
        off_us = (offsets[i] if offsets and i < len(offsets) else 0.0) * 1e6
        for ev in tr.get("traceEvents", []):
            ev = dict(ev)
            if "ts" in ev:
                ev["ts"] = float(ev["ts"]) + off_us
            ev.setdefault("args", {})
            ev["args"].setdefault("source_trace", i)
            out["traceEvents"].append(ev)
    out["traceEvents"].sort(key=lambda e: e.get("ts", 0.0))
    return out


def trace_tree_check(trace: dict) -> dict:
    """Connectivity census over span ids: how many distinct trace ids,
    and which ones span more than one pid (a request that moved between
    engines/hosts but kept one trace id — the migration invariant)."""
    by_trace = defaultdict(set)
    for ev in trace.get("traceEvents", []):
        args = ev.get("args", {})
        tid = args.get("trace_id")
        if tid:
            by_trace[tid].add((ev.get("pid"), args.get("engine")))
    cross = sorted(t for t, owners in by_trace.items() if len(owners) > 1)
    return {"n_traces": len(by_trace), "cross_process": cross}


def straggler_section(snaps, metric: str = "train/step_ms",
                      factor: float = 1.5) -> str:
    """Per-rank p95 comparison across metrics snapshots: flag ranks
    whose `metric` p95 exceeds `factor` x the fleet median p95. Uses
    the digest percentiles embedded in each histogram snapshot."""
    rows = []
    for i, snap in enumerate(snaps):
        h = snap.get("histograms", {}).get(metric)
        if not h:
            continue
        who = snap.get("replica") or snap.get("namespace") \
            or f"snap{i}(pid{snap.get('pid')})"
        host = snap.get("host_id")
        if host:
            who = f"{host}/{who}"
        rows.append((who, h.get("count", 0), _hist_quantile(h, 0.5),
                     _hist_quantile(h, 0.95), h.get("max")))
    if not rows:
        return f"  (no {metric} histograms across snapshots)"
    p95s = sorted(r[3] for r in rows if r[3] is not None)
    median = p95s[len(p95s) // 2] if p95s else None
    lines = [f"  {'Rank':<30} {'Count':>7} {'p50':>10} {'p95':>10} "
             f"{'Max':>10}  flag"]
    for who, count, p50, p95, mx in sorted(rows):
        flag = "STRAGGLER" if (median and p95 is not None
                               and p95 > factor * median) else ""
        def fmt(v):
            return f"{v:.3f}" if isinstance(v, (int, float)) else "-"
        lines.append(f"  {who[:30]:<30} {count:>7} {fmt(p50):>10} "
                     f"{fmt(p95):>10} {fmt(mx):>10}  {flag}")
    if median is not None:
        lines.append(f"  (median p95 {median:.3f}, straggler threshold "
                     f"{factor:g}x = {factor * median:.3f})")
    return "\n".join(lines)


def build_report(trace: dict = None, metrics: dict = None) -> str:
    parts = ["paddle_tpu trace report", "=" * 70]
    if metrics is not None:
        ts = metrics.get("ts")
        head = "Metrics snapshot"
        if ts:
            import datetime

            head += " @ " + datetime.datetime.fromtimestamp(ts).isoformat()
        parts += [head, "-" * 70, summarize_metrics(metrics), ""]
    if trace is not None:
        parts += ["Host span summary", "-" * 70, summarize_trace(trace), ""]
    if trace is None and metrics is None:
        parts.append("(nothing to report: pass --trace and/or --metrics)")
    return "\n".join(parts)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--trace", action="append", default=[],
                    help="chrome-trace JSON (Profiler.export or "
                         "tracing.export_chrome); repeat for a "
                         "multi-host merge")
    ap.add_argument("--clock-offset", action="append", default=[],
                    type=float, metavar="SECONDS",
                    help="per --trace clock offset (aggregate."
                         "estimate_clock_offset), positional match; "
                         "missing entries default to 0")
    ap.add_argument("--metrics", action="append", default=[],
                    help="metrics snapshot JSON; repeat for a per-rank "
                         "straggler report")
    ap.add_argument("--straggler-metric", default="train/step_ms",
                    help="histogram compared across ranks "
                         "(default: train/step_ms)")
    ap.add_argument("--merged-trace", help="also write the merged "
                                           "chrome trace JSON here")
    ap.add_argument("-o", "--output", help="write report here "
                                           "(default: stdout)")
    args = ap.parse_args(argv)
    traces = []
    for path in args.trace:
        with open(path) as f:
            traces.append(json.load(f))
    snaps = []
    for path in args.metrics:
        with open(path) as f:
            snaps.append(json.load(f))
    trace = None
    if traces:
        trace = traces[0] if len(traces) == 1 \
            else merge_traces(traces, args.clock_offset)
    report = build_report(trace, snaps[0] if snaps else None)
    if len(snaps) > 1:
        report += "\n".join([
            "", f"Per-rank stragglers ({args.straggler_metric})",
            "-" * 70, straggler_section(snaps, args.straggler_metric), ""])
    if trace is not None and len(traces) > 1:
        tree = trace_tree_check(trace)
        report += "\n".join([
            "", "Merged-trace connectivity", "-" * 70,
            f"  {len(traces)} traces merged, {tree['n_traces']} distinct "
            f"trace ids, {len(tree['cross_process'])} spanning multiple "
            f"processes", ""])
    if args.merged_trace and trace is not None:
        with open(args.merged_trace, "w") as f:
            json.dump(trace, f)
    if args.output:
        with open(args.output, "w") as f:
            f.write(report + "\n")
    else:
        print(report)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
