"""Merge a host chrome-trace with a metrics snapshot into one report.

Inputs:
  --trace    chrome-trace JSON written by paddle.profiler.Profiler.export
             (traceEvents with ph="X" duration spans)
  --metrics  JSON snapshot written by paddle.profiler.metrics
             (snapshot_to_file / enable_periodic_flush / PT_METRICS_FLUSH_PATH)

Either input may be omitted; the report covers what it is given. Output
is a human-readable text report: a span summary table (calls, total,
avg, max per span name), the counters/gauges, and histogram summaries
with bucket-estimated p50/p95 — the triage view that answers "where did
the time go" without opening perfetto.

Usage:
  python tools/trace_report.py --trace /tmp/prof/worker.json \
      --metrics /tmp/metrics.json [-o report.txt]
"""
from __future__ import annotations

import argparse
import json
from collections import defaultdict

# The framework's metric-name inventory — the single known set shared by
# this report, the README "Observability" section, and the PT403 lint
# rule (paddle_tpu/analysis/registry_rules.py), which statically checks
# every literal metric name emitted in paddle_tpu/ against it. '*'
# entries cover dynamically-built families (f-string / concatenated
# names). Names outside this set render with an "(unknown)" marker below
# and fail ptlint at the emit site.
KNOWN_METRICS = (
    # op-dispatch funnel (core/dispatch.py, ops/registry.py)
    "dispatch/calls", "dispatch/cache_hit", "dispatch/cache_miss",
    "dispatch/uncacheable", "dispatch/cache_disabled_calls",
    "dispatch/cache_evictions", "dispatch/cache_fallbacks",
    # jit compile bridge (jit/api.py, jit/partial_capture.py)
    "jit/compile_count", "jit/compile_ms", "jit/retrace_count",
    "jit/retrace_cause/*", "jit/graph_break_count",
    "jit/partial_regions", "jit/partial_regions_installed",
    "jit/region_break_count",
    # collectives (distributed/collective.py)
    "comm/collective_count", "comm/collective_bytes", "comm/latency_ms",
    "comm/*_count", "comm/*_bytes",
    # collective-compute overlap (meta_parallel: stage-3 param prefetch,
    # latency-hidden pipeline sends / 1F1B hand-off windows)
    "comm/overlap_ms",
    # fusion compiler (static/passes.py auto_fuse + static/stablehlo.py)
    "compiler/fused_regions", "compiler/est_bytes_saved",
    "compiler/auto_fuse_ms", "compiler/stablehlo_emissions",
    # transport reliability + watchdog escalation
    # (distributed/transport.py, distributed/watchdog.py)
    "comm/retries", "comm/redials", "comm/corrupt_frames",
    "comm/dup_frames", "comm/watchdog_escalations",
    "comm/escalation_errors", "comm/escalation_store_errors",
    "comm/close_errors", "comm/peer_close_errors",
    "comm/recv_loop_close_errors",
    # elastic manager (distributed/elastic.py) + supervisor re-form
    "elastic/heartbeat_errors", "elastic/last_beat_ts",
    "elastic/membership_changes", "elastic/unhealthy_cleared",
    # host-level fault domains: quorum gate + generation fencing
    # (distributed/resilience/supervisor.py)
    "elastic/quorum_checks", "elastic/quorum_ok", "elastic/quorum_lost",
    "elastic/fenced_writes", "elastic/stale_snapshots_dropped",
    # replicated rendezvous store: hot standby + client failover
    # (distributed/store.py)
    "store/failovers", "store/redials", "store/tailer_drops",
    "store/replicated_records", "store/replication_naks",
    "store/standby_takeovers",
    # chaos injector (distributed/resilience/faults.py)
    "faults/injected", "faults/*",
    # self-healing training loop (distributed/resilience/supervisor.py
    # + guards.py): restarts/re-forms, recovery tiers, snapshot ring,
    # numerical-anomaly policy, SDC agreement probe
    "train/restarts", "train/reform_ms", "train/recovery_source/*",
    "train/steps", "train/snapshots", "train/snapshot_bytes",
    "train/replication_errors", "train/anomalies",
    "train/skipped_batches", "train/rollbacks", "train/sdc_flags",
    # checkpoint retention (distributed/resilience/recovery.py)
    "ckpt/pruned", "ckpt/swept_incomplete",
    # serving engine (inference/serving.py)
    "serving/ttft_ms", "serving/tpot_ms", "serving/steps",
    "serving/tokens_generated", "serving/requests",
    "serving/preemptions", "serving/batch_occupancy",
    "serving/kv_cache_utilization", "serving/deadline_evictions",
    "serving/load_shed",
    # fleet serving tier: shared-prefix KV reuse (inference/
    # prefix_cache.py), multi-replica routing (inference/router.py),
    # disaggregated prefill/decode hand-offs (inference/disagg.py)
    "serving/prefix_hit_rate", "serving/prefix_pages_reused",
    "serving/reroutes", "serving/requeues", "serving/migrations",
    # serving resilience tier (inference/fleet_supervisor.py + router
    # half-open circuit breaker + prefix-cache persistence)
    "serving/replica_failures", "serving/replica_restored",
    "serving/replica_restarts", "serving/drains",
    "serving/drain_requeues",
    # cross-host serving failover: off-host drain targets + real
    # TensorTransport KV hand-offs (inference/fleet_supervisor.py)
    "serving/cross_host_drains", "serving/cross_host_migrations",
    "serving/prefix_hits_restored", "serving/cache_restore_ms",
    "serving/cache_snapshots", "serving/cache_snapshots_swept",
    "serving/cache_snapshots_pruned",
    # int8 double-buffered weight streaming (inference/weight_stream.py)
    "weights/stream_prefetch_ms",
    # Executor-tier auto_fuse fallback (static/__init__.py)
    "compiler/executor_fuse_reverts",
    # IR-level program analyzer (paddle_tpu/analysis/program/)
    "analysis/programs_analyzed", "analysis/ops_analyzed",
    "analysis/findings", "analysis/peak_bytes",
    "analysis/verify_failures",
)


def _known(name: str) -> bool:
    import fnmatch

    return any(name == p or ("*" in p and fnmatch.fnmatchcase(name, p))
               for p in KNOWN_METRICS)


def _tag(name: str) -> str:
    return name if _known(name) else name + " (unknown)"


def summarize_trace(trace: dict) -> str:
    events = trace.get("traceEvents", [])
    agg = defaultdict(lambda: [0, 0.0, 0.0])        # calls, total_us, max_us
    for ev in events:
        if ev.get("ph") != "X":
            continue
        name = ev.get("name", "?")
        dur = float(ev.get("dur", 0.0))
        a = agg[name]
        a[0] += 1
        a[1] += dur
        if dur > a[2]:
            a[2] = dur
    if not agg:
        return "  (no duration spans in trace)"
    lines = [f"  {'Span':<44} {'Calls':>8} {'Total(ms)':>11} "
             f"{'Avg(ms)':>9} {'Max(ms)':>9}"]
    for name, (calls, total, mx) in sorted(agg.items(),
                                           key=lambda kv: -kv[1][1]):
        lines.append(f"  {name[:44]:<44} {calls:>8} {total / 1e3:>11.3f} "
                     f"{total / calls / 1e3:>9.3f} {mx / 1e3:>9.3f}")
    if trace.get("xplane_dir"):
        lines.append(f"  device XPlane dir: {trace['xplane_dir']}")
    return "\n".join(lines)


def _hist_quantile(h: dict, q: float):
    """Bucket-estimated quantile (upper bound of the covering bucket)."""
    total = h.get("count", 0)
    if not total:
        return None
    target = q * total
    acc = 0
    for bound, c in sorted(h.get("buckets", {}).items(),
                           key=lambda kv: float(kv[0])):
        acc += c
        if acc >= target:
            return float(bound)
    return h.get("max")


def summarize_metrics(snap: dict) -> str:
    lines = []
    counters = snap.get("counters", {})
    gauges = snap.get("gauges", {})
    hists = snap.get("histograms", {})
    if counters:
        lines.append("  Counters:")
        for name in sorted(counters):
            lines.append(f"    {_tag(name):<44} {counters[name]}")
    if gauges:
        lines.append("  Gauges:")
        for name in sorted(gauges):
            v = gauges[name]
            v = f"{v:.4f}" if isinstance(v, float) else v
            lines.append(f"    {_tag(name):<44} {v}")
    if hists:
        lines.append("  Histograms:")
        lines.append(f"    {'Name':<34} {'Count':>7} {'Avg':>10} "
                     f"{'Min':>10} {'~p50':>10} {'~p95':>10} {'Max':>10}")
        for name in sorted(hists):
            h = hists[name]

            def fmt(v):
                return f"{v:.3f}" if isinstance(v, (int, float)) else "-"

            lines.append(
                f"    {name[:34]:<34} {h.get('count', 0):>7} "
                f"{fmt(h.get('avg')):>10} {fmt(h.get('min')):>10} "
                f"{fmt(_hist_quantile(h, 0.5)):>10} "
                f"{fmt(_hist_quantile(h, 0.95)):>10} "
                f"{fmt(h.get('max')):>10}")
    return "\n".join(lines) if lines else "  (empty snapshot)"


def build_report(trace: dict = None, metrics: dict = None) -> str:
    parts = ["paddle_tpu trace report", "=" * 70]
    if metrics is not None:
        ts = metrics.get("ts")
        head = "Metrics snapshot"
        if ts:
            import datetime

            head += " @ " + datetime.datetime.fromtimestamp(ts).isoformat()
        parts += [head, "-" * 70, summarize_metrics(metrics), ""]
    if trace is not None:
        parts += ["Host span summary", "-" * 70, summarize_trace(trace), ""]
    if trace is None and metrics is None:
        parts.append("(nothing to report: pass --trace and/or --metrics)")
    return "\n".join(parts)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--trace", help="chrome-trace JSON (Profiler.export)")
    ap.add_argument("--metrics", help="metrics snapshot JSON")
    ap.add_argument("-o", "--output", help="write report here "
                                           "(default: stdout)")
    args = ap.parse_args(argv)
    trace = metrics = None
    if args.trace:
        with open(args.trace) as f:
            trace = json.load(f)
    if args.metrics:
        with open(args.metrics) as f:
            metrics = json.load(f)
    report = build_report(trace, metrics)
    if args.output:
        with open(args.output, "w") as f:
            f.write(report + "\n")
    else:
        print(report)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
