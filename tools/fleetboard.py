"""Fleet SLO dashboard: one terminal (or JSON) view of the serving
fleet's health — snapshot, per-tenant SLO attainment, active burn
alerts, capacity advice, and timeline sparklines.

Inputs (all optional; the board renders what it is given):
  --spill DIR    timeline spill directory (windows.jsonl + MANIFEST.json
                 written by profiler.timeline.Timeline) — manifest-gated
                 replay, torn tails ignored
  --slo FILE     SLOTracker.report() JSON (attainment + burn + alerts)
  --fleet FILE   FleetAggregator.fleet_snapshot() JSON
  --advice FILE  ScaleAdvisor recommend().to_dict() JSON
  --metric NAME  extra sparkline rows (repeatable; gauges plot the
                 sampled value, counters plot the per-window rate)
  --json         emit the merged machine-readable document instead

Deliberately importable without jax: the quantile sketch is loaded
straight from profiler/digest.py (dependency-free by design) so the
board runs on an ops box with no accelerator stack installed.

Usage:
  python tools/fleetboard.py --spill /var/pt/timeline --slo slo.json
"""
from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
from typing import List, Optional, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_digest_module():
    """profiler/digest.py without importing the jax-backed package."""
    path = os.path.join(_REPO, "paddle_tpu", "profiler", "digest.py")
    spec = importlib.util.spec_from_file_location("_pt_digest", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# -- spill replay (mirrors profiler.timeline.load_spill, jax-free) -----

def load_spill(path: str) -> List[dict]:
    """The complete prefix of windows the manifest published; [] for a
    spill with no manifest, torn tail lines ignored."""
    try:
        with open(os.path.join(path, "MANIFEST.json")) as f:
            man = json.load(f)
    except (OSError, ValueError):
        return []
    published = int(man.get("windows", 0))
    out: List[dict] = []
    try:
        f = open(os.path.join(path, "windows.jsonl"))
    except OSError:
        return []
    with f:
        for line in f:
            if len(out) >= published:
                break
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                break
    return out


# -- rendering ---------------------------------------------------------

SPARK = "▁▂▃▄▅▆▇█"


def sparkline(values: List[Optional[float]], width: int = 48) -> str:
    vals = [v for v in values if v is not None]
    if not vals:
        return "(no data)"
    if len(values) > width:
        values = values[-width:]
    lo, hi = min(vals), max(vals)
    span = hi - lo
    out = []
    for v in values:
        if v is None:
            out.append(" ")
        elif span <= 0:
            out.append(SPARK[0])
        else:
            idx = int((v - lo) / span * (len(SPARK) - 1))
            out.append(SPARK[idx])
    return "".join(out) + f"  [{lo:.3g} .. {hi:.3g}]"


def _series(windows: List[dict], name: str) -> List[Optional[float]]:
    """Gauge series, falling back to the counter's per-window rate."""
    if any(name in w.get("gauges", {}) for w in windows):
        return [w.get("gauges", {}).get(name) for w in windows]
    out: List[Optional[float]] = []
    for a, b in zip(windows, windows[1:]):
        dt = b["t"] - a["t"]
        if dt <= 0:
            out.append(None)
            continue
        out.append((b.get("counters", {}).get(name, 0)
                    - a.get("counters", {}).get(name, 0)) / dt)
    return out


def _window_p95(windows: List[dict], name: str, digest_mod) -> List[
        Optional[float]]:
    out: List[Optional[float]] = []
    for w in windows:
        d = w.get("digests", {}).get(name)
        if not d:
            out.append(None)
        elif "p95" in d:                    # recent()-style summary
            out.append(d["p95"])
        else:
            out.append(digest_mod.QuantileDigest.from_dict(d)
                       .quantile(0.95))
    return out


DEFAULT_METRICS = ("gateway/load_score", "gateway/brownout_level",
                   "gateway/outcome/completed")


def _autoscaler_panel(windows: List[dict]) -> List[str]:
    """Resize history from the timeline's autoscale_* events: the
    last executed action, the current fleet size it left behind, and
    any replica stuck DRAINING (an autoscale_draining event with no
    matching retirement — the RUNBOOK's stuck-drain walk starts
    here)."""
    evs = [ev for w in windows for ev in w.get("events", ())
           if str(ev.get("kind", "")).startswith("autoscale")]
    if not evs:
        return []
    lines = ["autoscaler:"]
    actions = [e for e in evs if e.get("kind") == "autoscale_action"]
    if actions:
        last = actions[-1]
        lines.append(f"  last action: {last.get('action')} "
                     f"{last.get('replica', '?')} -> fleet size "
                     f"{last.get('size', '?')} "
                     f"({last.get('reason', '')})")
    frozen = [e for e in evs if e.get("kind") == "autoscale_frozen"]
    if frozen:
        lines.append(f"  frozen evals: {len(frozen)} "
                     f"(last: {frozen[-1].get('reason')})")
    failed = [e for e in evs
              if e.get("kind") in ("autoscale_spawn_retry",
                                   "autoscale_spawn_failed")]
    if failed:
        lines.append(f"  spawn retries/failures: {len(failed)} "
                     f"(last: {failed[-1].get('kind')})")
    retired = {e.get("replica") for e in evs
               if e.get("kind") == "autoscale_action"
               and e.get("action") == "scale_down"}
    stuck = [e.get("replica") for e in evs
             if e.get("kind") == "autoscale_draining"
             and e.get("replica") not in retired]
    if stuck:
        lines.append("  STUCK DRAINING: " + ", ".join(
            str(s) for s in stuck))
    return lines


def render(windows: List[dict], slo: Optional[dict] = None,
           fleet: Optional[dict] = None, advice: Optional[dict] = None,
           metrics: Tuple[str, ...] = ()) -> str:
    digest_mod = _load_digest_module()
    lines: List[str] = ["== fleetboard =="]

    if fleet:
        lines.append(f"fleet: {fleet.get('n_replicas', '?')} replicas")
        for key, rep in sorted(fleet.get("replicas", {}).items()):
            gauges = rep.get("gauges", {})
            load = gauges.get("gateway/load_score") \
                or gauges.get("serving/load_score")
            lines.append(f"  {key:<28} load="
                         f"{load if load is not None else '-'}")

    if windows:
        span = windows[-1]["t"] - windows[0]["t"]
        lines.append(f"timeline: {len(windows)} windows over "
                     f"{span:.1f}s (seq {windows[0]['seq']}.."
                     f"{windows[-1]['seq']})")
        names = list(DEFAULT_METRICS) + [m for m in metrics
                                         if m not in DEFAULT_METRICS]
        for name in names:
            vals = _series(windows, name)
            if any(v is not None for v in vals):
                lines.append(f"  {name:<32} {sparkline(vals)}")
        hist_names = sorted({n for w in windows
                             for n in w.get("digests", {})})
        for name in hist_names:
            vals = _window_p95(windows, name, digest_mod)
            if any(v is not None for v in vals):
                lines.append(f"  {name + ' p95':<32} {sparkline(vals)}")
        evs = [ev for w in windows for ev in w.get("events", ())]
        if evs:
            lines.append(f"  events: {len(evs)} "
                         f"(last: {evs[-1].get('kind')})")

    if slo:
        lines.append("slo attainment (tenant/class  att  target  "
                     "fast-burn  alert):")
        for key, row in sorted(slo.get("per_tenant", {}).items()):
            att = row.get("attainment")
            lines.append(
                f"  {key:<28} "
                f"{att if att is not None else '-':<8} "
                f"{row.get('target', '-'):<8} "
                f"{row.get('fast_burn', '-'):<10} "
                f"{'ACTIVE' if row.get('alert_active') else '-'}")
        al = slo.get("alerts", {})
        lines.append(f"alerts: raised={al.get('raised', 0)} "
                     f"active={al.get('active', 0)} "
                     f"cleared={al.get('cleared', 0)}")
        for a in al.get("log", ()):
            state = "ACTIVE" if a.get("active") else "cleared"
            lines.append(f"  [{state}] {a.get('tenant')}/"
                         f"{a.get('slo_class')} fast_burn="
                         f"{a.get('fast_burn')} raised_t="
                         f"{a.get('raised_t')}")

    if advice:
        lines.append(f"advice: {advice.get('action', '?').upper()} — "
                     f"{advice.get('reason', '')}")
        lines.append(f"  load={advice.get('current_load')} "
                     f"headroom={advice.get('headroom')} "
                     f"knee={advice.get('saturation_load')}")
        if advice.get("drain_candidates"):
            lines.append("  drain: "
                         + ", ".join(advice["drain_candidates"]))

    auto = _autoscaler_panel(windows)
    if auto:
        lines.extend(auto)

    if len(lines) == 1:
        lines.append("(no inputs — pass --spill/--slo/--fleet/--advice)")
    return "\n".join(lines)


def _read_json(path: Optional[str]) -> Optional[dict]:
    if not path:
        return None
    with open(path) as f:
        return json.load(f)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--spill", help="timeline spill directory")
    ap.add_argument("--slo", help="SLOTracker.report() JSON file")
    ap.add_argument("--fleet", help="fleet_snapshot() JSON file")
    ap.add_argument("--advice", help="ScaleAdvice JSON file")
    ap.add_argument("--metric", action="append", default=[],
                    help="extra sparkline metric (repeatable)")
    ap.add_argument("--json", action="store_true",
                    help="emit the merged document as JSON")
    ap.add_argument("-o", "--output", help="write to file instead")
    args = ap.parse_args(argv)

    windows = load_spill(args.spill) if args.spill else []
    slo = _read_json(args.slo)
    fleet = _read_json(args.fleet)
    advice = _read_json(args.advice)

    if args.json:
        text = json.dumps({"windows": windows, "slo": slo,
                           "fleet": fleet, "advice": advice}, indent=2)
    else:
        text = render(windows, slo, fleet, advice,
                      metrics=tuple(args.metric))
    if args.output:
        with open(args.output, "w") as f:
            f.write(text + "\n")
    else:
        print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
