#!/usr/bin/env python
"""ptlint — standalone entry point for the paddle_tpu static-analysis
suite (equivalent to ``python -m paddle_tpu.analysis``).

Loads the analysis package directly from source files so it runs even
when paddle_tpu isn't installed and without importing the framework
(no jax import — the linter stays milliseconds-fast in CI).

Usage:
  python tools/ptlint.py paddle_tpu/
  python tools/ptlint.py paddle_tpu/ --format json     # or sarif
  python tools/ptlint.py paddle_tpu/ --update-baseline # prune stale
  python tools/ptlint.py --list-rules

For the IR-level Program analyzer (PT6xx, needs jax) use
tools/ptprog.py / ``python -m paddle_tpu.analysis --program``.
"""
import importlib.util
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_analysis():
    """Import paddle_tpu.analysis as a detached package (skipping
    paddle_tpu/__init__.py and its jax import)."""
    pkg_dir = os.path.join(_REPO, "paddle_tpu", "analysis")
    spec = importlib.util.spec_from_file_location(
        "paddle_tpu.analysis", os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir])
    # a stub parent keeps the relative imports inside the package working
    import types

    parent = types.ModuleType("paddle_tpu")
    parent.__path__ = [os.path.join(_REPO, "paddle_tpu")]
    sys.modules.setdefault("paddle_tpu", parent)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["paddle_tpu.analysis"] = mod
    spec.loader.exec_module(mod)
    return mod


if __name__ == "__main__":
    sys.exit(_load_analysis().main())
