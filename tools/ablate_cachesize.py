"""Does decode-step cost scale with PAGE-POOL size? If yes, something
copies the whole cache per step (scan-carry aliasing failure); if no,
the cost is per-token attention work. Two engines, same model, same
max_blocks_per_seq, different num_blocks. Prints one JSON line."""
import json
import time

import numpy as np
import jax
import jax.numpy as jnp


def main():
    import paddle_tpu as paddle
    from paddle_tpu.inference import serving as S

    B, prompt_len = 16, 64
    paddle.seed(0)
    base = S.PagedServingConfig.llama_1b(max_batch=B)
    with jax.default_device(jax.devices("cpu")[0]):
        model = S.PagedCausalLM(base)
    model.eval()
    rng = np.random.RandomState(0)
    sp = S.SamplingParams(temperature=0.8, top_k=50, top_p=0.95)
    res = {}
    for tag, nb in (("small", B * 5 + 8), ("large", B * 15 + 8)):
        cfg = S.PagedServingConfig.llama_1b(max_batch=B, num_blocks=nb)
        model._serving_shared = None   # page-pool size changes shapes
        eng = S.ServingEngine.from_model(model, cfg, seed=0)
        for _ in range(B):
            eng.add_request(list(rng.randint(1, cfg.vocab_size,
                                             prompt_len)),
                            max_new_tokens=126, sampling=sp)
        while any(r.length - r.cached > 1 for r in eng.pending()):
            eng.step()
        eng.decode_run(2)
        pts = []
        for n in (8, 32):
            dt = float("inf")
            for _ in range(2):
                t0 = time.perf_counter()
                out = eng.decode_run(n)
                assert len(out) == n * B, (len(out), n * B)
                dt = min(dt, time.perf_counter() - t0)
            pts.append((n, dt))
        (n1, d1), (n2, d2) = pts
        slope = (d2 - d1) / (n2 - n1)
        res[f"{tag}_pool_pages"] = nb
        res[f"{tag}_ms_per_step_slope"] = round(slope * 1e3, 3)
        cache_gb = 2 * 16 * nb * 8 * 32 * 128 * 2 / 1e9
        res[f"{tag}_cache_gb"] = round(cache_gb, 3)
    print(json.dumps(res))


if __name__ == "__main__":
    main()
