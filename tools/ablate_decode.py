"""Decode-step ablation on the real chip (VERDICT r4 weak #1).

Times each component of the bs-16 flagship decode step in isolation:
  full      — the engine's decode_run window (reproduces BENCH step_ms)
  greedy    — same window with greedy sampling (isolates the sampler)
  no_attn   — block_multihead_attention stubbed to a pass-through
              (isolates the paged-cache gather + attention math)
  weights   — bare 16-layer matmul stack on T=16 tokens in a 16-step
              scan (the weight-streaming floor as XLA actually runs it)
  sampler   — 16-step scan of the top-k sampler alone on [17, 32000]

Run on an idle host. Prints one JSON line.
"""
import functools
import json
import time

import numpy as np
import jax
import jax.numpy as jnp


def _sync(out):
    """Force a REAL device sync: block_until_ready can no-op over the
    tunnel; fetching a scalar reduction cannot."""
    leaves = [x for x in jax.tree_util.tree_leaves(out)
              if hasattr(x, "dtype")]
    if leaves:   # engine paths sync internally (np.asarray of samples)
        jax.device_get(jnp.sum(leaves[-1].astype(jnp.float32)))


def timed(fn, n=2):
    _sync(fn())  # warm/compile
    best = float("inf")
    for _ in range(n):
        t0 = time.perf_counter()
        out = fn()
        _sync(out)
        best = min(best, time.perf_counter() - t0)
    return best


def main():
    import sys
    stages = set(sys.argv[1:]) or {"full", "greedy", "no_attn", "weights",
                                   "sampler"}
    import paddle_tpu as paddle
    from paddle_tpu.inference import serving as S

    # prompt 64 (not the bench's 128): per-step cost is STATIC-shape
    # (gather + attention always run at max_seq), so a shorter prompt
    # changes nothing per-step but leaves max_new room for the window
    # sweep inside the 6-page/seq budget
    B, win, prompt_len = 16, 32, 64
    paddle.seed(0)
    cfg = S.PagedServingConfig.llama_1b(max_batch=B, num_blocks=B * 6 + 16)
    model = None
    if stages & {"full", "greedy", "no_attn"}:
        with jax.default_device(jax.devices("cpu")[0]):
            model = S.PagedCausalLM(cfg)
        model.eval()
    rng = np.random.RandomState(0)
    sp = S.SamplingParams(temperature=0.8, top_k=50, top_p=0.95)

    def mk_engine(m):
        eng = S.ServingEngine.from_model(m, cfg, seed=0)
        for _ in range(B):
            eng.add_request(list(rng.randint(1, cfg.vocab_size, prompt_len)),
                            max_new_tokens=126, sampling=sp)
        while any(r.length - r.cached > 1 for r in eng.pending()):
            eng.step()
        return eng

    res = {}

    # -- full window sweep ------------------------------------------------
    # One decode_run(n) is one dispatch + one sync; the tunnel sync alone
    # costs ~100 ms, so a single window size conflates per-step cost with
    # per-window overhead. Sweep n and fit the slope: per_step = the real
    # device time, intercept = dispatch+sync overhead per window.
    if "full" in stages:
        eng = mk_engine(model)
        eng.decode_run(2)  # warm
        pts = []
        for n in (8, 32):
            dt = timed(lambda: eng.decode_run(n) or eng._kc)
            pts.append((n, dt))
            res[f"full_win{n}_ms_per_step"] = round(dt / n * 1e3, 3)
        (n1, d1), (n2, d2) = pts
        slope = (d2 - d1) / (n2 - n1)
        res["full_ms_per_step_slope"] = round(slope * 1e3, 3)
        res["full_window_overhead_ms"] = round((d1 - slope * n1) * 1e3, 2)

    # -- greedy window (no top-k sampler) ---------------------------------
    if "greedy" in stages:
        eng2 = S.ServingEngine.from_model(model, cfg, seed=0)
        for _ in range(B):
            eng2.add_request(
                list(rng.randint(1, cfg.vocab_size, prompt_len)),
                max_new_tokens=126, sampling=S.GREEDY)
        while any(r.length - r.cached > 1 for r in eng2.pending()):
            eng2.step()
        eng2.decode_run(2)
        dt = timed(lambda: eng2.decode_run(win) or eng2._kc)
        res["greedy_ms_per_step"] = round(dt / win * 1e3, 3)

    # -- no-attention window ---------------------------------------------
    if "no_attn" in stages:
        from paddle_tpu.incubate.nn import functional as IF
        orig = IF.block_multihead_attention

        def stub(qkv, kc, vc, *a, layer_idx=None, **kw):
            def fn(q):
                D = cfg.head_dim
                HQ, HKV = cfg.num_heads, cfg.num_kv_heads
                return q[:, :HQ * D]
            from paddle_tpu.core.dispatch import apply
            return apply(fn, qkv, op_name="attn_stub"), qkv, kc, vc

        IF.block_multihead_attention = stub
        try:
            with jax.default_device(jax.devices("cpu")[0]):
                model2 = S.PagedCausalLM(cfg)
            model2.eval()
            eng3 = mk_engine(model2)
            eng3.decode_run(2)
            dt = timed(lambda: eng3.decode_run(win) or eng3._kc)
            res["no_attn_ms_per_step"] = round(dt / win * 1e3, 3)
        finally:
            IF.block_multihead_attention = orig

    if not stages & {"weights", "sampler"}:
        dev = jax.devices()[0]
        res["device"] = str(getattr(dev, "device_kind", dev))
        print(json.dumps(res))
        return

    # -- bare weight-streaming scan --------------------------------------
    h, f, V = cfg.hidden_size, cfg.ffn_size, cfg.vocab_size
    L = cfg.num_layers
    key = jax.random.key(0)
    if "weights" in stages:
        Ws = _make_ws(cfg, key)

        # Ws must be jit ARGUMENTS: closed-over they become HLO literal
        # constants and the remote compile ships 1.77 GB of proto
        def wstep(ws, x, _):
            def layer(xc, w):
                qkvw, projw, guw, downw = w
                a = xc @ qkvw
                xc = xc + a[:, :h] @ projw
                g = xc @ guw
                xc = xc + (jax.nn.silu(g[:, :f]) * g[:, f:]) @ downw
                return xc, None
            x, _ = jax.lax.scan(layer, x,
                                (ws["qkv"], ws["proj"], ws["gu"],
                                 ws["down"]))
            logits = x @ ws["head"]
            nxt = jnp.argmax(logits, -1).astype(jnp.int32)
            return ws["emb"][nxt], nxt

        x0 = jnp.zeros((B, h), jnp.bfloat16)
        pts = []
        for n in (win, 4 * win):
            wrun = jax.jit(functools.partial(
                lambda ln, ws, x: jax.lax.scan(
                    lambda c, u: wstep(ws, c, u), x, None, length=ln), n))
            dt = timed(lambda: wrun(Ws, x0))
            pts.append((n, dt))
            res[f"weights_win{n}_ms_per_step"] = round(dt / n * 1e3, 3)
        (n1, d1), (n2, d2) = pts
        slope = (d2 - d1) / (n2 - n1)
        res["weights_ms_per_step_slope"] = round(slope * 1e3, 3)

    if "sampler" in stages:
        logits = jax.device_put(
            jax.random.normal(key, (B + 1, V), jnp.float32))
        temps = jnp.full((B + 1,), 0.8, jnp.float32)
        topks = jnp.full((B + 1,), 50, jnp.int32)
        topps = jnp.full((B + 1,), 0.95, jnp.float32)

        def srun(ln, lg):
            def body(c, j):
                salts = jnp.full((B + 1,), j, jnp.int32)
                s = S._sample_topk_core(lg + c[:, None] * 0, temps, topks,
                                        topps, salts)
                return s, s
            return jax.lax.scan(body, jnp.zeros((B + 1,), jnp.int32),
                                jnp.arange(ln))
        pts = []
        for n in (win, 4 * win):
            srun_j = jax.jit(functools.partial(srun, n))
            dt = timed(lambda: srun_j(logits))
            pts.append((n, dt))
            res[f"sampler_win{n}_ms_per_step"] = round(dt / n * 1e3, 3)
        (n1, d1), (n2, d2) = pts
        res["sampler_ms_per_step_slope"] = round(
            (d2 - d1) / (n2 - n1) * 1e3, 3)

    dev = jax.devices()[0]
    res["device"] = str(getattr(dev, "device_kind", dev))
    print(json.dumps(res))


def _make_ws(cfg, key):
    h, f, V = cfg.hidden_size, cfg.ffn_size, cfg.vocab_size
    L = cfg.num_layers
    Ws = {
        "qkv": jnp.zeros((L, h, h + 2 * cfg.num_kv_heads * cfg.head_dim),
                         jnp.bfloat16),
        "proj": jnp.zeros((L, h, h), jnp.bfloat16),
        "gu": jnp.zeros((L, h, 2 * f), jnp.bfloat16),
        "down": jnp.zeros((L, f, h), jnp.bfloat16),
        "head": jnp.zeros((h, V), jnp.bfloat16),
        "emb": jnp.zeros((V, h), jnp.bfloat16),
    }
    return jax.tree_util.tree_map(
        lambda a: jax.device_put(
            jax.random.normal(key, a.shape, jnp.float32).astype(a.dtype)
            * 0.02, jax.devices()[0]), Ws)


if __name__ == "__main__":
    main()
