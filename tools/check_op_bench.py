"""Regression gate over two op_bench.py runs (reference analog:
tools/check_op_benchmark_result.py). Fails (exit 1) if any op slowed by
more than --threshold (default 1.5x).

Usage: python tools/check_op_bench.py baseline.json current.json [--threshold 1.15]
"""
import json
import sys


def main():
    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    thr = 1.5
    for a in sys.argv[1:]:
        if a.startswith("--threshold"):
            thr = float(a.split("=", 1)[1]) if "=" in a else thr
    base = json.load(open(args[0]))["ops"]
    cur = json.load(open(args[1]))["ops"]
    failures = []
    for name, t0 in base.items():
        t1 = cur.get(name)
        if t1 is None or t0 <= 0:
            continue
        ratio = t1 / t0
        mark = "SLOWER" if ratio > thr else "ok"
        print(f"{name:24s} {t0:.6f}s -> {t1:.6f}s  x{ratio:.3f}  {mark}")
        if ratio > thr:
            failures.append((name, ratio))
    # absolute bars for the eager dispatch rows (VERDICT r3 #2 "done"
    # criteria: fwd <= 100 us, fwd+bwd <= 300 us). They gate the
    # HOST-PATH rows — the tunneled-device rows include ~85 us/enqueue
    # of relay RPC that no dispatch work can remove (a local chip has
    # none). 2x headroom before failing; raw numbers printed either way.
    bars = {"eager:host_fwd": 100e-6,
            "eager:host_fwd_bwd": 300e-6}
    for name, bar in bars.items():
        t = cur.get(name)
        if t is None:
            # a missing gated row must not silently pass the bar
            print(f"{name:24s} MISSING — absolute bar not evaluated")
            failures.append((name, float("inf")))
            continue
        status = "ok" if t <= bar else (
            "WARN (tunnel noise?)" if t <= 2 * bar else "FAIL")
        print(f"{name:24s} {t * 1e6:8.1f} us  bar {bar * 1e6:.0f} us  "
              f"{status}")
        if status == "FAIL":
            failures.append((name, t / bar))
    if failures:
        print(f"FAIL: {len(failures)} op(s) regressed beyond x{thr}")
        sys.exit(1)
    print("PASS")


if __name__ == "__main__":
    main()
