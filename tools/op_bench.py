"""Per-op microbenchmark (reference analog: tools/ci_op_benchmark.sh —
a relative regression gate over op kernels).

Times a representative set of registered ops under jit on the attached
device and writes JSON: {"device": ..., "ops": {name: sec_per_call}}.
Compare two runs with tools/check_op_bench.py.

Usage: python tools/op_bench.py [out.json]
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def _bench(fn, *args, chain=50, repeats=5):
    """Time `fn` with the op CHAINED inside one compiled scan — a single
    dispatch per measurement, so device compute dominates instead of the
    host/tunnel latency (which would swamp ~µs ops and make the
    regression gate pure noise). Returns min over repeats."""
    def chained(*a):
        def body(carry, _):
            # thread the carry into the first float operand so the op is
            # loop-VARIANT — otherwise XLA CSE-hoists it and the scan
            # times an empty loop
            a2 = list(a)
            for i, arr in enumerate(a2):
                if jnp.issubdtype(arr.dtype, jnp.floating):
                    a2[i] = arr + carry.astype(arr.dtype)
                    break
            out = fn(*a2)
            leaf = jax.tree_util.tree_leaves(out)[0]
            return (carry + jnp.sum(leaf).astype(jnp.float32) * 1e-30,
                    None)

        total, _ = jax.lax.scan(body, jnp.float32(0), None, length=chain)
        return total

    jitted = jax.jit(chained)
    # device_get, not block_until_ready: the latter is unreliable through
    # the tunneled TPU relay and returns before compute finishes
    jax.device_get(jitted(*args))           # compile + warm
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        jax.device_get(jitted(*args))
        best = min(best, (time.perf_counter() - t0) / chain)
    return best


def main():
    import paddle_tpu  # noqa: F401  (registers ops)
    from paddle_tpu.ops import registry

    rng = np.random.RandomState(0)
    m = jnp.asarray(rng.randn(1024, 1024).astype(np.float32))
    v = jnp.asarray(rng.randn(1024, 4096).astype(np.float32))
    x4 = jnp.asarray(rng.randn(8, 64, 56, 56).astype(np.float32))
    ids = jnp.asarray(rng.randint(0, 1000, (64, 512)))

    cases = {
        "matmul": (lambda a, b: a @ b, m, v),
        "softmax": (lambda a: jax.nn.softmax(a, -1), v),
        "layer_norm": (lambda a: (a - a.mean(-1, keepdims=True))
                       / (a.std(-1, keepdims=True) + 1e-5), v),
        "gelu": (jax.nn.gelu, v),
        "reduce_sum": (lambda a: a.sum(), v),
        "transpose": (lambda a: a.T, m),
        "embedding_gather": (lambda t, i: t[i], m, ids),
        "conv_relu": (lambda a: jax.nn.relu(
            jax.lax.conv_general_dilated(
                a, jnp.ones((64, 64, 3, 3), jnp.float32) * 0.01,
                (1, 1), "SAME",
                dimension_numbers=("NCHW", "OIHW", "NCHW"))), x4),
    }
    # a sample of registry kernels exercised through the yaml surface
    reg_cases = {
        "p_norm": ((2.0, -1), v),
        "clip_by_norm": ((1.0,), v),
        "frobenius_norm": ((), m),
    }
    results = {}
    for name, (fn, *args) in cases.items():
        results[name] = _bench(fn, *args)
    for name, (extra, arr) in reg_cases.items():
        info = registry.get(name)
        if info is not None:
            results[f"op:{name}"] = _bench(
                lambda a, _f=info.fn, _e=extra: _f(a, *_e), arr)
    results.update(_bench_eager_dispatch())

    out = {"device": str(jax.devices()[0]),
           "backend": jax.default_backend(),
           "ops": {k: round(v, 6) for k, v in results.items()}}
    args = [a for a in sys.argv[1:] if not a.startswith("-")]
    if any(a in ("-h", "--help") for a in sys.argv[1:]):
        print(__doc__)
        sys.exit(0)
    path = args[0] if args else "op_bench.json"
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))


def _bench_eager_dispatch():
    """Steady-state eager dispatch through the per-signature jit cache
    (regression gate for VERDICT r2 #1 — uncached this was 5,447 µs/iter
    on a v5e for grad-recorded matmul(1024²)+add)."""
    import paddle_tpu as paddle

    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(1024, 1024).astype(np.float32))
    y = paddle.to_tensor(rng.randn(1024, 1024).astype(np.float32))
    x.stop_gradient = False

    def fwd():
        return (paddle.matmul(x, y) + x)._value

    def fwdbwd():
        z = (paddle.matmul(x, y) + x).sum()
        z.backward()
        g = x.grad._value
        x.clear_grad()
        return g

    out = {}
    for name, f in (("eager:matmul_add_fwd", fwd),
                    ("eager:matmul_add_fwd_bwd", fwdbwd)):
        for _ in range(6):
            jax.device_get(f())          # legacy + trace + steady warmup
        n = 50
        best = float("inf")
        for _ in range(3):
            jax.device_get(f())          # drain: sync outside the window
            t0 = time.perf_counter()
            for _ in range(n):
                f()
            best = min(best, (time.perf_counter() - t0) / n)
        out[name] = best

    # host-path rows (tunnel-free): the 100/300 us bars in
    # check_op_bench.py gate these — the tunneled-device rows above
    # carry ~85 us/enqueue of relay RPC no host work can remove
    import bench as _bench

    def measure_us(f):
        for _ in range(6):
            jax.device_get(f())
        n = 200
        best = float("inf")
        for _ in range(3):
            jax.device_get(f())
            t0 = time.perf_counter()
            for _ in range(n):
                f()
            best = min(best, (time.perf_counter() - t0) / n)
        return best * 1e6

    host = _bench.host_dispatch_bench(measure_us)
    if "error" not in host:
        out["eager:host_fwd"] = host["matmul_add_fwd_us"] / 1e6
        out["eager:host_fwd_bwd"] = host["matmul_add_fwd_bwd_us"] / 1e6
    return out


if __name__ == "__main__":
    main()
