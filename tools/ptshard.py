#!/usr/bin/env python
"""ptshard — standalone entry point for the PT9xx sharding-propagation
analyzer over serialized ShardGraph JSON (``ShardGraph.to_json``).

Loads the analysis package directly from source files so it runs even
when paddle_tpu isn't installed and without importing the framework
(no jax import — propagation is pure shape/spec arithmetic).

Usage:
  python tools/ptshard.py capture.json --mesh dp=2,mp=4
  python tools/ptshard.py s0.json s1.json --pipeline   # PT905 boundaries
  python tools/ptshard.py capture.json --format sarif
  python tools/ptshard.py capture.json --update-baseline

For presets (jax available) prefer the framework route:
  python -m paddle_tpu.analysis --program llama --families PT9
"""
import importlib.util
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_analysis():
    """Import paddle_tpu.analysis as a detached package (skipping
    paddle_tpu/__init__.py and its jax import).  The stub parent carries
    a real __path__, so the propagator's lazy ``paddle_tpu.cost_model``
    import (collective_bytes pricing) also resolves jax-free."""
    pkg_dir = os.path.join(_REPO, "paddle_tpu", "analysis")
    spec = importlib.util.spec_from_file_location(
        "paddle_tpu.analysis", os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir])
    import types

    parent = types.ModuleType("paddle_tpu")
    parent.__path__ = [os.path.join(_REPO, "paddle_tpu")]
    sys.modules.setdefault("paddle_tpu", parent)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["paddle_tpu.analysis"] = mod
    spec.loader.exec_module(mod)
    return mod


if __name__ == "__main__":
    _load_analysis()
    from paddle_tpu.analysis.sharding.cli import main

    sys.exit(main())
