#!/usr/bin/env python
"""ptrace — standalone entry point for the concurrency analysis
families (equivalent to ``python -m paddle_tpu.analysis --conc``):

- PT7xx: class-level lock-consistency race detection (guard-map
  inference, lock-order cycles, join discipline, condition usage);
- PT8xx: fleet-protocol invariants (manifest-last persistence,
  hand-off payload identity keys, generation-fenced writes, atomic
  metrics updates).

Loads the analysis package directly from source files so it runs even
when paddle_tpu isn't installed and without importing the framework
(no jax import — milliseconds-fast in CI, like tools/ptlint.py).

Usage:
  python tools/ptrace.py paddle_tpu/
  python tools/ptrace.py paddle_tpu/distributed/ --format sarif
  python tools/ptrace.py paddle_tpu/ --no-baseline    # include
                                                      # grandfathered
"""
import importlib.util
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_analysis():
    """Import paddle_tpu.analysis as a detached package (skipping
    paddle_tpu/__init__.py and its jax import)."""
    pkg_dir = os.path.join(_REPO, "paddle_tpu", "analysis")
    spec = importlib.util.spec_from_file_location(
        "paddle_tpu.analysis", os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir])
    # a stub parent keeps the relative imports inside the package working
    import types

    parent = types.ModuleType("paddle_tpu")
    parent.__path__ = [os.path.join(_REPO, "paddle_tpu")]
    sys.modules.setdefault("paddle_tpu", parent)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["paddle_tpu.analysis"] = mod
    spec.loader.exec_module(mod)
    return mod


if __name__ == "__main__":
    sys.exit(_load_analysis().main(["--conc"] + sys.argv[1:]))
