#!/usr/bin/env python
"""faultplan — validate a PT_FAULT_PLAN chaos plan offline.

Equivalent to ``python -m paddle_tpu.distributed.resilience.faults
--check "<plan>"`` but loads the DSL parser directly from source files
with stub parent packages, so it runs without importing the framework
(no jax import — CI validates a plan in milliseconds before a pod ever
sees it).

Usage:
  python tools/faultplan.py "drop@send#2,kill@step#5:rank=1"
  python tools/faultplan.py --check "seed=7,corrupt@send%0.05"
  python tools/faultplan.py --check "sigkill@replica#4:rank=1"
  PT_FAULT_PLAN="kill@save#1" python tools/faultplan.py

Process-event sites reject frame kinds (and vice versa): a
``corrupt@replica`` or a ``sigkill@send`` fails here, in
milliseconds, instead of silently no-oping on the pod.

Exit codes: 0 = plan parses (normalized form printed), 2 = invalid.
"""
import importlib.util
import os
import sys
import types

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load(name: str, path: str):
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


def _load_faults():
    """Import ...resilience.faults with stub parents (skipping every
    package __init__ and its jax import)."""
    pkg = os.path.join(_REPO, "paddle_tpu")
    for stub in ("paddle_tpu", "paddle_tpu.profiler",
                 "paddle_tpu.distributed",
                 "paddle_tpu.distributed.resilience"):
        if stub not in sys.modules:
            m = types.ModuleType(stub)
            m.__path__ = [os.path.join(
                pkg, *stub.split(".")[1:])] if stub != "paddle_tpu" \
                else [pkg]
            sys.modules[stub] = m
    metrics = _load("paddle_tpu.profiler.metrics",
                    os.path.join(pkg, "profiler", "metrics.py"))
    sys.modules["paddle_tpu.profiler"].metrics = metrics
    return _load("paddle_tpu.distributed.resilience.faults",
                 os.path.join(pkg, "distributed", "resilience",
                              "faults.py"))


def main(argv=None) -> int:
    return _load_faults().main(argv)


if __name__ == "__main__":
    sys.exit(main())
