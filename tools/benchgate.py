#!/usr/bin/env python
"""benchgate — CI regression gate over bench.py results.

Compares a candidate bench result (the final JSON line bench.py prints,
or a BENCH_r*.json driver wrapper, or a BENCH_partial.jsonl stream)
against the last GOOD baseline round and exits nonzero when the perf
signal regressed:

- llama train ``tokens/s-per-chip`` dropping more than ``--threshold``
  (default 5%),
- serving ``ttft_s_p50`` / ``ttft_s_p95`` / ``tpot_ms_min`` rising more
  than the threshold on any decode batch present in both runs,
- fleet serving ``requests_per_sec`` or ``prefix_hit_rate`` dropping
  more than the threshold, or ``ttft_mean_s`` rising more than it
  (the shared-prefix wave of bench.py's ``fleet`` gate row),
- chaos recovery (bench.py's ``fleet_recovery`` row — one replica
  killed mid-decode — and ``host_recovery`` — a whole host's replicas
  felled at once): ``requests_completed`` dropping AT ALL (every
  admitted request must survive the kill; no threshold slack), or
  ``recovery_s`` rising more than the threshold,
- overload (bench.py's ``gateway_storm`` row — every arrival
  multiplied 4x at the gateway's admit site): ``interactive_completed``
  dropping AT ALL (the brownout ladder must protect interactive
  traffic; no slack), ``goodput_rps`` dropping or
  ``interactive_ttft_p95_s`` rising more than the threshold,
- speculative decoding (bench.py's ``spec_decode`` row — the draftable
  shared-prompt workload): ``bitwise_match`` dropping AT ALL (spec
  streams must stay token-identical to the baseline; no slack),
  ``tokens_per_sec`` / ``accept_rate`` / ``speedup`` dropping or
  ``step_ms`` rising more than the threshold,
- the candidate missing the flagship metric entirely (a timed-out
  flagship row must fail the gate, not silently pass it — the r05
  failure mode).

"Last good" baseline: ``--baseline FILE``, or auto-discovery — the
newest ``BENCH_r*.json`` in ``--baseline-dir`` (default: repo root)
whose payload parses and carries a flagship value (r05's rc-124 empty
round is skipped automatically).

Usage:
  python tools/benchgate.py --candidate /tmp/BENCH_new.json
  python tools/benchgate.py --candidate BENCH_partial.jsonl --threshold 0.03
  python bench.py --fast > /tmp/row.json && python tools/benchgate.py -c /tmp/row.json
"""
import argparse
import glob
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def extract_result(obj):
    """Pull the bench final-result dict out of any of the shapes we
    store: the result itself, the driver wrapper {"tail": "...json..."},
    or None when unparseable."""
    if not isinstance(obj, dict):
        return None
    if obj.get("metric") == "llama_train_tokens_per_sec_per_chip":
        return obj
    # BENCH_partial.jsonl row: {"bench": "final", "result": {...}}
    if obj.get("bench") == "final" and isinstance(obj.get("result"), dict):
        return extract_result(obj["result"])
    # driver wrapper: the final JSON line is embedded in "tail"
    tail = obj.get("tail")
    if isinstance(tail, str):
        for line in reversed(tail.splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    got = extract_result(json.loads(line))
                except ValueError:
                    continue
                if got is not None:
                    return got
    return None


def load_result(path):
    """Load a result from a JSON file or a .jsonl stream (last parseable
    final row wins)."""
    with open(path) as f:
        text = f.read()
    try:
        got = extract_result(json.loads(text))
        if got is not None:
            return got
    except ValueError:
        pass
    result = None
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            got = extract_result(json.loads(line))
        except ValueError:
            continue
        if got is not None:
            result = got
    return result


def find_baseline(baseline_dir):
    """Newest BENCH_r*.json with a parsed flagship value."""
    rounds = sorted(glob.glob(os.path.join(baseline_dir,
                                           "BENCH_r*.json")),
                    reverse=True)
    for path in rounds:
        try:
            result = load_result(path)
        except OSError:
            continue
        if result is not None and result.get("value") is not None:
            return path, result
    return None, None


def _serving_metrics(result):
    """{(batch_key, metric): value} for the gated serving latencies."""
    out = {}
    serving = (result.get("extra") or {}).get("serving") or {}
    for key, row in serving.items():
        if not isinstance(row, dict):
            continue
        # step_ms is the tpot proxy older rounds (<= r04) recorded
        for metric in ("ttft_s_p50", "ttft_s_p95", "tpot_ms_min",
                       "step_ms"):
            v = row.get(metric)
            if isinstance(v, (int, float)):
                out[(key, metric)] = float(v)
    return out


# fleet row signals: value is True when HIGHER is better (a drop fails),
# False for latencies (a rise fails)
_FLEET_GATES = {"requests_per_sec": True, "prefix_hit_rate": True,
                "ttft_mean_s": False,
                # digest tail latency (PR 10): an honest p95 over every
                # request in the fleet row, not a mean that hides tails.
                # Old baselines without the key are skipped (set
                # intersection below), so the gate phases in as soon as
                # a baseline carries it.
                "ttft_p95_s": False}


def _fleet_metrics(result):
    """{metric: value} for the gated fleet-serving signals."""
    fleet = ((result.get("extra") or {}).get("fleet") or {}).get("fleet") \
        or {}
    return {m: float(fleet[m]) for m in _FLEET_GATES
            if isinstance(fleet.get(m), (int, float))}


# chaos rows, all sharing one gate shape: {metric: True} means higher
# is better (a drop fails), False means lower is better (a rise fails).
# Metrics named in the third field are gated with ZERO slack — any drop
# under the injected fault means an admitted request was lost (the
# recovery rows) or a protected interactive request failed to complete
# under the 4x storm (gateway_storm).
_RECOVERY_GATES = {"requests_completed": True, "recovery_s": False}
_GATEWAY_GATES = {"interactive_completed": True, "goodput_rps": True,
                  "interactive_ttft_p95_s": False,
                  # SLO engine (ISSUE 16): interactive attainment is
                  # zero-slack — the storm may not push good-fraction
                  # below the baseline; burn_alerts_resolved (1.0 =
                  # every raised alert cleared by run end) gates at the
                  # normal threshold.  Old baselines without the keys
                  # skip them (set intersection), so both phase in.
                  "interactive_slo_attainment": True,
                  "burn_alerts_resolved": True}
# spec_decode: speculative decoding on the draftable shared-prompt
# workload. bitwise_match is the exactness contract — speculative
# streams must equal the non-speculative baseline's, so ANY drop from
# a passing baseline (1.0) fails with zero slack; throughput, accept
# rate and speedup-over-baseline gate with the normal threshold and
# step latency must not rise.
_SPEC_GATES = {"tokens_per_sec": True, "accept_rate": True,
               "speedup": True, "bitwise_match": True, "step_ms": False}
# weight_publish: a live versioned rollout lands mid-wave.
# requests_completed and bitwise_match are zero-slack — a publish may
# never drop a request, and every stream must match the regenerated
# reference of the version it was PINNED to (old streams finish under
# N, new streams under N+1); publish wall time must not rise and
# goodput under the rollout must not sag past the normal threshold.
_PUBLISH_GATES = {"requests_completed": True, "bitwise_match": True,
                  "goodput_rps": True, "publish_s": False}
# autoscale_storm: the fleet RESIZES under a 4x admit storm (ISSUE 18)
# — scale-up with catch-up-gated entry (kill@spawn fells the first
# attempt), then a drain-down while late traffic is in flight.
# requests_completed and bitwise_match are zero-slack — a resize may
# never lose an admitted request, and every stream must match the
# fixed-fleet reference bitwise whether it was placed on a spawned
# replica or drained off a retiring one; scale-up reaction time must
# not rise and goodput under the resize must not sag past the normal
# threshold.  Old baselines without the row skip it (set
# intersection), so the gate phases in.
_AUTOSCALE_GATES = {"requests_completed": True, "bitwise_match": True,
                    "goodput_rps": True, "scaleup_to_traffic_s": False}
# autotune_rank: the static tuner must keep ranking the FULL parallel-
# config grid and its top pick must stay Pareto-consistent with the
# MULTICHIP dryrun-validated configs — both zero-slack (a shrunken grid
# or a dominated top pick is a tuner bug, not noise).  rank_ms is
# recorded in the row but not gated: tens of milliseconds of pure
# python is too noisy for a 5% latency gate.
_AUTOTUNE_GATES = {"configs_ranked": True, "pareto_consistent": True}
# fleet_subprocess: one WORKER PROCESS SIGKILLed mid-decode (ISSUE 20)
# — death inferred from missed heartbeats, the drain's dead-process
# path requeues to the surviving worker, a fresh process respawns via
# the factory.  requests_completed and bitwise_match are zero-slack (a
# pod kill may never lose an admitted request or perturb a surviving
# stream); recovery_s must not rise past the normal threshold.
# respawn_s/detect_s ride in the row unguarded — respawn pays a full
# interpreter + jax start and is too noisy for a 5% latency gate.
_SUBPROC_GATES = {"requests_completed": True, "bitwise_match": True,
                  "recovery_s": False}
_CHAOS_ROWS = (
    # fleet_recovery: one replica killed mid-decode; host_recovery: a
    # whole host's replicas felled at once; gateway_storm: every
    # arrival multiplied 4x at the admit site; spec_decode: draft k /
    # verify-in-one-step decoding vs the plain step loop;
    # weight_publish: canary-gated hot swap under live traffic
    ("fleet_recovery", _RECOVERY_GATES, ("requests_completed",)),
    ("host_recovery", _RECOVERY_GATES, ("requests_completed",)),
    ("fleet_subprocess", _SUBPROC_GATES,
     ("requests_completed", "bitwise_match")),
    ("gateway_storm", _GATEWAY_GATES,
     ("interactive_completed", "interactive_slo_attainment")),
    ("spec_decode", _SPEC_GATES, ("bitwise_match",)),
    ("weight_publish", _PUBLISH_GATES,
     ("requests_completed", "bitwise_match")),
    ("autoscale_storm", _AUTOSCALE_GATES,
     ("requests_completed", "bitwise_match")),
    ("autotune_rank", _AUTOTUNE_GATES,
     ("configs_ranked", "pareto_consistent")),
)
_RECOVERY_ROWS = tuple(r for r, _, _ in _CHAOS_ROWS)


def _recovery_metrics(result, row, gates=None):
    """{metric: value} for one gated chaos row."""
    gates = gates or _RECOVERY_GATES
    rec = ((result.get("extra") or {}).get(row) or {}).get(row) or {}
    return {m: float(rec[m]) for m in gates
            if isinstance(rec.get(m), (int, float))}


def compare(candidate, baseline, threshold=0.05):
    """Returns (failures, report_lines). A failure is a formatted
    string; an empty list means the gate passes."""
    failures = []
    lines = []

    cand_tps = candidate.get("value")
    base_tps = baseline.get("value")
    if cand_tps is None:
        failures.append("candidate has no llama_train tokens/s value "
                        "(flagship row missing or timed out)")
    elif base_tps:
        drop = (base_tps - cand_tps) / base_tps
        verdict = "FAIL" if drop > threshold else "ok"
        lines.append(f"tokens/s-per-chip: {base_tps:.1f} -> "
                     f"{cand_tps:.1f}  ({-drop * 100:+.1f}%) [{verdict}]")
        if drop > threshold:
            failures.append(
                f"tokens/s-per-chip dropped {drop * 100:.1f}% "
                f"(> {threshold * 100:.0f}%)")

    cand_sv = _serving_metrics(candidate)
    base_sv = _serving_metrics(baseline)
    for key in sorted(set(cand_sv) & set(base_sv)):
        b, c = base_sv[key], cand_sv[key]
        if b <= 0:
            continue
        rise = (c - b) / b                 # latency: higher is worse
        verdict = "FAIL" if rise > threshold else "ok"
        lines.append(f"{key[0]}.{key[1]}: {b:g} -> {c:g}  "
                     f"({rise * 100:+.1f}%) [{verdict}]")
        if rise > threshold:
            failures.append(
                f"{key[0]}.{key[1]} rose {rise * 100:.1f}% "
                f"(> {threshold * 100:.0f}%)")

    cand_fl = _fleet_metrics(candidate)
    base_fl = _fleet_metrics(baseline)
    for m in sorted(set(cand_fl) & set(base_fl)):
        b, c = base_fl[m], cand_fl[m]
        if b <= 0:
            continue
        if _FLEET_GATES[m]:                # throughput/hit-rate: drop bad
            delta = (b - c) / b
            word = "dropped"
        else:                              # latency: rise bad
            delta = (c - b) / b
            word = "rose"
        verdict = "FAIL" if delta > threshold else "ok"
        lines.append(f"fleet.{m}: {b:g} -> {c:g}  "
                     f"({-delta * 100 if _FLEET_GATES[m] else delta * 100:+.1f}%) "
                     f"[{verdict}]")
        if delta > threshold:
            failures.append(
                f"fleet.{m} {word} {delta * 100:.1f}% "
                f"(> {threshold * 100:.0f}%)")

    for row, gates, zero_slack in _CHAOS_ROWS:
        cand_rc = _recovery_metrics(candidate, row, gates)
        base_rc = _recovery_metrics(baseline, row, gates)
        for m in sorted(set(cand_rc) & set(base_rc)):
            b, c = base_rc[m], cand_rc[m]
            if b <= 0:
                continue
            if gates[m]:
                delta = (b - c) / b
                word = "dropped"
                # zero-slack counts: ANY drop under the injected fault
                # means an admitted/protected request was lost
                budget = 0.0 if m in zero_slack else threshold
            else:
                delta = (c - b) / b
                word = "rose"
                budget = threshold
            verdict = "FAIL" if delta > budget else "ok"
            lines.append(
                f"{row}.{m}: {b:g} -> {c:g}  "
                f"({-delta * 100 if gates[m] else delta * 100:+.1f}%) "
                f"[{verdict}]")
            if delta > budget:
                failures.append(
                    f"{row}.{m} {word} {delta * 100:.1f}% "
                    f"(> {budget * 100:.0f}%)")
    return failures, lines


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("-c", "--candidate", required=True,
                    help="candidate result: bench.py output JSON, "
                         "BENCH_partial.jsonl, or BENCH_r*.json wrapper")
    ap.add_argument("--baseline",
                    help="explicit baseline file (default: newest good "
                         "BENCH_r*.json in --baseline-dir)")
    ap.add_argument("--baseline-dir", default=_REPO)
    ap.add_argument("--threshold", type=float, default=0.05,
                    help="relative regression budget (default 0.05)")
    args = ap.parse_args(argv)

    candidate = load_result(args.candidate)
    if candidate is None:
        print(f"benchgate: FAIL — candidate {args.candidate} has no "
              f"parseable bench result")
        return 2
    if args.baseline:
        base_path, baseline = args.baseline, load_result(args.baseline)
    else:
        base_path, baseline = find_baseline(args.baseline_dir)
    if baseline is None:
        print("benchgate: FAIL — no usable baseline round found "
              f"(looked in {args.baseline or args.baseline_dir})")
        return 2

    failures, lines = compare(candidate, baseline, args.threshold)
    print(f"benchgate: candidate={args.candidate} baseline={base_path} "
          f"threshold={args.threshold * 100:.0f}%")
    for ln in lines:
        print("  " + ln)
    if failures:
        for f in failures:
            print("  REGRESSION: " + f)
        print("benchgate: FAIL")
        return 1
    print("benchgate: ok")
    return 0


if __name__ == "__main__":
    sys.path.insert(0, _REPO)
    sys.exit(main())
