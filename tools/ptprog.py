#!/usr/bin/env python
"""ptprog — IR-level Program analyzer CLI.

Runs the four PT6xx analysis passes (shape/dtype dataflow, peak-memory
estimation, collective consistency, pass equivalence) over a recorded
``static.Program``.  Unlike ``tools/ptlint.py`` this needs jax: the
dataflow core abstractly evaluates every recorded op entry with
``jax.eval_shape``.

Usage:
  python tools/ptprog.py llama                      # preset capture
  python tools/ptprog.py mlp --format json
  python tools/ptprog.py llama --budget-gb 16 --memory-report
  python tools/ptprog.py my_pkg.my_mod:make_program
  python tools/ptprog.py --list-rules

Equivalent to ``python -m paddle_tpu.analysis --program <target>``.
"""
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

if __name__ == "__main__":
    sys.path.insert(0, _REPO)
    from paddle_tpu.analysis.main import main

    argv = sys.argv[1:]
    # first positional (if any) is the program target
    if argv and not argv[0].startswith("-") \
            and "--program" not in argv:
        argv = ["--program", argv[0]] + argv[1:]
    elif "--program" not in argv and "--list-rules" not in argv:
        argv = ["--program", "llama"] + argv
    sys.exit(main(argv))
