"""Validate the auto-tuner memory model against XLA's own accounting.

Compiles (AOT — no execution needed) the AdamW train step of a stack of
Llama-2-13B-dimension decoder blocks and compares
`auto_tuner.estimate_memory_bytes` against the compiled executable's
argument + temp bytes from `compiled.memory_analysis()`.

Usage: python tools/validate_memory_model.py [--small]
  --small: debug dims (runs anywhere, including the CPU backend)

Reference analog: the reference's tuner validates its memory model by
running trial jobs (distributed/auto_tuner/cost_model.py + recorder);
XLA's static memory analysis gives the same signal without burning chip
time.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp


def build_block_step(hidden, inter, heads, seq, batch, layers, remat):
    """The AdamW train step over `layers` stacked decoder blocks at the
    given dims. Returns (step_fn, blocks, opt_state, x, n_block_params) —
    shared by this validator and bench.py's llama13b_block row."""
    from paddle_tpu.models import llama
    from paddle_tpu.models.llama import _block

    cfg = llama.LlamaConfig(
        vocab_size=256, hidden_size=hidden, intermediate_size=inter,
        num_hidden_layers=layers, num_attention_heads=heads,
        num_key_value_heads=heads, max_position_embeddings=seq,
        dtype="bfloat16", recompute=remat)

    params = jax.jit(
        lambda k: llama.init_stacked_params(cfg, k))(jax.random.key(0))
    blocks = params["blocks"]
    n_blk = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(blocks))
    opt = {"m": jax.tree.map(lambda x: jnp.zeros(x.shape, jnp.float32),
                             blocks),
           "v": jax.tree.map(lambda x: jnp.zeros(x.shape, jnp.float32),
                             blocks)}

    def step(blocks, opt, x):
        def loss_of(bl):
            def body(c, lp):
                return _block(lp, c, cfg), None

            bf = jax.checkpoint(body) if remat else body
            y, _ = jax.lax.scan(bf, x, bl)
            return jnp.sum(y.astype(jnp.float32)) * 1e-6

        loss, grads = jax.value_and_grad(loss_of)(blocks)
        grads = jax.tree.map(lambda g: g.astype(jnp.float32), grads)

        def upd(p, g, m, v):
            m = 0.9 * m + 0.1 * g
            v = 0.95 * v + 0.05 * g * g
            return ((p.astype(jnp.float32)
                     - 3e-4 * m / (jnp.sqrt(v) + 1e-8)).astype(p.dtype),
                    m, v)

        out = jax.tree.map(upd, blocks, grads, opt["m"], opt["v"])

        def pick(i):
            return jax.tree.map(lambda o: o[i], out,
                                is_leaf=lambda x: isinstance(x, tuple))

        return pick(0), {"m": pick(1), "v": pick(2)}, loss

    x = jax.random.normal(jax.random.key(1), (batch, seq, hidden),
                          jnp.bfloat16)
    return step, blocks, opt, x, n_blk


def block_step_memory(hidden, inter, heads, seq, batch, layers, remat):
    """(predicted_bytes, measured_bytes, n_block_params) for the AdamW
    step of `layers` stacked decoder blocks at the given dims."""
    from paddle_tpu.distributed.auto_tuner import (TunerCfg,
                                                   estimate_memory_bytes)

    step, blocks, opt, x, n_blk = build_block_step(
        hidden, inter, heads, seq, batch, layers, remat)
    compiled = jax.jit(step, donate_argnums=(0, 1)).lower(
        blocks, opt, x).compile()
    ma = compiled.memory_analysis()
    measured = ma.argument_size_in_bytes + ma.temp_size_in_bytes
    predicted = estimate_memory_bytes(
        TunerCfg(1, 1, 1, 1, 1, batch, remat), n_blk, hidden, layers, seq)
    return predicted, measured, n_blk


def main():
    small = "--small" in sys.argv
    if small:
        grid = [dict(hidden=256, inter=688, heads=4, seq=512,
                     batch=b, layers=l, remat=rc)
                for b in (1, 2) for l in (1, 2) for rc in (True, False)]
    else:
        grid = [dict(hidden=5120, inter=13824, heads=40, seq=4096,
                     batch=b, layers=l, remat=rc)
                for (b, l, rc) in ((1, 1, True), (2, 1, True),
                                   (4, 1, True), (1, 2, True),
                                   (1, 1, False), (2, 1, False),
                                   (1, 2, False))]
    worst = 0.0
    for g in grid:
        pred, meas, n = block_step_memory(**g)
        ratio = pred / meas
        worst = max(worst, abs(1 - ratio))
        print(f"{g}: predicted {pred/1e9:.3f} GB, measured "
              f"{meas/1e9:.3f} GB, ratio {ratio:.3f}")
    print(f"worst |1-ratio|: {worst:.3f}")
    return worst


if __name__ == "__main__":
    main()
