#!/usr/bin/env python
"""fusereport — pre/post ``auto_fuse`` roofline diff for a captured
Program.

Loads a Program capture (the ptprog presets, or a
``module.path:callable`` target), takes the cost-model roofline
estimate (per-op FLOPs / bytes moved / arithmetic intensity /
peak live bytes), runs the cost-model-driven ``auto_fuse`` pass under
the pass-equivalence verifier, re-estimates, and prints the diff:
per-region members + estimated HBM bytes saved, total bytes-moved and
peak-memory deltas.  ``--stablehlo DIR`` additionally dumps each fused
region (and the whole post-fusion module) as .mlir artifacts — the
inspectable-compiler-output contract of the fusion tier.

``--preset NAME`` is target selection plus the artifact dump in one
flag: ``--preset decode`` runs the serving decode-iteration capture
(paged KV gather -> attention -> swiglu -> LM head -> argmax, the
region serving.py executes as one fused program) and writes its
roofline diff next to the .mlir dumps (default directory
``fusereport_<preset>/`` unless ``--stablehlo`` names one).

Usage:
  python tools/fusereport.py llama-block
  python tools/fusereport.py mlp --json
  python tools/fusereport.py llama-block --stablehlo /tmp/fused
  python tools/fusereport.py --preset decode
  python tools/fusereport.py my_pkg.my_mod:make_capture --max-intensity 4
"""
import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _estimate(program, feed_spec):
    from paddle_tpu.cost_model import CostModel

    rep = CostModel().static_estimate(program, feed_spec=feed_spec)
    return {
        "ops": len(program.ops),
        "total_bytes_moved": sum(r["bytes_moved"] for r in rep.per_op),
        "total_flops": rep.total_flops,
        "peak_bytes": rep.peak_bytes,
    }


def build_report(target: str, max_intensity: float = 8.0,
                 min_chain: int = 2, verify: bool = True,
                 stablehlo_dir=None) -> dict:
    """Run the fusion pipeline over ``target`` and return the diff as a
    plain dict (the CLI prints it; tests and CI call this directly)."""
    import functools

    from paddle_tpu.analysis.program import load_target
    from paddle_tpu.static.passes import (PassManager, auto_fuse,
                                          fusion_candidates)

    cap = load_target(target)
    feed_spec = cap.feed_spec or None
    pre = _estimate(cap.program, feed_spec)
    candidates = fusion_candidates(cap.program,
                                   max_intensity=max_intensity,
                                   min_chain=min_chain,
                                   feed_spec=feed_spec)
    fuse = functools.partial(auto_fuse, max_intensity=max_intensity,
                             min_chain=min_chain, feed_spec=feed_spec)
    fuse.__name__ = "auto_fuse"
    pm = PassManager([fuse])
    pm.run(cap.program, verify=verify, feed_spec=feed_spec)
    post = _estimate(cap.program, feed_spec)

    report = {
        "target": cap.name,
        "max_intensity": max_intensity,
        "verified": verify,
        "regions": [{"names": c["names"],
                     "est_bytes_saved": c["est_bytes_saved"]}
                    for c in candidates],
        "pre": pre,
        "post": post,
        "bytes_moved_saved": pre["total_bytes_moved"]
        - post["total_bytes_moved"],
        "bytes_moved_saved_pct": round(
            100.0 * (pre["total_bytes_moved"]
                     - post["total_bytes_moved"])
            / max(pre["total_bytes_moved"], 1), 2),
    }
    if stablehlo_dir:
        from paddle_tpu.static.stablehlo import (fused_regions_stablehlo,
                                                 program_stablehlo)

        os.makedirs(stablehlo_dir, exist_ok=True)
        paths = []
        for idx, text in fused_regions_stablehlo(
                cap.program, feed_spec=feed_spec).items():
            p = os.path.join(stablehlo_dir,
                             f"{cap.name}.region{idx}.mlir")
            with open(p, "w") as f:
                f.write(text)
            paths.append(p)
        mod = os.path.join(stablehlo_dir, f"{cap.name}.module.mlir")
        with open(mod, "w") as f:
            f.write(program_stablehlo(cap.program, feed_spec=feed_spec))
        paths.append(mod)
        report["stablehlo_artifacts"] = paths
    return report


def _fmt_bytes(n):
    for unit, div in (("GiB", 1 << 30), ("MiB", 1 << 20),
                      ("KiB", 1 << 10)):
        if abs(n) >= div:
            return f"{n / div:.2f} {unit}"
    return f"{n} B"


def render(report: dict) -> str:
    lines = [f"fusion report — {report['target']} "
             f"(max_intensity={report['max_intensity']}, "
             f"verified={report['verified']})"]
    if not report["regions"]:
        lines.append("  no fusable memory-bound chains found")
    for i, r in enumerate(report["regions"]):
        lines.append(f"  region {i}: {' -> '.join(r['names'])}   "
                     f"saves ~{_fmt_bytes(r['est_bytes_saved'])}")
    pre, post = report["pre"], report["post"]
    lines.append(f"  ops           : {pre['ops']} -> {post['ops']}")
    lines.append(f"  bytes moved   : "
                 f"{_fmt_bytes(pre['total_bytes_moved'])} -> "
                 f"{_fmt_bytes(post['total_bytes_moved'])}  "
                 f"(-{report['bytes_moved_saved_pct']}%)")
    lines.append(f"  peak live set : {_fmt_bytes(pre['peak_bytes'])} -> "
                 f"{_fmt_bytes(post['peak_bytes'])}")
    for p in report.get("stablehlo_artifacts", []):
        lines.append(f"  stablehlo     : {p}")
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("target", nargs="?", default=None,
                    help="preset (mlp / llama-block / decode) or "
                         "module:callable (default: llama-block)")
    ap.add_argument("--preset", metavar="NAME",
                    help="preset target + artifact dump: run NAME and "
                         "write the roofline report and region .mlir "
                         "dumps (to --stablehlo, default "
                         "fusereport_<NAME>/)")
    ap.add_argument("--max-intensity", type=float, default=8.0,
                    help="roofline intensity ceiling for chain members")
    ap.add_argument("--min-chain", type=int, default=2)
    ap.add_argument("--no-verify", action="store_true",
                    help="skip pass-equivalence verification")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--stablehlo", metavar="DIR",
                    help="dump fused regions + module as .mlir here")
    args = ap.parse_args(argv)
    if args.preset and args.target and args.target != args.preset:
        ap.error(f"both a positional target ({args.target!r}) and "
                 f"--preset ({args.preset!r}) given — pick one")
    target = args.preset or args.target or "llama-block"
    stablehlo_dir = args.stablehlo
    if args.preset and not stablehlo_dir:
        stablehlo_dir = f"fusereport_{args.preset}"
    report = build_report(target, max_intensity=args.max_intensity,
                          min_chain=args.min_chain,
                          verify=not args.no_verify,
                          stablehlo_dir=stablehlo_dir)
    if args.preset:
        path = os.path.join(stablehlo_dir, f"{report['target']}.roofline"
                                           f".json")
        with open(path, "w") as f:
            json.dump(report, f, indent=2)
        report["roofline_artifact"] = path
    print(json.dumps(report) if args.json else render(report))
    return 0


if __name__ == "__main__":
    sys.path.insert(0, _REPO)
    sys.exit(main())
