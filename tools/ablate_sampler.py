"""Sampler-internals ablation (decode bottleneck hunt, VERDICT r5).

The r5 decode ablation showed the top-k sampler scan costs ~7.5 ms of
the 10.26 ms bs-16 decode step. This times each sampler ingredient in a
16-step scan with a REAL sync (device_get of a scalar — block_until_ready
can no-op over the tunnel). Prints one JSON line.
"""
import json
import sys
import time

import jax
import jax.numpy as jnp

B1, V, C, WIN = 17, 32000, 128, 16


def timed(fn, n=3):
    jax.device_get(jnp.sum(fn()))  # warm/compile + sync
    best = float("inf")
    for _ in range(n):
        t0 = time.perf_counter()
        jax.device_get(jnp.sum(fn()))
        best = min(best, time.perf_counter() - t0)
    return best


def scan_of(row_fn):
    """16-step scan of vmap(row_fn) over [B1, V] logits."""
    def run(lg):
        def body(c, j):
            out = jax.vmap(lambda l: row_fn(l, j))(lg + c[:, None] * 0)
            return out.astype(jnp.int32), out
        _, ys = jax.lax.scan(body, jnp.zeros((B1,), jnp.int32),
                             jnp.arange(WIN))
        return ys
    return jax.jit(run)


def main():
    stages = set(sys.argv[1:]) or {"argmax", "topk", "approx", "gumbelV",
                                   "full", "approx_full"}
    key = jax.random.key(0)
    lg = jax.device_put(jax.random.normal(key, (B1, V), jnp.float32))
    res = {}
    base = jax.random.key(0)

    if "argmax" in stages:
        dt = timed(lambda: scan_of(lambda l, j: jnp.argmax(l))(lg))
        res["argmax_ms_per_step"] = round(dt / WIN * 1e3, 3)

    if "topk" in stages:
        def row(l, j):
            vals, idx = jax.lax.top_k(l, C)
            return idx[0]
        dt = timed(lambda: scan_of(row)(lg))
        res["topk_ms_per_step"] = round(dt / WIN * 1e3, 3)

    if "approx" in stages:
        def row(l, j):
            vals, idx = jax.lax.approx_max_k(l, C)
            return idx[0]
        dt = timed(lambda: scan_of(row)(lg))
        res["approx_topk_ms_per_step"] = round(dt / WIN * 1e3, 3)

    if "gumbelV" in stages:
        def row(l, j):
            g = jax.random.gumbel(jax.random.fold_in(base, j), (V,),
                                  jnp.float32)
            return jnp.argmax(l + g)
        dt = timed(lambda: scan_of(row)(lg))
        res["gumbel_fullV_ms_per_step"] = round(dt / WIN * 1e3, 3)

    if "full" in stages:
        # the current _sample_topk_core chain
        def row(l, j):
            lt = l / 0.8
            vals, idx = jax.lax.top_k(lt, C)
            keep = jnp.arange(C) < 50
            pr = jax.nn.softmax(jnp.where(keep, vals, -jnp.inf))
            keep = keep & ((jnp.cumsum(pr) - pr) < 0.95)
            g = jax.random.gumbel(jax.random.fold_in(base, j), (V,),
                                  jnp.float32)
            win = jnp.argmax(jnp.where(keep, vals, -jnp.inf) + g[idx])
            return idx[win]
        dt = timed(lambda: scan_of(row)(lg))
        res["current_chain_ms_per_step"] = round(dt / WIN * 1e3, 3)

    if "approx_full" in stages:
        # candidate chain with approx_max_k + per-candidate-id gumbel
        def row(l, j):
            lt = l / 0.8
            vals, idx = jax.lax.approx_max_k(lt, C)
            keep = jnp.arange(C) < 50
            pr = jax.nn.softmax(jnp.where(keep, vals, -jnp.inf))
            keep = keep & ((jnp.cumsum(pr) - pr) < 0.95)
            kj = jax.random.fold_in(base, j)
            bits = jax.vmap(
                lambda t: jax.random.bits(jax.random.fold_in(kj, t)))(idx)
            u = (bits >> 8).astype(jnp.float32) * (1.0 / (1 << 24))
            g = -jnp.log(-jnp.log(jnp.maximum(u, 1e-20)))
            win = jnp.argmax(jnp.where(keep, vals, -jnp.inf) + g)
            return idx[win]
        dt = timed(lambda: scan_of(row)(lg))
        res["approx_chain_ms_per_step"] = round(dt / WIN * 1e3, 3)

    res["device"] = str(getattr(jax.devices()[0], "device_kind", ""))
    print(json.dumps(res))


if __name__ == "__main__":
    main()
