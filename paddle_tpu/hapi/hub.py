"""paddle.hub — hubconf-protocol model loading.

Reference analog: python/paddle/hapi/hub.py (list/help/load over a repo
that ships a `hubconf.py` with entrypoint callables and an optional
`dependencies` list; sources github | gitee | local, with a download
cache under the hub home).

Behavior parity: the local source is fully functional; github/gitee
resolve to the same archive URLs and cache layout as the reference and
download via urllib — on an air-gapped host the download raises a clear
RuntimeError naming the URL (the protocol, cache and hubconf handling
are identical either way).
"""
from __future__ import annotations

import importlib.util
import os
import sys
import zipfile

__all__ = ["list", "help", "load"]

_HUBCONF = "hubconf"
_VAR_DEPENDENCY = "dependencies"


def _hub_home():
    return os.environ.get(
        "PPTPU_HUB_HOME",
        os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                     "hub"))


def _parse_repo(repo, source):
    """'owner/name[:branch]' -> (owner, name, branch) with the
    reference's default branch per source."""
    if ":" in repo:
        repo, branch = repo.split(":", 1)
    else:
        branch = "main" if source == "github" else "master"
    if repo.count("/") != 1:
        raise ValueError(
            f'repo must look like "repo_owner/repo_name[:branch]", got '
            f'"{repo}"')
    owner, name = repo.split("/")
    return owner, name, branch


def _archive_url(owner, name, branch, source):
    if source == "github":
        return (f"https://github.com/{owner}/{name}/archive/"
                f"{branch}.zip")
    return (f"https://gitee.com/{owner}/{name}/repository/archive/"
            f"{branch}.zip")


def _get_cache_or_reload(repo, force_reload, source):
    """Materialize the repo under the hub cache dir; returns its path."""
    owner, name, branch = _parse_repo(repo, source)
    home = _hub_home()
    os.makedirs(home, exist_ok=True)
    dirname = f"{owner}_{name}_{branch}".replace("/", "_")
    repo_dir = os.path.join(home, dirname)
    if os.path.isdir(repo_dir) and not force_reload:
        return repo_dir
    url = _archive_url(owner, name, branch, source)
    zip_path = os.path.join(home, dirname + ".zip")
    try:
        import urllib.request

        urllib.request.urlretrieve(url, zip_path)
    except Exception as e:
        raise RuntimeError(
            f"failed to download hub repo from {url}: {e}. On an offline "
            "host use source='local' with a checked-out repo directory."
        ) from e
    with zipfile.ZipFile(zip_path) as zf:
        top = zf.namelist()[0].split("/")[0]
        zf.extractall(home)
    if os.path.isdir(repo_dir):        # force_reload over a prior cache
        import shutil

        shutil.rmtree(repo_dir)
    os.replace(os.path.join(home, top), repo_dir)
    os.unlink(zip_path)
    return repo_dir


def _import_hubconf(repo_dir):
    path = os.path.join(repo_dir, _HUBCONF + ".py")
    if not os.path.isfile(path):
        raise RuntimeError(f"no {_HUBCONF}.py found in {repo_dir}")
    spec = importlib.util.spec_from_file_location(_HUBCONF, path)
    m = importlib.util.module_from_spec(spec)
    sys.path.insert(0, repo_dir)
    try:
        spec.loader.exec_module(m)
    finally:
        sys.path.remove(repo_dir)
    deps = getattr(m, _VAR_DEPENDENCY, None)
    if deps:
        missing = [d for d in deps
                   if importlib.util.find_spec(d) is None]
        if missing:
            raise RuntimeError(
                f"hub repo requires missing dependencies: {missing}")
    return m


def _resolve(repo_dir, source, force_reload):
    if source not in ("github", "gitee", "local"):
        raise ValueError(
            f'Unknown source: "{source}". Allowed values: "github" | '
            '"gitee" | "local".')
    if source == "local":
        return repo_dir
    return _get_cache_or_reload(repo_dir, force_reload, source)


def _load_entry(m, name):
    fn = getattr(m, name, None)
    if fn is None or not callable(fn):
        raise RuntimeError(f"cannot find callable {name} in hubconf")
    return fn


def list(repo_dir, source="github", force_reload=False):  # noqa: A001
    """Entrypoint names exposed by the repo's hubconf.py."""
    m = _import_hubconf(_resolve(repo_dir, source, force_reload))
    return [k for k, v in vars(m).items()
            if callable(v) and not k.startswith("_")]


def help(repo_dir, model, source="github", force_reload=False):  # noqa: A001
    """Docstring of one entrypoint."""
    m = _import_hubconf(_resolve(repo_dir, source, force_reload))
    return _load_entry(m, model).__doc__


def load(repo_dir, model, *args, source="github", force_reload=False,
         **kwargs):
    """Call the entrypoint and return its result (usually a Layer)."""
    m = _import_hubconf(_resolve(repo_dir, source, force_reload))
    return _load_entry(m, model)(*args, **kwargs)
