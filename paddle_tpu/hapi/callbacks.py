"""Training callbacks (reference: python/paddle/hapi/callbacks.py)."""
from __future__ import annotations

import time

import numpy as np

__all__ = ["Callback", "CallbackList", "ProgBarLogger", "ModelCheckpoint",
           "EarlyStopping", "LRScheduler"]


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params

    def on_begin(self, mode, logs=None):
        pass

    def on_end(self, mode, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_batch_begin(self, mode, step, logs=None):
        pass

    def on_batch_end(self, mode, step, logs=None):
        pass

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks=None, model=None, verbose=2, metrics=None,
                 log_freq=10):
        self.callbacks = list(callbacks or [])
        if verbose and not any(isinstance(c, ProgBarLogger)
                               for c in self.callbacks):
            self.callbacks.insert(0, ProgBarLogger(log_freq, verbose))
        for c in self.callbacks:
            c.set_model(model)
            c.set_params({"metrics": metrics or [], "verbose": verbose})

    def _call(self, name, *args):
        for c in self.callbacks:
            getattr(c, name)(*args)

    def on_begin(self, mode, logs=None):
        self._call("on_begin", mode, logs)
        if mode == "train":
            self._call("on_train_begin", logs)

    def on_end(self, mode, logs=None):
        self._call("on_end", mode, logs)
        if mode == "train":
            self._call("on_train_end", logs)

    def on_epoch_begin(self, epoch, logs=None):
        self._call("on_epoch_begin", epoch, logs)

    def on_epoch_end(self, epoch, logs=None):
        self._call("on_epoch_end", epoch, logs)

    def on_batch_begin(self, mode, step, logs=None):
        self._call("on_batch_begin", mode, step, logs)
        if mode == "train":
            self._call("on_train_batch_begin", step, logs)

    def on_batch_end(self, mode, step, logs=None):
        self._call("on_batch_end", mode, step, logs)
        if mode == "train":
            self._call("on_train_batch_end", step, logs)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=10, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose
        self._t0 = None
        self._count = 0

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self._t0 = time.perf_counter()
        self._count = 0
        self.steps = (logs or {}).get("steps")

    def on_batch_end(self, mode, step, logs=None):
        if mode != "train" or not self.verbose:
            return
        logs = logs or {}
        bs = logs.get("batch_size") or 1
        self._count += bs
        if (step + 1) % self.log_freq == 0:
            dt = time.perf_counter() - self._t0
            ips = self._count / max(dt, 1e-9)
            items = " - ".join(
                f"{k}: {v:.4f}" for k, v in logs.items()
                if isinstance(v, (int, float)) and k != "batch_size")
            total = f"/{self.steps}" if self.steps else ""
            eta = ""
            if self.steps:
                remaining = max(self.steps - (step + 1), 0)
                eta_s = remaining * dt / (step + 1)
                eta = f" - ETA: {int(eta_s // 60):d}:{int(eta_s % 60):02d}"
            print(f"Epoch {self.epoch} step {step + 1}{total}: {items}"
                  f" - {ips:.1f} samples/s{eta}")

    def on_epoch_end(self, epoch, logs=None):
        if not self.verbose:
            return
        logs = logs or {}
        items = " - ".join(f"{k}: {v:.4f}" for k, v in logs.items()
                           if isinstance(v, (int, float))
                           and k != "batch_size")
        dt = time.perf_counter() - (self._t0 or time.perf_counter())
        print(f"Epoch {epoch} done ({dt:.1f}s): {items}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            self.model.save(f"{self.save_dir}/{epoch}")

    def on_end(self, mode, logs=None):
        if mode == "train" and self.save_dir:
            self.model.save(f"{self.save_dir}/final")


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        if mode == "max" or (mode == "auto" and "acc" in monitor):
            self.better = lambda a, b: a > b + self.min_delta
            self.best = -np.inf
        else:
            self.better = lambda a, b: a < b - self.min_delta
            self.best = np.inf
        self.wait = 0

    def on_epoch_end(self, epoch, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor) or logs.get("eval_" + self.monitor)
        if cur is None:
            return
        if self.better(cur, self.best):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if hasattr(lr, "step") else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if s and self.by_step:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if s and self.by_epoch:
            s.step()
