"""High-level Model API (reference: python/paddle/hapi/model.py —
Model.fit:1052, evaluate:1750, predict:1999)."""
from __future__ import annotations

import numbers
import time
from typing import List, Optional

import numpy as np

from ..core.autograd import no_grad
from ..core.tensor import Tensor
from ..io import DataLoader
from .callbacks import CallbackList, ProgBarLogger

__all__ = ["Model"]


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self._ddp = None
        self.stop_training = False

    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = _to_list(metrics)
        # distributed-aware fit (reference DynamicGraphAdapter: under a
        # multi-process launch the network trains through DataParallel —
        # grads allreduce over the transport — while save/state_dict
        # keep addressing the inner network). Wrap once: re-preparing
        # (e.g. to swap optimizers) must not re-register grad hooks.
        from .. import distributed as dist

        if self._ddp is None and dist.is_initialized() \
                and dist.get_world_size() > 1:
            self._ddp = dist.parallel.DataParallel(self.network)
        return self

    @property
    def _train_network(self):
        return self._ddp if self._ddp is not None else self.network

    # -- core steps ---------------------------------------------------------
    def train_batch(self, inputs, labels=None, update=True):
        net = self._train_network
        net.train()
        inputs = _to_list(inputs)
        labels = _to_list(labels)
        outputs = net(*[self._t(x) for x in inputs])
        losses = self._compute_loss(outputs, labels)
        total = losses if isinstance(losses, Tensor) else sum(losses)
        total.backward()
        if update:
            self._optimizer.step()
            self._optimizer.clear_grad()
        metrics = self._update_metrics(outputs, labels)
        loss_val = [float(total.numpy())]
        return (loss_val, metrics) if metrics else loss_val

    @no_grad()
    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        inputs = _to_list(inputs)
        labels = _to_list(labels)
        outputs = self.network(*[self._t(x) for x in inputs])
        losses = self._compute_loss(outputs, labels) if self._loss else None
        metrics = self._update_metrics(outputs, labels)
        if losses is not None:
            total = losses if isinstance(losses, Tensor) else sum(losses)
            return [float(total.numpy())], metrics
        return metrics

    @no_grad()
    def predict_batch(self, inputs):
        self.network.eval()
        inputs = _to_list(inputs)
        outputs = self.network(*[self._t(x) for x in inputs])
        outs = _to_list(outputs)
        return [o.numpy() for o in outs]

    def _t(self, x):
        return x if isinstance(x, Tensor) else Tensor(np.asarray(x))

    def _compute_loss(self, outputs, labels):
        if self._loss is None:
            return outputs if isinstance(outputs, Tensor) else outputs[0]
        outs = _to_list(outputs)
        labs = [self._t(l) for l in labels]
        return self._loss(*(outs + labs))

    def _update_metrics(self, outputs, labels):
        outs = _to_list(outputs)
        labs = [self._t(l) for l in labels]
        results = []
        for metric in self._metrics:
            computed = metric.compute(*(outs + labs))
            r = metric.update(*_to_list(computed))
            results.append(r)
        return results

    # -- loops --------------------------------------------------------------
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        dist_sampler = None
        if not isinstance(train_data, DataLoader):
            if self._ddp is not None:
                # shard the dataset across ranks (reference fit uses
                # DistributedBatchSampler under a parallel env)
                from ..io import DistributedBatchSampler

                dist_sampler = DistributedBatchSampler(
                    train_data, batch_size=batch_size, shuffle=shuffle,
                    drop_last=drop_last)
                train_loader = DataLoader(train_data,
                                          batch_sampler=dist_sampler,
                                          num_workers=num_workers)
            else:
                train_loader = DataLoader(train_data,
                                          batch_size=batch_size,
                                          shuffle=shuffle,
                                          drop_last=drop_last,
                                          num_workers=num_workers)
        else:
            train_loader = train_data
            dist_sampler = getattr(train_loader, "batch_sampler", None)
            if not hasattr(dist_sampler, "set_epoch"):
                dist_sampler = None
        eval_loader = None
        if eval_data is not None:
            eval_loader = eval_data if isinstance(eval_data, DataLoader) \
                else DataLoader(eval_data, batch_size=batch_size,
                                num_workers=num_workers)
        cbks = CallbackList(callbacks, model=self, verbose=verbose,
                            metrics=["loss"] + [
                                n for m in self._metrics
                                for n in _to_list(m.name())],
                            log_freq=log_freq)
        cbks.on_begin("train")
        steps = None
        try:
            steps = len(train_loader)
        except Exception:
            pass
        for epoch in range(epochs):
            if self.stop_training:
                break
            for m in self._metrics:
                m.reset()
            if dist_sampler is not None:
                # fresh per-epoch shuffle order across ranks
                dist_sampler.set_epoch(epoch)
            cbks.on_epoch_begin(epoch, {"steps": steps})
            logs = {}
            for step, batch in enumerate(train_loader):
                if num_iters is not None and step >= num_iters:
                    break
                cbks.on_batch_begin("train", step, logs)
                ins, labs = self._split_batch(batch)
                out = self.train_batch(ins, labs)
                logs = self._pack_logs(out)
                logs["batch_size"] = (
                    ins[0].shape[0] if hasattr(ins[0], "shape") else None)
                cbks.on_batch_end("train", step, logs)
            if hasattr(self._optimizer, "_learning_rate") and hasattr(
                    self._optimizer._learning_rate, "step"):
                self._optimizer._learning_rate.step()
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate(eval_loader, verbose=0)
                logs.update({"eval_" + k: v for k, v in eval_logs.items()})
            cbks.on_epoch_end(epoch, logs)
            if save_dir and (epoch + 1) % save_freq == 0:
                self.save(f"{save_dir}/epoch_{epoch}")
        cbks.on_end("train", logs)
        return self

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_iters=None):
        loader = eval_data if isinstance(eval_data, DataLoader) else \
            DataLoader(eval_data, batch_size=batch_size,
                       num_workers=num_workers)
        for m in self._metrics:
            m.reset()
        losses = []
        for step, batch in enumerate(loader):
            if num_iters is not None and step >= num_iters:
                break
            ins, labs = self._split_batch(batch)
            out = self.eval_batch(ins, labs)
            if isinstance(out, tuple) and self._loss:
                losses.append(out[0][0])
        logs = {}
        if losses:
            logs["loss"] = float(np.mean(losses))
        for m in self._metrics:
            for name, val in zip(_to_list(m.name()),
                                 _to_list(m.accumulate())):
                logs[name] = val
        if verbose:
            print("Eval:", " - ".join(f"{k}: {v:.4f}" for k, v in
                                      logs.items()))
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        loader = test_data if isinstance(test_data, DataLoader) else \
            DataLoader(test_data, batch_size=batch_size,
                       num_workers=num_workers)
        outputs = []
        for batch in loader:
            ins, _ = self._split_batch(batch)
            outputs.append(self.predict_batch(ins))
        if stack_outputs:
            n_out = len(outputs[0])
            return [np.concatenate([o[i] for o in outputs])
                    for i in range(n_out)]
        return outputs

    def _split_batch(self, batch):
        if isinstance(batch, (list, tuple)):
            if len(batch) >= 2:
                return _to_list(batch[0]), _to_list(batch[1])
            return _to_list(batch[0]), []
        return [batch], []

    def _pack_logs(self, out):
        logs = {}
        if isinstance(out, tuple):
            losses, metrics = out
            logs["loss"] = losses[0]
            i = 0
            for m in self._metrics:
                for name, val in zip(_to_list(m.name()),
                                     _to_list(metrics[i])):
                    logs[name] = float(val)
                i += 1
        else:
            logs["loss"] = out[0]
        return logs

    # -- io ------------------------------------------------------------------
    def save(self, path, training=True):
        from ..framework.io import save as fsave

        fsave(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            fsave(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework.io import load as fload

        state = fload(path + ".pdparams")
        self.network.set_state_dict(state)
        if not reset_optimizer and self._optimizer is not None:
            import os

            if os.path.exists(path + ".pdopt"):
                self._optimizer.set_state_dict(fload(path + ".pdopt"))

    def parameters(self, *args, **kwargs):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        from .summary import summary as _summary

        return _summary(self.network, input_size, dtype)
