"""Global RNG state.

Reference analog: paddle.seed + per-device generators
(python/paddle/framework/random.py) and the TP-determinism RNG tracker
(fleet/meta_parallel/parallel_layers/random.py). JAX randomness is functional
(explicit keys), so the framework keeps a key-splitting generator for eager
mode and a *traceable* key context for compiled steps: inside
`rng_guard(key)` every draw folds a fresh counter into the provided (possibly
traced) key — deterministic, replayable, and jit-safe.

The named-state tracker (`RNGStatesTracker`) reproduces the reference's
model-parallel seed discipline: "global" states agree across TP ranks
(e.g. residual dropout), "local" states differ per rank (e.g. attention
dropout inside a sharded region)."""
from __future__ import annotations

import contextlib
import os
import threading

import jax
import jax.numpy as jnp

__all__ = ["seed", "get_rng_state", "set_rng_state", "next_key", "rng_guard",
           "RNGStatesTracker", "get_rng_tracker", "default_seed"]

_DEFAULT_SEED = 34342423252

_prng_impl_chosen = False


def _choose_prng_impl():
    """Pick the PRNG implementation once, before the first key exists.

    TPU has no native threefry — it lowers to a long scalar ALU chain that
    measurably dominates dropout-heavy train steps (BERT-base with p=0.1
    spent ~25% of its step time generating threefry bits; the on-chip RNG
    behind 'unsafe_rbg' removes that entirely). CPU/GPU keep threefry for
    bit-exact reproducibility of existing test expectations.
    Override with FLAGS_prng_impl=threefry2x32|rbg|unsafe_rbg."""
    global _prng_impl_chosen
    if _prng_impl_chosen:
        return
    _prng_impl_chosen = True
    impl = os.environ.get("FLAGS_prng_impl", "auto")
    if impl == "auto":
        try:
            impl = ("unsafe_rbg"
                    if jax.default_backend() in ("tpu", "axon")
                    else "threefry2x32")
        except Exception:
            impl = "threefry2x32"
    if impl != "threefry2x32":
        jax.config.update("jax_default_prng_impl", impl)


class _RNGState(threading.local):
    def __init__(self):
        self._key = None
        self.counter = 0
        self.draws = 0
        # when set, draws fold counters into this (possibly traced) key
        self.guard_key = None
        self.guard_counter = 0
        self.deferred_prev = None

    @property
    def key(self):
        if self._key is None:
            _choose_prng_impl()
            self._key = jax.random.key(_DEFAULT_SEED)
        return self._key

    @key.setter
    def key(self, value):
        self._key = value


_state = _RNGState()


def default_seed():
    return _DEFAULT_SEED


def seed(s: int):
    _choose_prng_impl()
    _state.key = jax.random.key(int(s))
    _state.counter = 0
    return s


def get_rng_state():
    return (_state.key, _state.counter)


def set_rng_state(state):
    _state.key, _state.counter = state


_DEFERRED = object()


def next_key():
    """Return a fresh PRNG key. Inside rng_guard, derives from the guard key
    (trace-safe); otherwise advances the global eager state."""
    _state.draws += 1
    if _state.guard_key is _DEFERRED:
        _materialize_deferred_guard()
    if _state.guard_key is not None:
        _state.guard_counter += 1
        return jax.random.fold_in(_state.guard_key, _state.guard_counter)
    _state.counter += 1
    return jax.random.fold_in(_state.key, _state.counter)


def _materialize_deferred_guard():
    """First draw under a deferred guard: advance the PARENT stream (the
    global state or an enclosing guard) by exactly one key and adopt it as
    this guard's key — the same derivation the dispatcher's cached
    executables use, so the i-th post-seed draw is identical whether an op
    runs its first (probe) call or a warm cached call."""
    prev_guard, prev_counter = _state.deferred_prev
    _state.guard_key, _state.guard_counter = prev_guard, prev_counter
    _state.draws -= 1          # the parent advance is not a user draw
    k = next_key()
    # propagate the parent's consumed counter back through the restore in
    # deferred_rng_guard's finally (it restores from deferred_prev)
    _state.deferred_prev = (_state.guard_key, _state.guard_counter)
    _state.guard_key = k
    _state.guard_counter = 0


@contextlib.contextmanager
def deferred_rng_guard():
    """Guard for a cache entry's first (probe) run: materializes its key
    lazily on the first draw, so ops that consume no randomness leave the
    RNG stream untouched while RNG ops derive keys exactly like the
    dispatcher's cached fast path (fold_in(parent_key, ++parent_counter)
    then per-draw fold_ins)."""
    prev = (_state.guard_key, _state.guard_counter)
    prev_deferred = getattr(_state, "deferred_prev", None)
    _state.deferred_prev = prev
    _state.guard_key = _DEFERRED
    _state.guard_counter = 0
    try:
        yield
    finally:
        _state.guard_key, _state.guard_counter = _state.deferred_prev
        _state.deferred_prev = prev_deferred


def draw_count():
    """Total next_key() draws on this thread — the dispatcher's jit cache
    probes this around an op's first (eager) run to learn whether the op
    consumes randomness and therefore needs a key threaded as a traced
    input (a baked-in constant key would freeze the op's randomness)."""
    return _state.draws


@contextlib.contextmanager
def rng_guard(key):
    """Route all framework randomness through `key` (a jax PRNG key or int
    seed, may be traced). Used by the compiled train step so dropout etc. get
    fresh per-step randomness as a function input, not baked constants."""
    if isinstance(key, int):
        _choose_prng_impl()
        key = jax.random.key(key)
    elif hasattr(key, "dtype") and not jax.dtypes.issubdtype(
        key.dtype, jax.dtypes.prng_key
    ):
        # a raw scalar (e.g. per-step seed passed into a jitted step)
        _choose_prng_impl()
        key = jax.random.key(key.astype(jnp.uint32))
    prev = (_state.guard_key, _state.guard_counter)
    _state.guard_key = key
    _state.guard_counter = 0
    try:
        yield
    finally:
        _state.guard_key, _state.guard_counter = prev


class RNGStatesTracker:
    """Named RNG streams for TP determinism (reference:
    fleet/meta_parallel/parallel_layers/random.py RNGStatesTracker)."""

    def __init__(self):
        self.states = {}

    def reset(self):
        self.states = {}

    def add(self, name, seed_):
        if name in self.states:
            raise ValueError(f"rng state {name} already exists")
        _choose_prng_impl()
        self.states[name] = (jax.random.key(int(seed_)), 0)

    @contextlib.contextmanager
    def rng_state(self, name="model-parallel-rng"):
        if name not in self.states:
            self.add(name, _DEFAULT_SEED + hash(name) % 10007)
        key, counter = self.states[name]
        prev = (_state.guard_key, _state.guard_counter)
        _state.guard_key = key
        _state.guard_counter = counter
        try:
            yield
        finally:
            self.states[name] = (key, _state.guard_counter)
            _state.guard_key, _state.guard_counter = prev


_tracker = RNGStatesTracker()


def get_rng_tracker():
    return _tracker
