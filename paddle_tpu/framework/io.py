"""paddle.save / paddle.load (reference: python/paddle/framework/io.py:743,985
— pickle-based nested state dicts)."""
from __future__ import annotations

import os
import pickle

import numpy as np

from ..core.tensor import Tensor

__all__ = ["save", "load"]

_PROTO = 4


def _to_storable(obj):
    if isinstance(obj, Tensor):
        return ("__tensor__", np.asarray(obj.numpy()))
    if isinstance(obj, dict):
        return {k: _to_storable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_to_storable(v) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    return obj


def _from_storable(obj, return_numpy=False):
    if isinstance(obj, tuple) and len(obj) == 2 and obj[0] == "__tensor__":
        return obj[1] if return_numpy else Tensor(obj[1])
    if isinstance(obj, dict):
        return {k: _from_storable(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_from_storable(v, return_numpy) for v in obj]
    if isinstance(obj, tuple):
        return tuple(_from_storable(v, return_numpy) for v in obj)
    return obj


def save(obj, path, protocol=_PROTO, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_storable(obj), f, protocol=protocol)


def load(path, return_numpy=False, **configs):
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _from_storable(obj, return_numpy=return_numpy)


# -- async checkpoint save (reference: paddle.async_save /
# clear_async_save_task_queue, python/paddle/framework/io.py) -----------
_async_tasks = []


def async_save(obj, path, protocol=_PROTO, sync_other_task=False,
               **configs):
    """Snapshot `obj` host-side NOW, write it on a background thread —
    training continues while the checkpoint hits disk (the reference's
    async_save contract: the caller may mutate params right after the
    call)."""
    import tempfile
    import threading

    if sync_other_task:
        clear_async_save_task_queue()
    snapshot = _to_storable(obj)        # host copy before returning

    def work():
        d = os.path.dirname(path) or "."
        os.makedirs(d, exist_ok=True)
        # unique temp per call: overlapping saves to the same path must
        # not interleave bytes; last os.replace wins atomically
        fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                pickle.dump(snapshot, f, protocol=protocol)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    _async_tasks[:] = [t for t in _async_tasks if t.is_alive()]
    t = threading.Thread(target=work, daemon=True)
    t.start()
    _async_tasks.append(t)
    return t


def clear_async_save_task_queue():
    """Block until all queued async saves finish (reference API)."""
    while _async_tasks:
        _async_tasks.pop().join()
