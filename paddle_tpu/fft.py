"""paddle_tpu.fft (reference: python/paddle/fft.py) — jnp.fft backed.

Backend note: some TPU runtimes (the axon relay among them) report
UNIMPLEMENTED for complex FFT. A one-time probe detects this and routes
the transforms through the host CPU backend with a device round-trip —
differentiable (device_put has a transpose) and transparent to callers;
the native path is used whenever the attached backend supports FFT.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as _np

from .core.dispatch import apply

_FFT_MODE = None  # None=unprobed | "native" | "cpu"


def _fft_mode():
    global _FFT_MODE
    if _FFT_MODE is None:
        # NO execution probe: a failed FFT poisons the relay's device
        # stream (every subsequent op errors), and compile-only probes
        # succeed — detection is by backend capability flag.
        from .core.place import backend_lacks_complex

        _FFT_MODE = "cpu" if backend_lacks_complex() else "native"
    return _FFT_MODE


def _hostable(f):
    """Run `f` on the host backend (with a differentiable device
    round-trip) when the attached device lacks FFT support."""

    def g(a, *args, **kw):
        if isinstance(a, jax.core.Tracer) or _fft_mode() == "native":
            return f(a, *args, **kw)
        dev = next(iter(a.devices())) if hasattr(a, "devices") else None
        cpu = jax.devices("cpu")[0]
        # default_device too: jnp.fft's norm path runs an internally
        # jitted scaling helper that otherwise lands on the (FFT-less)
        # default backend
        with jax.default_device(cpu):
            out = f(jax.device_put(a, cpu), *args, **kw)
        if dev is None or dev.platform == "cpu" \
                or jnp.issubdtype(out.dtype, jnp.complexfloating):
            # complex stays host-resident: backends that lack FFT lack
            # complex arrays altogether
            return out
        return jax.device_put(out, dev)

    return g


class _F:
    """jnp.fft with the host fallback applied per-function."""

    def __getattr__(self, name):
        return _hostable(getattr(jnp.fft, name))


_F = _F()

__all__ = ["fft", "ifft", "fft2", "ifft2", "fftn", "ifftn", "rfft", "irfft",
           "rfft2", "irfft2", "rfftn", "irfftn", "hfft", "ihfft", "fftfreq",
           "rfftfreq", "fftshift", "ifftshift"]


def _mk(name, fn, has_n=True):
    if has_n:
        def op(x, n=None, axis=-1, norm="backward", name=None):
            return apply(lambda a: fn(a, n=n, axis=int(axis), norm=norm), x,
                         op_name=name)
    else:
        def op(x, s=None, axes=None, norm="backward", name=None):
            return apply(lambda a: fn(a, s=s, axes=axes, norm=norm), x,
                         op_name=name)
    op.__name__ = name
    return op


fft = _mk("fft", _F.fft)
ifft = _mk("ifft", _F.ifft)
rfft = _mk("rfft", _F.rfft)
irfft = _mk("irfft", _F.irfft)
hfft = _mk("hfft", _F.hfft)
ihfft = _mk("ihfft", _F.ihfft)
fftn = _mk("fftn", _F.fftn, has_n=False)
ifftn = _mk("ifftn", _F.ifftn, has_n=False)
rfftn = _mk("rfftn", _F.rfftn, has_n=False)
irfftn = _mk("irfftn", _F.irfftn, has_n=False)


def fft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return apply(lambda a: _F.fft2(a, s=s, axes=axes, norm=norm), x,
                 op_name="fft2")


def ifft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return apply(lambda a: _F.ifft2(a, s=s, axes=axes, norm=norm), x,
                 op_name="ifft2")


def rfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return apply(lambda a: _F.rfft2(a, s=s, axes=axes, norm=norm), x,
                 op_name="rfft2")


def irfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return apply(lambda a: _F.irfft2(a, s=s, axes=axes, norm=norm), x,
                 op_name="irfft2")


def fftfreq(n, d=1.0, dtype=None, name=None):
    from .core.tensor import Tensor

    return Tensor(_F.fftfreq(int(n), d))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    from .core.tensor import Tensor

    return Tensor(_F.rfftfreq(int(n), d))


def fftshift(x, axes=None, name=None):
    return apply(lambda a: _F.fftshift(a, axes=axes), x,
                 op_name="fftshift")


def ifftshift(x, axes=None, name=None):
    return apply(lambda a: _F.ifftshift(a, axes=axes), x,
                 op_name="ifftshift")


def _resolve_axes(ndim, s, axes):
    """numpy rule: axes default to the last len(s) axes (all axes when s
    is also None)."""
    if axes is not None:
        return list(axes)
    if s is not None:
        return list(range(ndim - len(s), ndim))
    return list(range(ndim))


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    """N-D Hermitian FFT (reference fftn_c2r semantics): FORWARD fft over
    the leading axes, hfft over the last."""
    def fn(a):
        ax = _resolve_axes(a.ndim, s, axes)
        o = a
        for i, axis in enumerate(ax[:-1]):
            o = _F.fft(o, n=None if s is None else s[i], axis=axis,
                       norm=norm)
        return _F.hfft(o, n=None if s is None else s[-1],
                       axis=ax[-1], norm=norm)

    return apply(fn, x, op_name="hfftn")


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    """N-D inverse Hermitian FFT (reference fftn_r2c-conjugate semantics,
    ihfftn(x) == ifftn(x) truncated to the half spectrum): INVERSE fft
    over the leading axes, ihfft over the last."""
    def fn(a):
        ax = _resolve_axes(a.ndim, s, axes)
        o = _F.ihfft(a, n=None if s is None else s[-1], axis=ax[-1],
                     norm=norm)
        for i, axis in enumerate(ax[:-1]):
            o = _F.ifft(o, n=None if s is None else s[i], axis=axis,
                        norm=norm)
        return o

    return apply(fn, x, op_name="ihfftn")


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return hfftn(x, s=s, axes=axes, norm=norm)


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return ihfftn(x, s=s, axes=axes, norm=norm)


__all__ += ["hfft2", "ihfft2", "hfftn", "ihfftn"]
