"""paddle.cost_model (reference: python/paddle/cost_model/) — cost
estimation over a captured program; delegates to the auto-tuner's
XLA-measured cost model."""


class CostModel:
    def profile_measure(self, program, device="tpu", fetch_cost_list=None):
        from .distributed.auto_tuner import estimate_cost

        try:
            return estimate_cost(program)
        except Exception:
            return {"time": None}
