"""paddle.cost_model (reference: python/paddle/cost_model/) — cost
estimation over a captured program.

Two tiers:

- ``CostModel.profile_measure`` — the measured path, delegating to the
  auto-tuner's XLA-measured cost model (needs a device).
- ``op_flops`` / ``StaticCostModel`` — the static path: per-op FLOPs
  from recorded shapes, the roofline inputs
  (FLOPs, bytes moved, arithmetic intensity) the ptprog memory report
  prints per op.  Estimates are name-keyed heuristics in the reference
  op-benchmark style: exact for the dominant dense ops (matmul/conv
  classes), elementwise-cost fallback for the long tail — good enough
  to rank ops and spot the memory-bound region, not a simulator.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

__all__ = ["CostModel", "StaticCostModel", "op_flops",
           "collective_bytes"]


def _numel(aval) -> int:
    n = 1
    for d in getattr(aval, "shape", ()):
        n *= int(d)
    return n


def op_flops(name: str, in_avals: Sequence, out_avals: Sequence) -> int:
    """FLOPs estimate for one recorded op entry from its abstract
    input/output values (jax.ShapeDtypeStruct-likes)."""
    lname = name.lower()
    out_elems = sum(_numel(a) for a in out_avals)
    if any(k in lname for k in ("recompute::", "fused_")):
        # composed region: charge the elementwise floor.  This must be
        # checked FIRST — an auto_fuse region's name carries its member
        # list (e.g. "fused_auto[matmul+relu]"), and letting it fall
        # into the matmul branch would price the whole region as one
        # dense op with a bogus contraction dim.  The replay's true
        # compute is the sum of its members (the pre-fusion rows show
        # it); the roofline signal fusion changes is BYTES, which are
        # computed from the region's external inputs/outputs.
        return out_elems
    if any(k in lname for k in ("matmul", "linear", "fc_", "bmm",
                                "addmm", "dense")):
        # out[..., m, n] contracted over k = last dim of the first input
        if in_avals and len(in_avals[0].shape) >= 1 and out_avals:
            k = int(in_avals[0].shape[-1])
            return 2 * _numel(out_avals[0]) * k
        return 2 * out_elems
    if "conv" in lname:
        # out * (Cin/groups * prod(kernel)) * 2, kernel from the weight
        if len(in_avals) >= 2 and len(in_avals[1].shape) >= 3 \
                and out_avals:
            w = in_avals[1].shape
            k = 1
            for d in w[1:]:
                k *= int(d)
            return 2 * _numel(out_avals[0]) * k
        return 2 * out_elems
    if any(k in lname for k in ("softmax", "norm", "attention")):
        return 5 * out_elems          # exp/sum/div or mean/var/scale
    # elementwise / data-movement floor
    return out_elems


def collective_bytes(kind: str, nbytes: int, group_size: int) -> int:
    """Bytes moved per participant by one collective over a tensor of
    ``nbytes`` (the FULL, unsharded size) across ``group_size`` devices
    — the standard ring formulas the PT902 reshard estimates and the
    static auto-tuner's communication-volume scoring both use:

    - all_gather / reduce_scatter / all_to_all: ``(n-1)/n * nbytes``
    - all_reduce (reduce-scatter + all-gather): ``2 * (n-1)/n * nbytes``
    - p2p / broadcast / everything else: ``nbytes``
    """
    n = max(int(group_size), 1)
    if n <= 1:
        return 0
    frac = (n - 1) / n
    if kind in ("all_reduce", "reduce"):
        return int(2 * nbytes * frac)
    if kind in ("all_gather", "reduce_scatter", "all_to_all",
                "all_to_all_single", "reshard"):
        return int(nbytes * frac)
    return int(nbytes)


class StaticCostModel:
    """FLOPs/bytes roofline over a recorded ``static.Program`` without
    executing it — shapes come from the ptprog abstract dataflow."""

    def estimate(self, program, feed_spec=None, name: str = "program"):
        """Per-op roofline rows + totals for a captured Program.
        Returns the ptprog ``MemoryReport`` (peak bytes, live ranges,
        per-op flops/bytes/intensity, recompute/amp savings)."""
        from .analysis.program import ProgramIR, abstract_run, \
            estimate_memory

        ir = ProgramIR(program, feed_spec=feed_spec, name=name)
        env, _findings = abstract_run(ir)
        return estimate_memory(ir, env)


class CostModel:
    def profile_measure(self, program, device="tpu", fetch_cost_list=None):
        from .distributed.auto_tuner import estimate_cost

        try:
            return estimate_cost(program)
        except Exception:
            return {"time": None}

    # static estimation rides along on the measured interface so callers
    # holding a CostModel can get the roofline without a device
    def static_estimate(self, program, feed_spec=None):
        return StaticCostModel().estimate(program, feed_spec=feed_spec)
