"""SOT-equivalent partial-graph capture (sublayer-granular regions).

Reference analog: paddle.jit.sot — the bytecode-level graph capture
(/root/reference/python/paddle/jit/sot/opcode_translator/eval_frame_callback.py)
that, on a graph break, compiles the convertible subgraphs and runs the
unconvertible bytecode eagerly between them, so `to_static` never
silently loses the whole graph.

TPU-native shape: instead of bytecode surgery, regions are SUBLAYERS.
When a whole-function trace breaks (even after the dy2static AST
lowering), each sublayer of the broken callable becomes a candidate
compiled REGION: its forward is rebound to a single
`core.dispatch.apply` call over its functional form, so the entire
sublayer executes as one cached XLA executable — forward AND backward
ride the per-signature jit cache and the whole-sweep cached backward.
A region whose own body graph-breaks splits recursively into ITS
children; only the truly unconvertible code (plus per-op glue in parent
forwards) runs eagerly. A model with one `.item()` in one branch keeps
every other block compiled instead of forfeiting the whole step.

Traceability is validated with `jax.eval_shape` on first call (abstract
trace, no compile, no execution on device), so the decision to split is
made cheaply and deterministically.
"""
from __future__ import annotations

import itertools
import types
import warnings

import jax

from ..core.dispatch import apply, _fp_value, _Uncacheable
from ..core.tensor import Tensor
from ..profiler import metrics as _metrics
from . import functional as FB

__all__ = ["enable_partial_capture", "disable_partial_capture",
           "region_count"]

_region_ids = itertools.count(1)

# partial-capture observability: regions installed, and per-region graph
# breaks (each break = one more sublayer whose glue runs eagerly)
_m_regions = _metrics.counter("jit/partial_regions_installed")
_m_region_break = _metrics.counter("jit/region_break_count")


def _break_errors():
    from .api import _trace_break_errors

    return _trace_break_errors()


def _has_own_forward(layer):
    from ..nn.layer.layers import Layer

    fwd = getattr(type(layer), "forward", None)
    return fwd is not None and fwd is not Layer.forward


def _tracer_in(values):
    for v in values:
        a = v._value if isinstance(v, Tensor) else v
        if isinstance(a, jax.core.Tracer):
            return True
    return False


class _Region:
    """Instance-level forward replacement: one compiled region per
    sublayer. States: unvalidated -> compiled (routes through apply) or
    broken (restored to eager body, children become regions)."""

    def __init__(self, layer, orig_forward):
        self.layer = layer
        self.orig = orig_forward
        self.validated = False
        self.broken = False
        self.entered = 0
        self.rid = next(_region_ids)

    # -- the pure functional form (one apply call == one region) --------
    def _region_fn(self, kwargs, train):
        layer = self.layer

        def region_fn(p, b, *ins):
            # reentrancy guard: the region's own body invoking
            # layer.forward must run the plain body, not this region
            # again (apply's first-call probe runs region_fn with
            # CONCRETE arrays, so the tracer check alone can't stop it)
            self.entered += 1
            try:
                out, new_buf = FB.call_functional(layer, p, b, ins,
                                                  kwargs, train=train)
            finally:
                self.entered -= 1
            return out, new_buf

        return region_fn

    def _validate(self, params, buffers, args, kwargs, train):
        """Abstract-trace the region once; a trace-break here means the
        region must split into its children. Tensors ANYWHERE in the
        (args, kwargs) pytree are abstracted — apply() flattens nested
        tensors as dynamic leaves, so validating with a nested tensor
        left concrete would pass here and then trace-break (and silently
        disable the cache entry) on the real call."""
        layer = self.layer
        flat, tree = jax.tree.flatten(
            (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor))
        tpos = [i for i, a in enumerate(flat) if isinstance(a, Tensor)]

        def probe(p, b, tarrs):
            full = list(flat)
            for i, ta in zip(tpos, tarrs):
                full[i] = ta
            a2, kw2 = jax.tree.unflatten(tree, full)
            out, _ = FB.call_functional(layer, p, b, a2, kw2,
                                        train=train)
            return out

        sds = lambda t: jax.ShapeDtypeStruct(t.shape, t._value.dtype) \
            if isinstance(t, Tensor) else t
        jax.eval_shape(probe,
                       {k: sds(v) for k, v in params.items()},
                       {k: sds(v) for k, v in buffers.items()},
                       tuple(sds(flat[i]) for i in tpos))

    def __call__(self, *args, **kwargs):
        layer = self.layer
        if self.broken or self.entered:
            return self.orig(*args, **kwargs)
        params, buffers = FB.layer_state(layer)
        leaves = [a for a in jax.tree.leaves(
            (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor))]
        if _tracer_in(leaves) or _tracer_in(params.values()):
            # inside an outer trace (a parent region or a full to_static
            # trace is in flight): run the plain body
            return self.orig(*args, **kwargs)
        train = bool(layer.training)
        try:
            kw_fp = _fp_value(kwargs, 0) if kwargs else ()
        except _Uncacheable:
            return self.orig(*args, **kwargs)
        if not self.validated:
            try:
                self._validate(params, buffers, args, kwargs, train)
            except _break_errors() as e:
                self.broken = True
                _m_region_break.inc()
                _metrics.inc("jit/retrace_cause/" + type(e).__name__)
                n = _split_into_children(layer)
                warnings.warn(
                    f"partial capture: region '{type(layer).__name__}' "
                    f"graph-breaks ({type(e).__name__}); split into {n} "
                    f"child region(s), its own glue runs eagerly",
                    RuntimeWarning, stacklevel=2)
                return self.orig(*args, **kwargs)
            self.validated = True
        out, new_buf = apply(
            self._region_fn(kwargs, train), dict(params), dict(buffers),
            *args, op_name=f"region:{type(layer).__name__}",
            op_key=("partial_region", self.rid, train, kw_fp))
        if new_buf:
            FB.write_back(layer, {}, {
                k: (t._value if isinstance(t, Tensor) else t)
                for k, t in new_buf.items()})
        return out


def _split_into_children(layer) -> int:
    """Install regions on every direct child (recursing through
    containers without a forward of their own, e.g. LayerList)."""
    n = 0
    for child in getattr(layer, "_sub_layers", {}).values():
        if child is None:
            continue
        if _has_own_forward(child):
            n += _install(child)
        else:
            n += _split_into_children(child)
    return n


def _install(layer) -> int:
    if "__pt_region__" in layer.__dict__:
        return 0
    region = _Region(layer, layer.forward)
    layer.__dict__["__pt_region__"] = region
    layer.forward = region
    _m_regions.inc()
    return 1


def enable_partial_capture(root) -> int:
    """Give every direct sublayer of `root` a compiled-region forward
    (the root's own body — the code that graph-broke — stays eager).
    Returns the number of regions installed. Idempotent."""
    return _split_into_children(root)


def disable_partial_capture(root) -> None:
    """Remove every region installed under `root` (tests / undo)."""
    stack = [root]
    seen = set()
    while stack:
        l = stack.pop()
        if id(l) in seen or l is None:
            continue
        seen.add(id(l))
        region = l.__dict__.pop("__pt_region__", None)
        if region is not None:
            l.forward = region.orig
        stack.extend(getattr(l, "_sub_layers", {}).values())


def region_count(root, seen=None) -> int:
    """Active regions under `root`. Pass a shared `seen` set to count
    overlapping roots without double-counting."""
    n = 0
    stack = [root]
    if seen is None:
        seen = set()
    while stack:
        l = stack.pop()
        if id(l) in seen or l is None:
            continue
        seen.add(id(l))
        if "__pt_region__" in l.__dict__:
            n += 1
        stack.extend(getattr(l, "_sub_layers", {}).values())
    return n
