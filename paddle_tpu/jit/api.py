"""@to_static and the compiled TrainStep.

Reference analog: paddle.jit.to_static (python/paddle/jit/api.py:173) +
SOT/dy2static (33.6 kLoC of AST/bytecode machinery) + the static
PirInterpreter. On TPU none of that machinery is needed: the functional
bridge (jit/functional.py) re-traces the SAME eager model as a pure function
and jax.jit compiles it — trace-and-compile IS the graph capture. TrainStep
is the whole-graph compiled training step (forward+backward+optimizer in one
XLA executable with donated buffers), the single most important performance
primitive on TPU (SURVEY.md §7 "hard parts" (a)).
"""
from __future__ import annotations

import functools
import threading
import time
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp

from ..core.autograd import no_grad
from ..core.tensor import Tensor
from ..framework.random import next_key, rng_guard
from ..profiler import RecordEvent
from ..profiler import metrics as _metrics
from . import functional as FB

# compile-bridge observability (profiler/metrics.py): compiles, their
# wall time, trace-break retraces (with per-cause tallies) and whole-graph
# breaks — the numbers that explain "why is this step slow / eager"
_m_compile = _metrics.counter("jit/compile_count")
_m_compile_ms = _metrics.histogram("jit/compile_ms")
_m_retrace = _metrics.counter("jit/retrace_count")
_m_graph_break = _metrics.counter("jit/graph_break_count")


def _record_retrace(exc):
    _m_retrace.inc()
    _metrics.inc("jit/retrace_cause/" + type(exc).__name__)


def note_retrace(cause: str):
    """Public retrace tally for engine-level executable caches that
    re-specialize outside this module (e.g. the serving decode window
    compiling a new shape): same counter, cause-tagged, so
    ``jit/retrace_count`` stays the one number that answers "what keeps
    recompiling"."""
    _m_retrace.inc()
    _metrics.inc("jit/retrace_cause/" + cause)


def _timed_first_call(callable_, *a, **kw):
    """First call of a fresh jit entry = trace+lower+compile+run; count
    it and histogram the wall time under a RecordEvent span."""
    _m_compile.inc()
    with RecordEvent("jit::compile"):
        t0 = time.perf_counter()
        out = callable_(*a, **kw)
    _m_compile_ms.observe((time.perf_counter() - t0) * 1e3)
    return out

__all__ = ["to_static", "TrainStep", "in_to_static_tracing", "save", "load",
           "ignore_module", "not_to_static", "enable_to_static"]


def _trace_break_errors():
    """Exceptions that mean 'this Python cannot be traced' — the
    graph-break condition. On the first such error StaticFunction/
    TrainStep run the dy2static AST converter (jit/dy2static.py — the
    program_translator/SOT analog) and retry with tensor-dependent
    if/while/for lowered to lax control flow; only if the retry also
    breaks do they fall back to eager with a warning."""
    import jax.errors as jerr

    from .dy2static import DynamicControlFlowError

    return (jerr.TracerBoolConversionError,
            jerr.TracerArrayConversionError,
            jerr.TracerIntegerConversionError,
            jerr.ConcretizationTypeError,
            DynamicControlFlowError)


def _reachable_values(fn):
    """Objects a plain function can see: closure cells, bound self, and
    the globals it actually LOADs (dis-precise — co_names also holds
    attribute names, which must not trigger conversion of unrelated
    same-named module globals)."""
    values = []
    for c in getattr(fn, "__closure__", None) or ():
        try:
            values.append(c.cell_contents)
        except ValueError:        # empty cell
            pass
    if hasattr(fn, "__self__"):
        values.append(fn.__self__)
    code = getattr(fn, "__code__", None)
    if code is not None:
        import dis

        g = getattr(fn, "__globals__", {})
        try:
            loaded = {i.argval for i in dis.get_instructions(code)
                      if i.opname == "LOAD_GLOBAL"}
        except Exception:
            loaded = set()
        values.extend(g[n] for n in loaded if n in g)
    return values


def _try_convert_target(target) -> bool:
    """Run the dy2static converter over a Layer tree or plain function.
    Returns True if anything was converted (caller should retry the
    trace). Layer forwards are rebound in place (instance-level) — the
    converted code is semantics-preserving for concrete conditions, so
    eager execution through the same instance stays correct."""
    from ..nn.layer.layers import Layer
    from . import dy2static

    if isinstance(target, Layer):
        return dy2static.convert_layer_tree(target)
    return False


def _warn_graph_break(name: str, exc: Exception, n_regions: int = 0):
    import warnings

    if n_regions:
        tail = (f"Partial-graph capture installed {n_regions} compiled "
                f"sublayer region(s); only the breaking code runs eagerly "
                f"(SOT-analog graph break).")
    else:
        tail = ("Falling back to EAGER execution for this callable "
                "(graph break). Use jax-compatible control flow "
                "(lax.cond/where) to recover whole-graph compilation.")
    _m_graph_break.inc()
    _metrics.set_gauge("jit/partial_regions", n_regions)
    warnings.warn(
        f"to_static: '{name}' contains Python that cannot be traced "
        f"({type(exc).__name__}: {str(exc).splitlines()[0][:120]}). "
        + tail, RuntimeWarning, stacklevel=3)


def _reachable_layers(fn):
    from ..nn.layer.layers import Layer

    return [v for v in _reachable_values(fn) if isinstance(v, Layer)]


def _enable_partial_capture_for(target, is_layer: bool) -> int:
    """On a whole-graph break, keep every convertible sublayer compiled
    (jit/partial_capture.py — the SOT partial-graph analog). Plain-
    function targets reach models through closures/globals; capture any
    Layer they can see. Returns the number of ACTIVE regions (newly
    installed plus any already present from an earlier break), and never
    raises — the caller is the last-resort eager fallback."""
    from .partial_capture import enable_partial_capture, region_count

    roots = []
    try:
        roots = [target] if is_layer else _reachable_layers(target)
        for r in roots:
            enable_partial_capture(r)
    except Exception:
        pass
    # count AFTER install attempts (a partial failure may still have
    # installed regions); the shared `seen` set dedupes overlapping
    # roots (a closure can expose both a model and its own sublayers)
    try:
        seen = set()
        return sum(region_count(r, seen) for r in roots)
    except Exception:
        return 0

_tracing = threading.local()


def in_to_static_tracing():
    return getattr(_tracing, "active", False)


class _TracingGuard:
    def __enter__(self):
        self.prev = getattr(_tracing, "active", False)
        _tracing.active = True
        return self

    def __exit__(self, *exc):
        _tracing.active = self.prev
        return False


class InputSpec:
    """reference: paddle.static.InputSpec."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name
        self.stop_gradient = stop_gradient


def _unwrap_arg(a):
    if isinstance(a, Tensor):
        return a._value
    if isinstance(a, (list, tuple, dict)):
        return jax.tree.map(
            lambda t: t._value if isinstance(t, Tensor) else t, a,
            is_leaf=lambda x: isinstance(x, Tensor))
    return a


def _wrap_arg(a):
    if isinstance(a, (list, tuple, dict)):
        return jax.tree.map(
            lambda v: Tensor(v, stop_gradient=True)
            if isinstance(v, jax.Array) else v, a)
    if isinstance(a, jax.Array):
        return Tensor(a, stop_gradient=True)
    return a


def _is_dynamic_arg(a):
    """Array-like args trace; everything else (ints, strs, None, flags)
    is STATIC — part of the program spec, like the reference's
    to_static non-tensor arguments (a generate loop's max_new_tokens
    must shape buffers, not become a traced scalar)."""
    import numpy as _np

    if isinstance(a, (Tensor, jax.Array, _np.ndarray)):
        return True
    if isinstance(a, (list, tuple, dict)):
        return any(isinstance(l, (Tensor, jax.Array, _np.ndarray))
                   for l in jax.tree.leaves(
                       a, is_leaf=lambda x: isinstance(x, Tensor)))
    return False


class StaticFunction:
    """A compiled callable over a Layer or plain function."""

    def __init__(self, fn_or_layer, input_spec=None, train=None):
        from ..nn.layer.layers import Layer

        self._is_layer = isinstance(fn_or_layer, Layer)
        self._target = fn_or_layer
        self._input_spec = input_spec
        self._train = train
        self._compiled = None
        self._n_calls = 0

    def _build_layer_fn(self, static_pos=()):
        layer = self._target

        def pure(params, buffers, seed, *in_arrays):
            with _TracingGuard(), rng_guard(seed):
                out, new_buf = FB.call_functional(
                    layer, params, buffers, in_arrays,
                    train=layer.training if self._train is None
                    else self._train)
            return out, new_buf

        return jax.jit(pure,
                       static_argnums=tuple(p + 3 for p in static_pos))

    def _build_fn(self, static_pos=()):
        fn = self._target

        def pure(seed, *in_arrays, **kw):
            with _TracingGuard(), rng_guard(seed), no_grad():
                ins = [_wrap_arg(a) for a in in_arrays]
                out = fn(*ins, **kw)
            return jax.tree.map(
                lambda x: x._value if isinstance(x, Tensor) else x, out,
                is_leaf=lambda x: isinstance(x, Tensor))

        return jax.jit(pure,
                       static_argnums=tuple(p + 1 for p in static_pos))

    def __call__(self, *args, **kwargs):
        if getattr(self, "_fallback", False):
            return self._eager_call(*args, **kwargs)
        # pytree-aware: Tensors nested in list/tuple/dict args (kv-cache
        # lists, state dicts) unwrap to array pytrees for the jit
        in_arrays = [_unwrap_arg(a) for a in args]
        seed = next_key()
        try:
            return self._run_compiled(seed, in_arrays, kwargs)
        except _trace_break_errors() as e:
            _record_retrace(e)
            # dy2static retry: lower tensor-dependent control flow to
            # lax.cond/while_loop, then re-trace once
            if not getattr(self, "_converted", False):
                self._converted = True
                converted = self._convert_target()
                if converted:
                    self._compiled = None
                    try:
                        return self._run_compiled(seed, in_arrays, kwargs)
                    except _trace_break_errors() as e2:
                        e = e2
                    except Exception:
                        # converted code misbehaved beyond a trace break:
                        # undo the instance rebinds before surfacing
                        self._restore_converted()
                        raise
            n_regions = _enable_partial_capture_for(self._target,
                                                    self._is_layer)
            _warn_graph_break(getattr(self._target, "__name__",
                                      type(self._target).__name__), e,
                              n_regions)
            self._fallback = True
            return self._eager_call(*args, **kwargs)

    def _restore_converted(self):
        from .dy2static import restore_layer_tree

        targets = [self._target] if self._is_layer else \
            _reachable_layers(self._target)
        for t in targets:
            restore_layer_tree(t)
        self._compiled = None

    def _convert_target(self):
        from .dy2static import convert_function, convert_layer_tree

        if self._is_layer:
            return _try_convert_target(self._target)
        converted = False
        new = convert_function(self._target)
        if new is not None:
            self._target = new
            converted = True
        # a plain-function target (e.g. `lambda x: model(x)`) reaches the
        # model through its closure, its bound self, or a referenced
        # global — convert any Layer it can see so sublayer forwards
        # lower too
        for v in _reachable_layers(self._target):
            converted = convert_layer_tree(v) or converted
        return converted

    @staticmethod
    def _static_positions(in_arrays):
        def hashable(a):
            try:
                hash(a)
            except TypeError:
                return False
            return True

        return tuple(i for i, a in enumerate(in_arrays)
                     if not _is_dynamic_arg(a) and hashable(a))

    def _run_compiled(self, seed, in_arrays, kwargs):
        # non-tensor hashable args are STATIC (jit specializes per
        # value): a generate loop's max_new_tokens/eos_token_id shape
        # the program instead of becoming traced scalars
        static_pos = self._static_positions(in_arrays)
        if not isinstance(self._compiled, dict):
            self._compiled = {}
        jitted = self._compiled.get(static_pos)
        fresh = jitted is None
        if self._is_layer:
            if fresh:
                jitted = self._compiled[static_pos] = \
                    self._build_layer_fn(static_pos)
            params = FB.current_params(self._target)
            buffers = FB.current_buffers(self._target)
            if fresh:
                out, new_buf = _timed_first_call(
                    jitted, params, buffers, seed, *in_arrays)
            else:
                out, new_buf = jitted(params, buffers, seed, *in_arrays)
            FB.write_back(self._target, {}, new_buf)
        else:
            if fresh:
                jitted = self._compiled[static_pos] = \
                    self._build_fn(static_pos)
                out = _timed_first_call(jitted, seed, *in_arrays, **kwargs)
            else:
                out = jitted(seed, *in_arrays, **kwargs)
        return jax.tree.map(lambda x: Tensor(x), out)

    def _eager_call(self, *args, **kwargs):
        # mirror the compiled path's semantics: plain functions traced
        # under no_grad with stop_gradient inputs stay that way eagerly.
        # Only array-like args become Tensors — None/str/flags pass
        # through untouched, as they did through the traced pytree.
        def wrap(a, stop_grad):
            if isinstance(a, Tensor) or a is None \
                    or isinstance(a, (str, bool)):
                return a
            if hasattr(a, "__array__") or isinstance(
                    a, (int, float, complex, list, tuple)):
                try:
                    return Tensor(a, stop_gradient=stop_grad)
                except (TypeError, ValueError):
                    return a
            return a

        if self._is_layer:
            ins = [wrap(a, False) for a in args]
            return self._target(*ins, **kwargs)
        ins = [wrap(a, True) for a in args]
        with no_grad():
            return self._target(*ins, **kwargs)

    # compat surface
    def concrete_program(self):
        return None

    @property
    def forward(self):
        return self


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, **kwargs):
    """Decorator/wrapper: compile a Layer or function with XLA."""
    def deco(fn):
        from ..nn.layer.layers import Layer

        if isinstance(fn, Layer):
            sf = StaticFunction(fn, input_spec)
            fn.forward_static = sf
            # replace forward path: calling layer goes through compiled fn
            orig_forward = fn.forward
            fn._static_function = sf
            return fn
        if callable(fn):
            return StaticFunction(fn, input_spec)
        raise TypeError("to_static expects a Layer or callable")

    if function is not None:
        return deco(function)
    return deco


def capture_program(fn, input_spec, name_prefix: str = "x"):
    """Record ``fn``'s op stream into a fresh ``static.Program`` for
    IR-level analysis (ptprog: ``python -m paddle_tpu.analysis
    --program``), without compiling or executing a replay.

    ``input_spec`` is a list of InputSpecs (or (shape, dtype) tuples);
    each becomes a registered feed placeholder, so the analyzer knows
    the feed signature.  Returns the recorded Program with ``fn``'s
    tensor outputs appended as fetch targets.  This is the
    ``@to_static`` capture surface exposed as data: the same define-by-
    run recording ``program_guard`` does, shaped for pre-flight checks
    (shape/dtype dataflow, peak-memory, collective consistency) rather
    than for Executor replay.
    """
    from .. import static as _static

    main = _static.Program()
    with _static.program_guard(main, _static.Program()):
        ins = []
        for i, spec in enumerate(input_spec):
            if isinstance(spec, (tuple, list)):
                spec = InputSpec(spec[0], spec[1] if len(spec) > 1
                                 else "float32")
            ins.append(_static.data(spec.name or f"{name_prefix}{i}",
                                    spec.shape, spec.dtype))
        out = fn(*ins)
    for t in jax.tree.leaves(out, is_leaf=lambda x: isinstance(x, Tensor)):
        if isinstance(t, Tensor):
            main.fetch_targets.append(t)
    return main


def lower_stablehlo(fn, input_spec, name_prefix: str = "x",
                    auto_fuse: bool = False) -> str:
    """Capture ``fn`` at ``input_spec`` and emit its StableHLO module
    text — the jit-side entry of the fusion compiler's artifact path
    (``jax.jit(...).lower(...).as_text()`` over the recorded replay).
    With ``auto_fuse=True`` the cost-model fusion pass runs (verified)
    before lowering, so the emitted module reflects the fused op list.
    """
    prog = capture_program(fn, input_spec, name_prefix)
    if auto_fuse:
        from ..static import passes as _passes

        _passes.PassManager(["auto_fuse"]).run(prog, verify=True)
    from ..static.stablehlo import program_stablehlo

    return program_stablehlo(prog)


def not_to_static(fn):
    return fn


def ignore_module(modules):
    return None


def enable_to_static(flag: bool):
    return None


def build_train_step(model, loss_fn, optimizer, train=True, amp_dtype=None):
    """Build the fused forward+backward+update step function and jit it
    with donated param/opt-state/buffer pytrees.

    Shared by TrainStep (eager-facing) and the auto-parallel static Engine.
    Non-trainable params (stop_gradient / trainable=False) and params
    outside the optimizer's parameter list pass through untouched —
    matching eager Optimizer.step's filter.
    """
    opt = optimizer
    update = opt._update
    grad_clip = opt._grad_clip
    idx_of = {id(p): i for i, p in enumerate(opt._parameter_list)}
    lr_wd_by_name = {}
    trainable = set()
    for name, p in model.named_parameters():
        lr_wd_by_name[name] = opt._param_lr_wd(p, idx_of.get(id(p), 0))
        if id(p) in idx_of and getattr(p, "trainable", True) \
                and not p.stop_gradient:
            trainable.add(name)

    def step(params, opt_states, buffers, lr, step_i, seed, *batch):
        frozen = {k: v for k, v in params.items() if k not in trainable}

        def compute_loss(p_train):
            p = dict(frozen)
            p.update(p_train)
            if amp_dtype is not None:
                p = jax.tree.map(
                    lambda a: a.astype(amp_dtype)
                    if jnp.issubdtype(a.dtype, jnp.floating) else a, p)
            with _TracingGuard(), rng_guard(seed):
                out, new_buf = FB.call_functional(
                    model, p, buffers, batch[:-1] if loss_fn else batch,
                    train=train)
                if loss_fn is not None:
                    with no_grad():
                        out_t = jax.tree.map(lambda x: Tensor(x), out)
                        label = Tensor(batch[-1])
                        loss_t = loss_fn(out_t, label)
                    loss = loss_t._value
                else:
                    loss = out
            return loss.astype(jnp.float32), new_buf

        p_train = {k: v for k, v in params.items() if k in trainable}
        (loss, new_buf), grads = jax.value_and_grad(
            compute_loss, has_aux=True)(p_train)
        names = list(p_train.keys())
        gs = [grads[k] for k in names]
        if grad_clip is not None:
            gs = grad_clip.apply(gs)
        new_params = dict(frozen)
        new_states = {}
        for k, g in zip(names, gs):
            st = dict(opt_states.get(k) or {})
            st["_step"] = step_i
            lr_mult, wd = lr_wd_by_name.get(k, (1.0, 0.0))
            p_new, st_new = update(params[k], g.astype(params[k].dtype),
                                   st, lr * lr_mult, wd)
            st_new.pop("_step", None)
            new_params[k] = p_new
            new_states[k] = st_new
        # untouched states pass through (donated buffers must be returned)
        for k, st in opt_states.items():
            if k not in new_states:
                new_states[k] = st
        return new_params, new_states, new_buf, loss

    return jax.jit(step, donate_argnums=(0, 1, 2))


class TrainStep:
    """One fused XLA executable: forward + backward + optimizer update.

    Usage:
        step = TrainStep(model, loss_fn, optimizer)
        loss = step(x, y)          # params updated in place

    The pytree of parameters and optimizer state is donated each call, so
    XLA updates weights in place in HBM (no copy), and dropout randomness
    comes in through a per-step key — fresh every call, deterministic under
    paddle_tpu.seed().
    """

    def __init__(self, model, loss_fn, optimizer, train=True):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.train = train
        self._compiled = None
        self._param_names = None

    def _build(self):
        return build_train_step(self.model, self.loss_fn, self.optimizer,
                                train=self.train)

    def _opt_states(self, params: Dict) -> Dict:
        opt = self.optimizer
        states = {}
        name_by_id = {id(p): k for k, p in
                      self.model.named_parameters()}
        for p in opt._parameter_list:
            k = name_by_id.get(id(p))
            if k is None:
                continue
            states[k] = opt._get_state(p)
        return states

    def __call__(self, *batch):
        if getattr(self, "_fallback", False):
            return self._eager_step(*batch)
        fresh = self._compiled is None
        if fresh:
            self._compiled = self._build()
        params = FB.current_params(self.model)
        buffers = FB.current_buffers(self.model)
        opt_states = self._opt_states(params)
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        self.optimizer._step_count += 1
        step_i = jnp.asarray(self.optimizer._step_count, jnp.float32)
        seed = next_key()
        arrays = [b._value if isinstance(b, Tensor) else jnp.asarray(b)
                  for b in batch]
        try:
            if fresh:
                new_params, new_states, new_buf, loss = _timed_first_call(
                    self._compiled, params, opt_states, buffers, lr,
                    step_i, seed, *arrays)
            else:
                new_params, new_states, new_buf, loss = self._compiled(
                    params, opt_states, buffers, lr, step_i, seed, *arrays)
        except _trace_break_errors() as e:
            _record_retrace(e)
            retried = False
            if not getattr(self, "_converted", False):
                self._converted = True
                if self._convert_model_and_loss():
                    self._compiled = self._build()
                    try:
                        new_params, new_states, new_buf, loss = \
                            self._compiled(params, opt_states, buffers,
                                           lr, step_i, seed, *arrays)
                        retried = True
                    except _trace_break_errors() as e2:
                        e = e2
                    except Exception:
                        from .dy2static import restore_layer_tree

                        restore_layer_tree(self.model)
                        if hasattr(self.loss_fn, "_sub_layers"):
                            restore_layer_tree(self.loss_fn)
                        self._compiled = None
                        raise
            if not retried:
                n_regions = _enable_partial_capture_for(self.model, True)
                if self.loss_fn is not None and hasattr(self.loss_fn,
                                                        "_sub_layers"):
                    n_regions += _enable_partial_capture_for(self.loss_fn,
                                                             True)
                _warn_graph_break(type(self.model).__name__, e, n_regions)
                self._fallback = True
                self.optimizer._step_count -= 1   # eager step re-counts
                return self._eager_step(*batch)
        FB.write_back(self.model, new_params, new_buf)
        name_to_param = dict(self.model.named_parameters())
        for k, st in new_states.items():
            p = name_to_param.get(k)
            if p is not None:
                self.optimizer._accumulators[id(p)] = st
        return Tensor(loss)

    def _convert_model_and_loss(self):
        """dy2static both the model tree and the loss function (a branch
        in a custom loss graph-breaks the whole fused step otherwise)."""
        from ..nn.layer.layers import Layer
        from .dy2static import convert_function, convert_layer_tree

        converted = _try_convert_target(self.model)
        lf = self.loss_fn
        if lf is not None:
            if isinstance(lf, Layer):
                converted = convert_layer_tree(lf) or converted
            elif callable(lf):
                new = convert_function(lf)
                if new is not None:
                    self.loss_fn = new
                    converted = True
        return converted

    def _eager_step(self, *batch):
        """Graph-break path: plain eager forward/backward/update — the
        numerics of the compiled step without whole-graph compilation."""
        ins = [b if isinstance(b, Tensor) else Tensor(b) for b in batch]
        was_training = self.model.training
        if was_training != self.train:
            self.model.train() if self.train else self.model.eval()
        try:
            if self.loss_fn is not None:
                out = self.model(*ins[:-1])
                loss = self.loss_fn(out, ins[-1])
            else:
                loss = self.model(*ins)
            loss.backward()
            self.optimizer.step()
            self.optimizer.clear_grad()
        finally:
            if was_training != self.train:
                self.model.train() if was_training else self.model.eval()
        return loss.detach()


def save(layer, path, input_spec=None, **configs):
    """paddle.jit.save — persists the state dict, and (with input_spec,
    matching the reference's deploy contract) ALSO the serialized
    multi-platform executable the inference Predictor loads in a fresh
    process (reference: jit.save -> inference program + params)."""
    from ..framework.io import save as fsave

    state = layer.state_dict() if hasattr(layer, "state_dict") else {}
    fsave({"state_dict": state,
           "class": type(layer).__name__}, path + ".pdparams")
    if input_spec is not None:
        from ..inference import save_inference_model

        save_inference_model(path, layer, input_spec)


def load(path, **configs):
    from ..framework.io import load as fload

    return fload(path + ".pdparams")


class TranslatedLayer:
    """reference jit/translated_layer.py: the callable returned by
    jit.load for a saved-inference artifact. Here jit.load already
    returns a callable Layer-like object; this class is its public
    type alias for isinstance checks."""

    def __new__(cls, *args, **kwargs):
        raise TypeError("TranslatedLayer is constructed by paddle.jit.load")


def set_code_level(level=100):
    """reference jit/sot: dump generated code at the given log level —
    trace-based capture has no generated bytecode, kept as a no-op."""
    return None


def set_verbosity(level=0, also_to_stdout=False):
    """reference jit/dy2static logging verbosity — routed to the
    framework logger."""
    import logging

    logging.getLogger("paddle_tpu.jit").setLevel(
        logging.DEBUG if level and level > 0 else logging.WARNING)
