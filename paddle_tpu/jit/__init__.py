from . import api
from . import functional
from .api import (InputSpec, StaticFunction, TrainStep, TranslatedLayer,
                  capture_program, lower_stablehlo, set_code_level,
                  set_verbosity, enable_to_static, ignore_module, load,
                  not_to_static, save, to_static)
