"""Data-dependent control-flow capture for @to_static.

Reference analog: the dy2static AST transpiler
(/root/reference/python/paddle/jit/dy2static/program_translator.py and its
transformers/) and the SOT bytecode tracer (jit/sot/opcode_translator/
eval_frame_callback.py) — 33.6 kLoC that rewrite Python `if`/`while`/`for`
over tensor values into graph ops. The TPU-native design is much smaller
because XLA already has structured control flow: this module rewrites the
offending constructs into calls to runtime helpers that

  * keep EXACT plain-Python semantics when the condition is concrete
    (eager mode, or non-tensor conditions under trace), and
  * lower to `lax.cond` / `lax.while_loop` when the condition is traced,

so one converted function serves both eager and compiled execution, and
`to_static` compiles a model with tensor-dependent branches/loops into ONE
XLA executable instead of graph-breaking to eager.

Conversion is attempted lazily: the plain trace runs first (zero overhead
for trace-friendly code); on a trace-break error `StaticFunction` converts
the target (and, for Layers, every sublayer forward) and retries. Code the
transformer cannot prove convertible (early returns inside a branch,
break/continue, non-range iteration, names not bound before the branch)
is left untouched — the existing graph-break fallback still applies.
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
import types
from typing import Optional

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["convert_function", "convert_layer_tree",
           "DynamicControlFlowError", "HELPERS"]


class DynamicControlFlowError(Exception):
    """A construct reached the traced path but cannot lower to XLA control
    flow (mismatched branch structures, non-array state, ...). Treated by
    StaticFunction as a graph-break condition."""


# ---------------------------------------------------------------------------
# runtime helpers (injected into converted functions' globals)
# ---------------------------------------------------------------------------

def _unwrap(v):
    return v._value if isinstance(v, Tensor) else v


def _is_traced(v):
    return isinstance(_unwrap(v), jax.core.Tracer)


def _unwrap_state(state):
    """Flatten loop/branch state for lax control flow. Each element's
    spec is True (a Tensor), False (opaque non-tensor), or
    (treedef, tensor_mask) for a list/tuple/dict CONTAINING Tensors —
    so list-carried state (kv-cache lists, per-layer tuples) rides
    through lax.while_loop/cond as array pytrees (VERDICT r3 #5)."""
    from ..core.tensor import Tensor as _T

    flat = []
    spec = []
    for v in state:
        if isinstance(v, _T):
            spec.append(True)
            flat.append(v._value)
        elif isinstance(v, (list, tuple, dict)):
            leaves, td = jax.tree.flatten(
                v, is_leaf=lambda x: isinstance(x, _T))
            mask = [isinstance(l, _T) for l in leaves]
            if any(mask):
                spec.append((td, tuple(mask)))
                flat.append(tuple(l._value if m else l
                                  for l, m in zip(leaves, mask)))
            else:
                spec.append(False)
                flat.append(v)
        else:
            spec.append(False)
            flat.append(v)
    return flat, spec


def _rewrap_state(flat, spec):
    from ..core.tensor import Tensor as _T

    out = []
    for v, sp in zip(flat, spec):
        if sp is True:
            out.append(v if isinstance(v, _T) else Tensor(v))
        elif sp is False:
            out.append(v)
        else:
            td, mask = sp
            if len(v) != len(mask):
                raise DynamicControlFlowError(
                    "container state changed structure inside traced "
                    f"control flow ({len(mask)} -> {len(v)} leaves); "
                    "carried lists/dicts must keep a fixed shape")
            leaves = [Tensor(l) if m and not isinstance(l, _T) else l
                      for l, m in zip(v, mask)]
            out.append(jax.tree.unflatten(td, leaves))
    return tuple(out)


def _scalar_bool(cv):
    cv = jnp.asarray(cv)
    if cv.ndim:
        cv = cv.reshape(())
    return cv.astype(bool)


def _recording():
    from ..core.dispatch import _ProgramRecorder

    return _ProgramRecorder.active


def _all_tensor_state(cond, state):
    from ..core.tensor import Tensor

    return isinstance(cond, Tensor) and \
        all(isinstance(v, Tensor) for v in state)


def _all_tensor_state_only(state):
    from ..core.tensor import Tensor

    return bool(state) and all(isinstance(v, Tensor) for v in state)


def _record_cond_region(cond, true_fn, false_fn, state):
    """Record a tensor-dependent branch as ONE structured Program entry
    (the PIR Region analog, VERDICT r3 #3b): both branches are captured
    into sub-Programs; the recorded fn replays them under lax.cond, so
    the branch is decided by the FED value at Executor replay time —
    not frozen to the branch taken at capture.

    Capture semantics (inherent to data-dependent capture — the
    reference's IfOp lowering builds both blocks the same way): BOTH
    branch functions execute once at record time, so host-side side
    effects of the untaken branch (python counters, module-attribute
    mutation) run during capture even though replay will skip it. The
    returned values come from the taken branch."""
    from .. import static as _static
    from ..core.dispatch import apply
    from ..core.tensor import Tensor

    rec = _recording()
    with _static._sub_recorder(None):   # capture probes outside the rec
        p_t, in_t, out_t, _ = _static.capture_region(true_fn, state)
        p_f, in_f, out_f, _ = _static.capture_region(false_fn, state)
    if len(out_t) != len(out_f) or len(out_t) != len(state):
        raise DynamicControlFlowError(
            "branches must return one tensor per carried state name to "
            f"record a cond region (state {len(state)}, true "
            f"{len(out_t)}, false {len(out_f)}) — a branch rebinding a "
            "carried tensor to a non-tensor cannot be captured")
    t_replay = _static.region_replay(p_t, in_t, out_t)
    f_replay = _static.region_replay(p_f, in_f, out_f)

    def cond_fn(c, *fs):
        return jax.lax.cond(_scalar_bool(c), t_replay, f_replay, *fs)

    out = apply(cond_fn, cond, *state, op_name="cond", cacheable=False)
    _static.promote_last_to_region(
        rec, [("true", p_t), ("false", p_f)])
    out = out if isinstance(out, (list, tuple)) else (out,)
    return tuple(out)


def _record_while_region(test_fn, body_fn, state):
    """Record a tensor-dependent while as ONE structured entry whose fn
    replays [test]/[body] sub-Programs under lax.while_loop."""
    from .. import static as _static
    from ..core.dispatch import apply

    rec = _recording()
    with _static._sub_recorder(None):
        p_c, in_c, out_c, _ = _static.capture_region(
            lambda *s: (test_fn(*s),), state)
        p_b, in_b, out_b, _ = _static.capture_region(body_fn, state)
    if len(out_b) != len(state):
        raise DynamicControlFlowError(
            "while body must return the full loop state to record a "
            "while region")
    if not out_c:
        raise DynamicControlFlowError(
            "while test produced no tensor output (concrete python "
            "condition); recording falls back to the unrolled loop")
    c_replay = _static.region_replay(p_c, in_c, out_c)
    b_replay = _static.region_replay(p_b, in_b, out_b)

    def while_fn(*fs):
        return jax.lax.while_loop(
            lambda s: _scalar_bool(c_replay(*s)[0]),
            lambda s: b_replay(*s), tuple(fs))

    out = apply(while_fn, *state, op_name="while_loop", cacheable=False)
    _static.promote_last_to_region(rec, [("test", p_c), ("body", p_b)])
    out = out if isinstance(out, (list, tuple)) else (out,)
    return tuple(out)


def __pt_if__(cond, true_fn, false_fn, state):
    cv = _unwrap(cond)
    if not isinstance(cv, jax.core.Tracer):
        if _recording() is not None and _all_tensor_state(cond, state):
            try:
                return _record_cond_region(cond, true_fn, false_fn,
                                           state)
            except (DynamicControlFlowError, TypeError, ValueError):
                pass   # unrepresentable region: record unrolled (legacy)
        return true_fn(*state) if bool(cv) else false_fn(*state)
    flat, was_tensor = _unwrap_state(state)

    def mk(branch):
        def g(*fs):
            out = branch(*_rewrap_state(fs, was_tensor))
            return tuple(_unwrap_state(out)[0])

        return g

    try:
        out = jax.lax.cond(_scalar_bool(cv), mk(true_fn), mk(false_fn),
                           *flat)
    except (TypeError, ValueError) as e:
        raise DynamicControlFlowError(
            f"if-branch cannot lower to lax.cond: {e}") from e
    return _rewrap_state(out, was_tensor)


def __pt_while__(test_fn, body_fn, state):
    if _recording() is not None \
            and all(not _is_traced(v) for v in state) \
            and _all_tensor_state_only(state):
        try:
            return _record_while_region(test_fn, body_fn, state)
        except (DynamicControlFlowError, TypeError, ValueError):
            pass       # unrepresentable region: record unrolled (legacy)
    cv = _unwrap(test_fn(*state))
    if not isinstance(cv, jax.core.Tracer) \
            and not any(_is_traced(v) for v in state):
        while bool(cv):
            state = body_fn(*state)
            cv = _unwrap(test_fn(*state))
        return tuple(state)
    flat, was_tensor = _unwrap_state(state)

    def cond_fun(fs):
        return _scalar_bool(_unwrap(test_fn(*_rewrap_state(fs, was_tensor))))

    def body_fun(fs):
        out = body_fn(*_rewrap_state(fs, was_tensor))
        return tuple(_unwrap_state(out)[0])

    try:
        # loop-carried avals must be stable: pre-broadcast weak scalars by
        # one body application is NOT done — jax reports mismatches, which
        # we surface as a graph-break condition
        out = jax.lax.while_loop(cond_fun, body_fun, tuple(flat))
    except (TypeError, ValueError) as e:
        raise DynamicControlFlowError(
            f"while-loop cannot lower to lax.while_loop: {e}") from e
    return _rewrap_state(out, was_tensor)


class _UnboundLoopVar:
    """Binding for a loop variable after a zero-trip for-range with no
    prior binding: any use raises NameError, matching plain Python
    (where the name would simply not exist)."""

    __slots__ = ("name",)

    def __init__(self, name):
        object.__setattr__(self, "name", name)

    def _raise(self, *a, **k):
        raise NameError(
            f"name '{object.__getattribute__(self, 'name')}' is not "
            "defined (a zero-trip for-range left the loop variable "
            "unbound)")

    def __getattr__(self, attr):
        self._raise()

    __bool__ = __int__ = __float__ = __index__ = _raise
    __add__ = __radd__ = __sub__ = __rsub__ = __mul__ = __rmul__ = _raise
    __eq__ = __ne__ = __lt__ = __le__ = __gt__ = __ge__ = _raise
    __repr__ = __str__ = __hash__ = _raise


def __pt_for_range__(rargs, body_fn, state, prior=None, has_prior=False,
                     name="<loop var>"):
    """prior/has_prior: the loop variable's binding before the loop (when
    definitely bound) so a zero-trip range preserves it like Python; with
    no prior binding a zero-trip range binds a NameError-raising sentinel
    (plain Python leaves the name undefined)."""
    rargs = tuple(_unwrap(a) for a in rargs)
    if len(rargs) == 1:
        start, stop, step = 0, rargs[0], 1
    elif len(rargs) == 2:
        start, stop, step = rargs[0], rargs[1], 1
    else:
        start, stop, step = rargs
    if not any(isinstance(a, jax.core.Tracer)
               for a in (start, stop, step)):
        i = prior if has_prior else _UnboundLoopVar(name)
        for i in range(int(start), int(stop), int(step)):
            state = body_fn(i, *state)
        return (i,) + tuple(state)
    flat, was_tensor = _unwrap_state(state)
    start = jnp.asarray(start, jnp.int32)
    stop = jnp.asarray(stop, jnp.int32)
    step = jnp.asarray(step, jnp.int32)

    def cond_fun(carry):
        i, _ = carry
        return jnp.where(step > 0, i < stop, i > stop)

    def body_fun(carry):
        i, fs = carry
        out = body_fn(i, *_rewrap_state(fs, was_tensor))
        return i + step, tuple(_unwrap_state(out)[0])

    try:
        i_final, out = jax.lax.while_loop(cond_fun, body_fun,
                                          (start, tuple(flat)))
    except (TypeError, ValueError) as e:
        raise DynamicControlFlowError(
            f"for-range cannot lower to lax.while_loop: {e}") from e
    # python leaves the target at the last executed index; a zero-trip
    # loop keeps its prior binding when one exists
    i_out = i_final - step
    if has_prior and prior is not None:
        i_out = jnp.where(i_final != start, i_out,
                          jnp.asarray(_unwrap(prior), jnp.int32))
    return (Tensor(i_out),) + _rewrap_state(out, was_tensor)


def __pt_and__(left, right_thunk):
    if not _is_traced(left):
        return left and right_thunk()
    right = right_thunk()
    return Tensor(jnp.logical_and(_scalar_bool(_unwrap(left)),
                                  _scalar_bool(_unwrap(right))))


def __pt_or__(left, right_thunk):
    if not _is_traced(left):
        return left or right_thunk()
    right = right_thunk()
    return Tensor(jnp.logical_or(_scalar_bool(_unwrap(left)),
                                 _scalar_bool(_unwrap(right))))


def __pt_not__(v):
    if not _is_traced(v):
        return not v
    return Tensor(jnp.logical_not(_scalar_bool(_unwrap(v))))


HELPERS = {
    "__pt_if__": __pt_if__,
    "__pt_while__": __pt_while__,
    "__pt_for_range__": __pt_for_range__,
    "__pt_and__": __pt_and__,
    "__pt_or__": __pt_or__,
    "__pt_not__": __pt_not__,
}


# ---------------------------------------------------------------------------
# the AST transformer
# ---------------------------------------------------------------------------

class _Unsupported(Exception):
    pass


def _assigned_names(stmts):
    """Names (re)bound by a statement list, NOT descending into nested
    function/class definitions (their scopes are separate)."""
    names = []

    def targets_of(t):
        if isinstance(t, ast.Name):
            names.append(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                targets_of(e)
        elif isinstance(t, ast.Starred):
            targets_of(t.value)
        # Attribute/Subscript targets mutate objects, not local bindings

    class V(ast.NodeVisitor):
        def visit_FunctionDef(self, node):
            names.append(node.name)

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_ClassDef(self, node):
            names.append(node.name)

        def visit_Lambda(self, node):
            pass

        def visit_Assign(self, node):
            for t in node.targets:
                targets_of(t)
            self.generic_visit(node.value)

        def visit_AugAssign(self, node):
            targets_of(node.target)
            self.generic_visit(node.value)

        def visit_AnnAssign(self, node):
            targets_of(node.target)
            if node.value:
                self.generic_visit(node.value)

        def visit_For(self, node):
            targets_of(node.target)
            self.generic_visit(node)

        def visit_With(self, node):
            for item in node.items:
                if item.optional_vars is not None:
                    targets_of(item.optional_vars)
            self.generic_visit(node)

        def visit_NamedExpr(self, node):
            targets_of(node.target)
            self.generic_visit(node.value)

    v = V()
    for s in stmts:
        v.visit(s)
    return names


def _contains_escape(stmts):
    """True if the statement list cannot be lifted into a nested function:
    return/global/nonlocal/del/yield anywhere (outside nested defs), or
    break/continue not enclosed in a loop WITHIN the list (they'd target
    an outer loop and become SyntaxErrors after lifting)."""
    found = []

    class V(ast.NodeVisitor):
        def __init__(self, in_loop):
            self.in_loop = in_loop

        def visit_FunctionDef(self, node):
            pass

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Lambda(self, node):
            pass

        def visit_Return(self, node):
            found.append(node)

        def visit_Global(self, node):
            found.append(node)

        def visit_Nonlocal(self, node):
            found.append(node)

        def visit_Delete(self, node):
            found.append(node)

        def visit_Yield(self, node):
            found.append(node)

        def visit_YieldFrom(self, node):
            found.append(node)

        def visit_Break(self, node):
            if not self.in_loop:
                found.append(node)

        def visit_Continue(self, node):
            if not self.in_loop:
                found.append(node)

        def visit_For(self, node):
            inner = V(True)
            for s in node.body + node.orelse:
                inner.visit(s)

        def visit_While(self, node):
            inner = V(True)
            for s in node.body + node.orelse:
                inner.visit(s)

    v = V(False)
    for s in stmts:
        v.visit(s)
    return bool(found)


def _definite_names(stmts):
    """Names UNCONDITIONALLY bound by executing the statement list:
    assignment targets (never walrus inside values — `c and (y := f())`
    is conditional), def/class/import names, with-as names, and the
    definite names of with-bodies (which execute unconditionally).
    Control-flow statements contribute nothing."""
    out = set()

    def targets_of(t):
        if isinstance(t, ast.Name):
            out.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                targets_of(e)
        elif isinstance(t, ast.Starred):
            targets_of(t.value)

    for s in stmts:
        if isinstance(s, ast.Assign):
            for t in s.targets:
                targets_of(t)
        elif isinstance(s, (ast.AugAssign, ast.AnnAssign)):
            targets_of(s.target)
        elif isinstance(s, (ast.FunctionDef, ast.ClassDef)):
            out.add(s.name)
        elif isinstance(s, (ast.Import, ast.ImportFrom)):
            for a in s.names:
                out.add((a.asname or a.name).split(".")[0])
        elif isinstance(s, ast.With):
            for item in s.items:
                if item.optional_vars is not None:
                    targets_of(item.optional_vars)
            out.update(_definite_names(s.body))
    return out


def _def_names(stmts):
    """Names bound by function/class definitions at this level."""
    names = []

    class V(ast.NodeVisitor):
        def visit_FunctionDef(self, node):
            names.append(node.name)

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_ClassDef(self, node):
            names.append(node.name)

        def visit_Lambda(self, node):
            pass

    v = V()
    for s in stmts:
        v.visit(s)
    return names


def _split_state(body_stmts, extra_stmts=()):
    """(state_names, ok): assignable loop/branch state, excluding our own
    generated helper defs; ok=False when USER def/class bindings exist
    (they cannot be carried through lax control flow)."""
    names = set(_assigned_names(list(body_stmts))
                + _assigned_names(list(extra_stmts)))
    defs = set(_def_names(list(body_stmts)) + _def_names(list(extra_stmts)))
    gen = {n for n in defs if n.startswith("__pt_") and n.endswith("__")}
    if defs - gen:
        return [], False
    return sorted(names - gen), True


def _load_names(stmts):
    """Every name READ anywhere in the statements — including the
    implicit read of an AugAssign target (`t += 1` loads t even though
    its Name node carries a Store ctx)."""
    out = set()
    for s in stmts:
        for n in ast.walk(s):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                out.add(n.id)
            elif isinstance(n, ast.AugAssign) \
                    and isinstance(n.target, ast.Name):
                out.add(n.target.id)
    return out


def _body_local_ok(stmts, name):
    """True when `name` is always definitely stored before any load
    within the statement list (statement granularity; only
    UNCONDITIONAL stores count — a store under an `if` may leave the
    previous iteration's value observable, which body-locals cannot
    model)."""
    stored = False
    for s in stmts:
        loads = any(
            (isinstance(n, ast.Name) and n.id == name
             and isinstance(n.ctx, ast.Load))
            or (isinstance(n, ast.AugAssign)
                and isinstance(n.target, ast.Name)
                and n.target.id == name)
            for n in ast.walk(s))
        if loads and not stored:
            return False
        if name in _definite_names([s]):
            stored = True
    return True


class _TestExprTransformer(ast.NodeTransformer):
    """Inside a condition expression: `a and b` -> __pt_and__(a, lambda: b)
    etc., so tensor conditions never hit Python's __bool__."""

    def visit_BoolOp(self, node):
        self.generic_visit(node)
        op = "__pt_and__" if isinstance(node.op, ast.And) else "__pt_or__"
        expr = node.values[0]
        for nxt in node.values[1:]:
            expr = ast.Call(
                func=ast.Name(id=op, ctx=ast.Load()),
                args=[expr, ast.Lambda(
                    args=ast.arguments(posonlyargs=[], args=[],
                                       vararg=None, kwarg=None,
                                       kwonlyargs=[], kw_defaults=[],
                                       defaults=[]),
                    body=nxt)],
                keywords=[])
        return expr

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return ast.Call(func=ast.Name(id="__pt_not__", ctx=ast.Load()),
                            args=[node.operand], keywords=[])
        return node

    def visit_Lambda(self, node):
        return node


def __pt_range_cont__(i, stop, step):
    """range-style continuation test, concrete or traced, sign-aware."""
    if not any(_is_traced(v) for v in (i, stop, step)):
        s = int(_unwrap(step))
        return (int(_unwrap(i)) < int(_unwrap(stop))) if s > 0 \
            else (int(_unwrap(i)) > int(_unwrap(stop)))
    iv, sv, st = (jnp.asarray(_unwrap(v)) for v in (i, stop, step))
    return Tensor(jnp.where(st > 0, iv < sv, iv > sv).reshape(()))


HELPERS["__pt_range_cont__"] = __pt_range_cont__


class _AbortLowering(Exception):
    pass


class _EscapeLowerer:
    """Pre-pass lowering break/continue/early-return to carried flags
    (VERDICT r3 #5; reference analog: the SOT bytecode tracer's
    graph-break/resume machinery, jit/sot/opcode_translator/executor/ —
    here the structured cases lower to flag-guarded code that BOTH runs
    as plain Python and converts to lax control flow):

      * `break`    -> `__pt_brkN__ = True`; loop test gains `not brk`
      * `continue` -> `__pt_cntN__ = True`; reset at body start
      * `return X` -> `__pt_rv__ = X; __pt_ret__ = True`; loop tests
        gain `not ret`; ONE canonical `return __pt_rv__` ends the body
      * statements after a flag-setting construct are wrapped in
        `if not <flags>:` guards
      * `for x in range(...)` containing an escape desugars to a while
        (increment placed BEFORE the body so `continue` keeps advancing)

    Constructs it cannot prove out (escapes inside with/try, loop-else)
    abort the pre-pass: the function keeps its original body and the
    existing loud graph-break behavior."""

    RET = "__pt_ret__"
    RV = "__pt_rv__"

    def __init__(self):
        self.n = 0
        self.ret_used = False

    def fresh(self, kind):
        self.n += 1
        return f"__pt_{kind}{self.n}__"

    # -- small AST builders ----------------------------------------------
    @staticmethod
    def _assign(name, value):
        return ast.Assign(targets=[ast.Name(id=name, ctx=ast.Store())],
                          value=value)

    @staticmethod
    def _true():
        return ast.Constant(value=True)

    @staticmethod
    def _false():
        return ast.Constant(value=False)

    @staticmethod
    def _not_flags(flags):
        """`not (f1 or f2 or ...)`"""
        test = ast.Name(id=flags[0], ctx=ast.Load()) if len(flags) == 1 \
            else ast.BoolOp(op=ast.Or(),
                            values=[ast.Name(id=f, ctx=ast.Load())
                                    for f in flags])
        return ast.UnaryOp(op=ast.Not(), operand=test)

    def _needs_lowering(self, stmts):
        class V(ast.NodeVisitor):
            found = False

            def visit_FunctionDef(self, node):
                pass

            visit_AsyncFunctionDef = visit_FunctionDef

            def visit_Lambda(self, node):
                pass

            def visit_Break(self, node):
                V.found = True

            def visit_Continue(self, node):
                V.found = True

            def visit_Return(self, node):
                V.found = True

        v = V()
        for s in stmts:
            # only escapes INSIDE compound statements need lowering; a
            # trailing straight-line return is fine as-is
            if isinstance(s, (ast.If, ast.While, ast.For)):
                v.visit(s)
        return V.found

    def _check_opaque(self, s):
        """Escapes inside constructs we don't lower (with/try/match)
        abort the pre-pass entirely."""
        class V(ast.NodeVisitor):
            def visit_FunctionDef(self, node):
                pass

            visit_AsyncFunctionDef = visit_FunctionDef

            def visit_Lambda(self, node):
                pass

            def visit_Break(self, node):
                raise _AbortLowering

            def visit_Continue(self, node):
                raise _AbortLowering

            def visit_Return(self, node):
                raise _AbortLowering

        V().visit(s)

    def lower_function(self, body):
        """Entry point: returns the rewritten function body."""
        if not self._needs_lowering(body):
            return body
        new, used = self.lower_block(body, brk=None, cont=None)
        pre = []
        if self.ret_used:
            pre = [self._assign(self.RET, self._false()),
                   self._assign(self.RV, ast.Constant(value=None))]
            new = pre + new + [ast.Return(
                value=ast.Name(id=self.RV, ctx=ast.Load()))]
        return new

    def lower_block(self, stmts, brk, cont):
        """Returns (stmts', used_flags): used_flags are the flag names
        this block may set (drives guard insertion by callers)."""
        out = []
        used = set()
        for idx, s in enumerate(stmts):
            rest = stmts[idx + 1:]
            if isinstance(s, ast.Return):
                self.ret_used = True
                out.append(self._assign(
                    self.RV, s.value or ast.Constant(value=None)))
                out.append(self._assign(self.RET, self._true()))
                used.add(self.RET)
                return out, used                  # rest is unreachable
            if isinstance(s, ast.Break):
                if brk is None:
                    raise _AbortLowering
                out.append(self._assign(brk, self._true()))
                used.add(brk)
                return out, used
            if isinstance(s, ast.Continue):
                if cont is None:
                    raise _AbortLowering
                out.append(self._assign(cont, self._true()))
                used.add(cont)
                return out, used
            if isinstance(s, ast.If):
                body2, u1 = self.lower_block(s.body, brk, cont)
                orelse2, u2 = self.lower_block(s.orelse, brk, cont)
                u = u1 | u2
                out.append(ast.If(test=s.test, body=body2 or [ast.Pass()],
                                  orelse=orelse2))
                used |= u
                if u and rest:
                    rb, ru = self.lower_block(rest, brk, cont)
                    out.append(ast.If(test=self._not_flags(sorted(u)),
                                      body=rb or [ast.Pass()], orelse=[]))
                    used |= ru
                    return out, used
                continue
            if isinstance(s, ast.While):
                if s.orelse:
                    raise _AbortLowering
                out_s, u = self._lower_loop(s.test, s.body, init=None)
                out.extend(out_s)
                used |= u
                if (self.RET in u) and rest:
                    rb, ru = self.lower_block(rest, brk, cont)
                    out.append(ast.If(
                        test=self._not_flags([self.RET]),
                        body=rb or [ast.Pass()], orelse=[]))
                    used |= ru
                    return out, used
                continue
            if isinstance(s, ast.For):
                has_escape = any(
                    isinstance(n, (ast.Break, ast.Continue, ast.Return))
                    for n in ast.walk(s))
                if not has_escape:
                    out.append(s)
                    continue
                out_s, u = self._lower_for_range(s)
                out.extend(out_s)
                used |= u
                if (self.RET in u) and rest:
                    rb, ru = self.lower_block(rest, brk, cont)
                    out.append(ast.If(
                        test=self._not_flags([self.RET]),
                        body=rb or [ast.Pass()], orelse=[]))
                    used |= ru
                    return out, used
                continue
            self._check_opaque(s)
            out.append(s)
        return out, used

    def _lower_loop(self, test, body, init):
        """Shared while-style lowering: fresh brk/cont flags, flag-aware
        test, cont reset at body start. Returns (stmts, outward_flags)
        — outward flags exclude the loop-local brk/cont."""
        brk2 = self.fresh("brk")
        cont2 = self.fresh("cnt")
        body2, bu = self.lower_block(body, brk2, cont2)
        pre = list(init or [])
        pre.append(self._assign(brk2, self._false()))
        pre.append(self._assign(cont2, self._false()))
        if cont2 in bu:
            body2 = [self._assign(cont2, self._false())] + body2
        guards = [f for f in (brk2, self.RET) if f in bu]
        new_test = test if not guards else ast.BoolOp(
            op=ast.And(), values=[self._not_flags([f]) for f in guards]
            + [test])
        out = pre + [ast.While(test=new_test, body=body2, orelse=[])]
        return out, bu - {brk2, cont2}

    def _lower_for_range(self, node):
        """`for i in range(...)` with an escape -> explicit while over a
        fresh induction variable; the increment runs BEFORE the body so
        `continue` guards cannot skip it. The target is pre-bound to the
        start value (zero-trip loops bind it — the one divergence from
        plain Python, which would leave it unbound)."""
        it = node.iter
        if node.orelse:
            raise _AbortLowering    # for/else + escape: keep Python
        if not (isinstance(it, ast.Call) and isinstance(it.func, ast.Name)
                and it.func.id == "range" and not it.keywords
                and 1 <= len(it.args) <= 3
                and not any(isinstance(a, ast.Starred) for a in it.args)
                and isinstance(node.target, ast.Name)):
            raise _AbortLowering
        ivar = node.target.id
        args = list(it.args)
        if len(args) == 1:
            start, stop, step = ast.Constant(value=0), args[0], \
                ast.Constant(value=1)
        elif len(args) == 2:
            start, stop, step = args[0], args[1], ast.Constant(value=1)
        else:
            start, stop, step = args
        iv = self.fresh("fi")
        sv = self.fresh("fs")
        pv = self.fresh("fp")
        init = [self._assign(iv, start), self._assign(sv, stop),
                self._assign(pv, step),
                self._assign(ivar, ast.Name(id=iv, ctx=ast.Load()))]
        test = ast.Call(
            func=ast.Name(id="__pt_range_cont__", ctx=ast.Load()),
            args=[ast.Name(id=iv, ctx=ast.Load()),
                  ast.Name(id=sv, ctx=ast.Load()),
                  ast.Name(id=pv, ctx=ast.Load())], keywords=[])
        body = [
            self._assign(ivar, ast.Name(id=iv, ctx=ast.Load())),
            self._assign(iv, ast.BinOp(
                left=ast.Name(id=iv, ctx=ast.Load()), op=ast.Add(),
                right=ast.Name(id=pv, ctx=ast.Load()))),
        ] + node.body
        return self._lower_loop(test, body, init)


class ControlFlowTransformer(ast.NodeTransformer):
    """Rewrites If/While/For-over-range into helper calls. Maintains the
    set of names bound earlier in the function so branch state is always
    referencable (the dy2static 'create_undefined_var' machinery is
    replaced by simply not transforming such code)."""

    def __init__(self, bound_names):
        self.bound = set(bound_names)
        self.counter = 0
        self.changed = False

    def _fresh(self, kind):
        self.counter += 1
        return f"__pt_{kind}_{self.counter}__"

    def _visit_block(self, stmts):
        out = []
        for k, s in enumerate(stmts):
            # lookahead: names read by LATER statements (plus the
            # enclosing blocks' pending reads) cannot be loop/branch
            # locals — they must be carried state
            prev_after = getattr(self, "_after_reads", frozenset())
            self._after_reads = frozenset(prev_after
                                          | _load_names(stmts[k + 1:]))
            try:
                r = self.visit(s)
            finally:
                self._after_reads = prev_after
            if isinstance(r, list):
                out.extend(r)
            elif r is not None:
                out.append(r)
            # only unconditionally-executed statements make a name
            # DEFINITELY bound; names from control-flow statements may be
            # unbound at runtime and would turn the generated state tuple
            # into an UnboundLocalError the original code didn't have
            self.bound.update(_definite_names([s]))
        return out

    def _drop_block_locals(self, state, *blocks):
        """Partition state names: a name unbound BEFORE the construct
        that is never read after it and always stored-before-load inside
        every block is a block LOCAL — it need not (and cannot) be
        carried through lax control flow."""
        after = getattr(self, "_after_reads", frozenset())
        carried = []
        for n in state:
            if n in self.bound:
                carried.append(n)
                continue
            if n not in after and all(_body_local_ok(b, n)
                                      for b in blocks if b):
                continue                       # block-local: drop
            carried.append(n)
        return carried

    def visit_FunctionDef(self, node):
        # nested defs keep their own scope; record the name, don't descend
        self.bound.add(node.name)
        return node

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        return node

    def _state_tuple(self, names, ctx):
        return ast.Tuple(
            elts=[ast.Name(id=n, ctx=ctx()) for n in names],
            ctx=ctx())

    def _branch_fn(self, fname, state, body):
        """def fname(s0, s1, ...): <body>; return (s0, s1, ...)"""
        return ast.FunctionDef(
            name=fname,
            args=ast.arguments(
                posonlyargs=[],
                args=[ast.arg(arg=n) for n in state],
                vararg=None, kwarg=None,
                kwonlyargs=[], kw_defaults=[], defaults=[]),
            body=body + [ast.Return(value=self._state_tuple(
                state, ast.Load))],
            decorator_list=[])

    def visit_If(self, node):
        pre = set(self.bound)
        body = self._visit_block(node.body)
        self.bound = set(pre)
        orelse = self._visit_block(node.orelse)
        self.bound = pre
        node = ast.If(test=node.test, body=body, orelse=orelse)
        if _contains_escape(node.body) or _contains_escape(node.orelse):
            return node
        state, ok = _split_state(node.body, node.orelse)
        if ok:
            state = self._drop_block_locals(state, node.body,
                                            node.orelse)
        if not ok or any(n not in self.bound for n in state):
            return node          # a maybe-unbound name: leave as Python
        self.changed = True
        test = _TestExprTransformer().visit(node.test)
        tname, fname = self._fresh("true"), self._fresh("false")
        tdef = self._branch_fn(tname, state, node.body or [ast.Pass()])
        fdef = self._branch_fn(fname, state,
                               node.orelse or [ast.Pass()])
        call = ast.Assign(
            targets=[self._state_tuple(state, ast.Store)],
            value=ast.Call(
                func=ast.Name(id="__pt_if__", ctx=ast.Load()),
                args=[test,
                      ast.Name(id=tname, ctx=ast.Load()),
                      ast.Name(id=fname, ctx=ast.Load()),
                      self._state_tuple(state, ast.Load)],
                keywords=[]))
        if not state:
            call = ast.Expr(value=call.value)
        return [tdef, fdef, call]

    def visit_While(self, node):
        pre = set(self.bound)
        # loop bodies re-enter: a name read by an EARLIER body statement
        # observes the previous iteration's binding, so every body read
        # counts as a "later" read for block-local analysis
        prev_after = getattr(self, "_after_reads", frozenset())
        self._after_reads = frozenset(prev_after
                                      | _load_names(node.body))
        body = self._visit_block(node.body)
        self._after_reads = prev_after
        self.bound = pre
        node = ast.While(test=node.test, body=body, orelse=node.orelse)
        if node.orelse or _contains_escape(node.body):
            return node
        state, ok = _split_state(node.body)
        if ok:
            state = self._drop_block_locals(state, node.body)
        if not ok or not state or any(n not in self.bound for n in state):
            return node
        self.changed = True
        test = _TestExprTransformer().visit(node.test)
        tname, bname = self._fresh("wtest"), self._fresh("wbody")
        tdef = ast.FunctionDef(
            name=tname,
            args=ast.arguments(
                posonlyargs=[], args=[ast.arg(arg=n) for n in state],
                vararg=None, kwarg=None,
                kwonlyargs=[], kw_defaults=[], defaults=[]),
            body=[ast.Return(value=test)],
            decorator_list=[])
        bdef = self._branch_fn(bname, state, node.body)
        call = ast.Assign(
            targets=[self._state_tuple(state, ast.Store)],
            value=ast.Call(
                func=ast.Name(id="__pt_while__", ctx=ast.Load()),
                args=[ast.Name(id=tname, ctx=ast.Load()),
                      ast.Name(id=bname, ctx=ast.Load()),
                      self._state_tuple(state, ast.Load)],
                keywords=[]))
        return [tdef, bdef, call]

    def visit_For(self, node):
        pre = set(self.bound)
        if isinstance(node.target, ast.Name):
            self.bound.add(node.target.id)   # bound inside the body
        prev_after = getattr(self, "_after_reads", frozenset())
        self._after_reads = frozenset(prev_after
                                      | _load_names(node.body))
        body = self._visit_block(node.body)
        self._after_reads = prev_after
        self.bound = pre
        node = ast.For(target=node.target, iter=node.iter, body=body,
                       orelse=node.orelse)
        if node.orelse or _contains_escape(node.body):
            return node
        if not (isinstance(node.iter, ast.Call)
                and isinstance(node.iter.func, ast.Name)
                and node.iter.func.id == "range"
                and not node.iter.keywords
                and 1 <= len(node.iter.args) <= 3
                and not any(isinstance(a, ast.Starred)
                            for a in node.iter.args)):
            return node
        if not isinstance(node.target, ast.Name):
            return node
        ivar = node.target.id
        state, ok = _split_state(node.body)
        state = [n for n in state if n != ivar]
        if ok:
            state = self._drop_block_locals(state, node.body)
        if not ok or any(n not in self.bound for n in state):
            return node
        self.changed = True
        bname = self._fresh("fbody")
        bdef = ast.FunctionDef(
            name=bname,
            args=ast.arguments(
                posonlyargs=[],
                args=[ast.arg(arg=ivar)] + [ast.arg(arg=n)
                                            for n in state],
                vararg=None, kwarg=None,
                kwonlyargs=[], kw_defaults=[], defaults=[]),
            body=node.body + [ast.Return(value=self._state_tuple(
                state, ast.Load))],
            decorator_list=[])
        call = ast.Assign(
            targets=[ast.Tuple(
                elts=[ast.Name(id=ivar, ctx=ast.Store())]
                + [ast.Name(id=n, ctx=ast.Store()) for n in state],
                ctx=ast.Store())],
            value=ast.Call(
                func=ast.Name(id="__pt_for_range__", ctx=ast.Load()),
                args=[ast.Tuple(elts=list(node.iter.args),
                                ctx=ast.Load()),
                      ast.Name(id=bname, ctx=ast.Load()),
                      self._state_tuple(state, ast.Load)],
                keywords=[
                    ast.keyword(arg="prior",
                                value=(ast.Name(id=ivar, ctx=ast.Load())
                                       if ivar in self.bound
                                       else ast.Constant(value=None))),
                    ast.keyword(arg="has_prior",
                                value=ast.Constant(
                                    value=ivar in self.bound)),
                    ast.keyword(arg="name",
                                value=ast.Constant(value=ivar)),
                ]))
        return [bdef, call]


# ---------------------------------------------------------------------------
# function conversion
# ---------------------------------------------------------------------------

_convert_cache: dict = {}      # code object -> converted code info or None


def _param_names(fn):
    code = fn.__code__
    n = code.co_argcount + code.co_kwonlyargcount
    names = list(code.co_varnames[:n])
    if code.co_flags & inspect.CO_VARARGS:
        names.append(code.co_varnames[n])
        n += 1
    if code.co_flags & inspect.CO_VARKEYWORDS:
        names.append(code.co_varnames[n])
    return names


def convert_function(fn) -> Optional[types.FunctionType]:
    """Return a converted version of `fn` (a plain function), or None when
    nothing needed conversion / the source is unavailable. The converted
    function has identical behavior for concrete conditions and lowers
    tensor-dependent control flow when traced."""
    if isinstance(fn, types.MethodType):
        inner = convert_function(fn.__func__)
        return None if inner is None else types.MethodType(
            inner, fn.__self__)
    if not isinstance(fn, types.FunctionType):
        return None
    code = fn.__code__
    if code in _convert_cache:
        cached = _convert_cache[code]
        return None if cached is None else _bind(cached, fn)
    result = None
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
        fdef = tree.body[0]
        if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                or isinstance(fdef, ast.AsyncFunctionDef):
            raise _Unsupported
        fdef.decorator_list = []
        bound = set(_param_names(fn))
        try:
            # escape lowering first: break/continue/early-return become
            # flag-guarded structured code the main transformer can lower
            fdef.body = _EscapeLowerer().lower_function(fdef.body)
        except _AbortLowering:
            pass        # keep the original body: loud graph-break path
        tr = ControlFlowTransformer(bound)
        fdef.body = tr._visit_block(fdef.body)
        if tr.changed:
            freevars = code.co_freevars
            factory = ast.FunctionDef(
                name="__pt_factory__",
                args=ast.arguments(
                    posonlyargs=[],
                    args=[ast.arg(arg=n) for n in freevars],
                    vararg=None, kwarg=None,
                kwonlyargs=[], kw_defaults=[], defaults=[]),
                body=[fdef, ast.Return(
                    value=ast.Name(id=fdef.name, ctx=ast.Load()))],
                decorator_list=[])
            mod = ast.Module(body=[factory], type_ignores=[])
            ast.fix_missing_locations(mod)
            compiled = compile(mod, f"<dy2static {code.co_filename}:"
                                    f"{code.co_firstlineno}>", "exec")
            result = {"compiled": compiled, "freevars": freevars,
                      "name": fdef.name}
    except (_Unsupported, OSError, TypeError, SyntaxError, ValueError):
        result = None
    _convert_cache[code] = result
    if result is None:
        return None
    try:
        return _bind(result, fn)
    except ValueError:        # e.g. an empty closure cell
        return None


def _bind(info, fn):
    # execute against the function's LIVE module globals (a snapshot dict
    # would freeze later rebinding of module-level names); the helper
    # names are unique dunders, so injecting them is collision-safe
    g = fn.__globals__
    for k, v in HELPERS.items():
        g.setdefault(k, v)
    ns = {}
    exec(info["compiled"], g, ns)
    cells = [c.cell_contents for c in (fn.__closure__ or ())]
    new_fn = ns["__pt_factory__"](*cells)
    functools.wraps(fn)(new_fn)
    new_fn.__pt_converted__ = True
    return new_fn


def convert_layer_tree(layer) -> bool:
    """Convert the forward of `layer` and every sublayer (instance-level
    rebind; the underlying function is converted once per code object).
    The original forward is kept on the instance so restore_layer_tree
    can undo the rebind if the converted code misbehaves.
    Returns True if anything was converted."""
    converted_any = False
    seen = set()
    stack = [layer]
    while stack:
        l = stack.pop()
        if id(l) in seen:
            continue
        seen.add(id(l))
        fwd = getattr(l, "forward", None)
        if isinstance(fwd, types.MethodType) \
                and not getattr(fwd.__func__, "__pt_converted__", False):
            new = convert_function(fwd.__func__)
            if new is not None:
                l.__dict__["__pt_orig_forward__"] = fwd
                l.forward = types.MethodType(new, l)
                converted_any = True
        for child in getattr(l, "_sub_layers", {}).values():
            stack.append(child)
    return converted_any


def restore_layer_tree(layer) -> None:
    """Undo convert_layer_tree's instance rebinds (used when a converted
    forward raises something the trace-break fallback can't absorb)."""
    seen = set()
    stack = [layer]
    while stack:
        l = stack.pop()
        if id(l) in seen:
            continue
        seen.add(id(l))
        orig = l.__dict__.pop("__pt_orig_forward__", None)
        if orig is not None:
            l.forward = orig
        for child in getattr(l, "_sub_layers", {}).values():
            stack.append(child)
