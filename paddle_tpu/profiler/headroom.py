"""Capacity headroom: fit the recorded load-vs-goodput curve from the
timeline and advise scale decisions.

ROADMAP item 1's AutoScaler needs one input: "given what the fleet
just did, should it grow, hold, or shrink — and if shrink, which
replicas drain first".  `ScaleAdvisor` is deliberately that exact
interface, computed from recorded telemetry instead of instantaneous
gauges:

  * **Curve fit.**  Adjacent timeline windows yield (load_score,
    goodput-rate) points; the saturation knee is the LOWEST load that
    already achieves ~peak goodput — pushing load past it buys
    queueing, not throughput.  Headroom is the remaining fraction of
    load below that knee (falling back to the configured `high_load`
    bound while the curve is still sparse).
  * **Monotone decision rules.**  `recommend()` escalates on current
    load, brownout activity, or active burn alerts; it de-escalates
    only when EVERY window in the decision horizon sat at/below
    `low_load` with no recent alert activity — so more load can never
    produce a lazier recommendation (the monotonicity test), and a
    fleet that just survived a storm holds instead of flapping into a
    scale-down while the storm is still inside the horizon.
  * **Drain candidates.**  On scale_down, the least-loaded replicas
    are proposed greedily while the survivors' projected mean load
    stays at/below `target_load`.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from . import metrics as _metrics

__all__ = ["ScaleAdvice", "ScaleAdvisor", "ACTIONS"]

ACTIONS = ("scale_down", "hold", "scale_up")

_m_advisories = _metrics.counter("slo/advisories")
_m_headroom = _metrics.gauge("slo/headroom")


@dataclass
class ScaleAdvice:
    """One advisory — the AutoScaler input record."""

    action: str                         # scale_up | hold | scale_down
    reason: str
    current_load: Optional[float]
    headroom: Optional[float]           # fraction of knee load left
    saturation_load: Optional[float]    # fitted knee (None: sparse)
    peak_goodput: Optional[float]       # req/s at the knee
    drain_candidates: List[str] = field(default_factory=list)

    def to_dict(self) -> dict:
        def r(v):
            return round(v, 4) if isinstance(v, float) else v
        return {"action": self.action, "reason": self.reason,
                "current_load": r(self.current_load),
                "headroom": r(self.headroom),
                "saturation_load": r(self.saturation_load),
                "peak_goodput": r(self.peak_goodput),
                "drain_candidates": list(self.drain_candidates)}


class ScaleAdvisor:
    """Headroom estimation + scale advisories over a Timeline (and
    optionally an SLOTracker for alert awareness).

    advisor = ScaleAdvisor(timeline, tracker=slo_tracker, window_s=60)
    advisor.recommend(replica_loads={"r0": 0.1, "r1": 0.05})
    """

    def __init__(self, timeline, tracker=None,
                 load_metric: str = "gateway/load_score",
                 goodput_metric: str = "gateway/outcome/completed",
                 brownout_metric: str = "gateway/brownout_level",
                 window_s: float = 60.0,
                 high_load: float = 1.0, low_load: float = 0.25,
                 target_load: float = 0.7,
                 min_windows: int = 3, sat_fraction: float = 0.9):
        self.timeline = timeline
        self.tracker = tracker
        self.load_metric = load_metric
        self.goodput_metric = goodput_metric
        self.brownout_metric = brownout_metric
        self.window_s = float(window_s)
        self.high_load = float(high_load)
        self.low_load = float(low_load)
        self.target_load = float(target_load)
        self.min_windows = max(1, int(min_windows))
        self.sat_fraction = float(sat_fraction)

    # -- the recorded curve -----------------------------------------------
    def curve(self) -> List[Tuple[float, float]]:
        """(load, goodput req/s) per adjacent-window pair, over the
        whole retained timeline."""
        wins = self.timeline.windows()
        pts = []
        for a, b in zip(wins, wins[1:]):
            dt = b["t"] - a["t"]
            load = b["gauges"].get(self.load_metric)
            if dt <= 0 or load is None:
                continue
            dg = (b["counters"].get(self.goodput_metric, 0)
                  - a["counters"].get(self.goodput_metric, 0))
            pts.append((float(load), dg / dt))
        return pts

    def saturation(self) -> Tuple[Optional[float], Optional[float]]:
        """(knee load, peak goodput) fitted from the curve, or
        (None, None) while the curve is too sparse to trust."""
        pts = self.curve()
        if len(pts) < self.min_windows:
            return None, None
        peak = max(g for _, g in pts)
        if peak <= 0:
            return None, None
        sat = min(l for l, g in pts if g >= self.sat_fraction * peak)
        return (sat if sat > 0 else None), peak

    def _alert_activity(self, now: Optional[float]) -> bool:
        """Any alert active, or raised/cleared inside the decision
        horizon — recent judgment vetoes a scale_down."""
        if self.tracker is None:
            return False
        if self.tracker.active_alerts():
            return True
        if now is None:
            return False
        for a in self.tracker.alerts:
            edge = a.cleared_t if a.cleared_t is not None else a.raised_t
            if edge >= now - self.window_s:
                return True
        return False

    # -- the advisory -----------------------------------------------------
    def recommend(self,
                  replica_loads: Optional[Dict[str, float]] = None,
                  now: Optional[float] = None) -> ScaleAdvice:
        wins = self.timeline.windows(self.window_s, now)
        loads = [w["gauges"][self.load_metric] for w in wins
                 if self.load_metric in w["gauges"]]
        # the LIVE registry gauges join the horizon: a storm that hits
        # between samples must not read as a calm set of windows
        gauges = self.timeline.registry.snapshot().get("gauges", {})
        live = gauges.get(self.load_metric)
        if live is not None:
            loads = loads + [float(live)]
        cur = loads[-1] if loads else None
        sat, peak = self.saturation()
        headroom = None
        if cur is not None:
            knee = sat if sat is not None else self.high_load
            if knee > 0:
                headroom = max(0.0, 1.0 - cur / knee)
        if now is None and wins:
            now = wins[-1]["t"]
        brown = max((w["gauges"].get(self.brownout_metric, 0)
                     for w in wins), default=0)
        brown = max(brown, gauges.get(self.brownout_metric, 0) or 0)
        alerts = bool(self.tracker.active_alerts()) \
            if self.tracker is not None else False
        if cur is None:
            advice = ScaleAdvice("hold", "no load signal recorded yet",
                                 None, None, sat, peak)
        elif alerts or brown >= 1 or cur >= self.high_load:
            why = ("active burn alert" if alerts
                   else "brownout ladder engaged" if brown >= 1
                   else f"load {cur:.2f} >= high watermark "
                        f"{self.high_load:.2f}")
            advice = ScaleAdvice("scale_up", why, cur, headroom,
                                 sat, peak)
        elif (len(loads) >= self.min_windows
                and all(l <= self.low_load for l in loads)
                and not self._alert_activity(now)):
            advice = ScaleAdvice(
                "scale_down",
                f"load held <= {self.low_load:.2f} across the horizon",
                cur, headroom, sat, peak,
                drain_candidates=self._drain_candidates(replica_loads))
        else:
            advice = ScaleAdvice("hold", "inside the comfort band",
                                 cur, headroom, sat, peak)
        _m_advisories.inc()
        if headroom is not None:
            _m_headroom.set(headroom)
        return advice

    def _drain_candidates(
            self, replica_loads: Optional[Dict[str, float]]) -> List[str]:
        """Least-loaded replicas removable while the survivors'
        projected mean load stays at/below target_load."""
        if not replica_loads or len(replica_loads) <= 1:
            return []
        items = sorted(replica_loads.items(), key=lambda kv: kv[1])
        total = sum(replica_loads.values())
        n = len(items)
        out = []
        for name, load in items:
            if n <= 1:
                break
            if (total - load) / (n - 1) > self.target_load:
                break
            out.append(name)
            total -= load
            n -= 1
        return out
