"""Fleet metrics aggregation plane: ship per-process registry snapshots
to an aggregator and merge them into fleet-wide percentiles.

The router's health scrape "shrunk to process-local method calls"
(router.py) — good enough for one process, useless as a placement
signal for a FleetGateway that must see every replica on every host.
This module closes the loop:

  * `MetricsCollector` — runs next to each replica/trainer; serializes
    the (child-)registry snapshot as JSON-bytes and sends it over any
    transport with the CRC/ACK `TensorTransport` surface
    (``send(arr, dst, channel)`` / ``recv(src, channel)``), identity-
    stamped with (host_id, replica).
  * `FleetAggregator` — ingests snapshots (in-process or off the
    transport), keys them by (host_id, replica), merges histogram
    digests across replicas (t-digest merge, so fleet p95 is honest,
    not an average of averages), and exposes the fleet-snapshot API.
  * `estimate_clock_offset` / `serve_clock` — NTP-style transport-ping
    offset estimation so `tools/trace_report.py` can shift per-host
    chrome traces onto one timeline before merging.
  * `straggler_report` — per-rank `train/step_ms` digest comparison
    flagging ranks whose p95 lags the fleet median.
"""
from __future__ import annotations

import json
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from . import metrics as _metrics
from .digest import QuantileDigest

__all__ = [
    "MetricsCollector", "FleetAggregator", "estimate_clock_offset",
    "serve_clock", "METRICS_CHANNEL", "CLOCK_CHANNEL",
]

METRICS_CHANNEL = "metrics"
CLOCK_CHANNEL = "clock"

_m_published = _metrics.counter("fleet/snapshots_published")
_m_ingested = _metrics.counter("fleet/snapshots_ingested")
_m_replicas = _metrics.gauge("fleet/replicas")
_m_stale = _metrics.counter("fleet/stale_evictions")


def _encode(doc: dict) -> np.ndarray:
    return np.frombuffer(json.dumps(doc).encode("utf-8"), dtype=np.uint8)


def _decode(arr) -> dict:
    return json.loads(bytes(np.asarray(arr, dtype=np.uint8)).decode("utf-8"))


class MetricsCollector:
    """Per-process publisher of identity-stamped registry snapshots."""

    def __init__(self, transport, dst: int, host_id: Optional[str] = None,
                 replica: Optional[str] = None, channel: str = METRICS_CHANNEL,
                 registry=None):
        self.transport = transport
        self.dst = dst
        self.host_id = host_id
        self.replica = replica
        self.channel = channel
        self.registry = registry if registry is not None \
            else _metrics.registry()

    def snapshot(self) -> dict:
        snap = self.registry.snapshot()
        snap["host_id"] = self.host_id
        snap["replica"] = self.replica \
            or snap.get("namespace") or f"pid{snap.get('pid')}"
        return snap

    def publish(self) -> dict:
        """Snapshot + send over the transport; returns the snapshot."""
        snap = self.snapshot()
        self.transport.send(_encode(snap), self.dst, channel=self.channel)
        _m_published.inc()
        return snap


def _merge_hist_snaps(snaps: List[dict]) -> dict:
    out = {"count": 0, "sum": 0.0, "min": None, "max": None}
    dg: Optional[QuantileDigest] = None
    for h in snaps:
        out["count"] += h.get("count", 0)
        out["sum"] += h.get("sum", 0.0) or 0.0
        for key, better in (("min", min), ("max", max)):
            v = h.get(key)
            if v is not None:
                out[key] = v if out[key] is None else better(out[key], v)
        d = h.get("digest")
        if d:
            part = QuantileDigest.from_dict(d)
            dg = part if dg is None else dg.merge(part)
    out["avg"] = out["sum"] / out["count"] if out["count"] else None
    if dg is not None:
        out["p50"] = dg.quantile(0.5)
        out["p95"] = dg.quantile(0.95)
        out["p99"] = dg.quantile(0.99)
        out["digest"] = dg.to_dict()
    return out


class FleetAggregator:
    """Keyed store of per-replica snapshots + digest-merging rollup.

    Snapshots are last-write-wins per (host_id, replica) and carry the
    ingest timestamp, so a retired or renamed replica that stops
    publishing can be EVICTED (`evict_stale`) instead of polluting
    fleet percentiles forever with its final digest.  Pass
    ``stale_after_s`` to evict automatically on every fleet read."""

    def __init__(self, clock=time.time,
                 stale_after_s: Optional[float] = None):
        self._snaps: Dict[Tuple[str, str], dict] = {}
        self._clock = clock
        self.stale_after_s = stale_after_s

    # -- ingestion --------------------------------------------------------
    def ingest(self, snap: dict) -> Tuple[str, str]:
        key = (str(snap.get("host_id")),
               str(snap.get("replica") or snap.get("namespace")
                   or f"pid{snap.get('pid')}"))
        snap = dict(snap)
        snap["ingest_ts"] = self._clock()
        self._snaps[key] = snap
        _m_ingested.inc()
        _m_replicas.set(len(self._snaps))
        return key

    def evict_stale(self, max_age_s: Optional[float] = None,
                    now: Optional[float] = None) -> List[Tuple[str, str]]:
        """Drop every snapshot not re-ingested within ``max_age_s``
        (default: the constructor's ``stale_after_s``); returns the
        evicted keys and counts ``fleet/stale_evictions``."""
        max_age = max_age_s if max_age_s is not None else self.stale_after_s
        if max_age is None:
            return []
        if now is None:
            now = self._clock()
        stale = sorted(k for k, s in self._snaps.items()
                       if now - s.get("ingest_ts", now) > max_age)
        for k in stale:
            del self._snaps[k]
        if stale:
            _m_stale.inc(len(stale))
            _m_replicas.set(len(self._snaps))
        return stale

    def poll(self, transport, src: int,
             channel: str = METRICS_CHANNEL) -> Tuple[str, str]:
        """Receive one published snapshot from `src` and ingest it."""
        return self.ingest(_decode(transport.recv(src, channel=channel)))

    def keys(self) -> List[Tuple[str, str]]:
        return sorted(self._snaps)

    # -- fleet snapshot API (the future FleetGateway input) ---------------
    def replica_snapshot(self, host_id, replica) -> Optional[dict]:
        return self._snaps.get((str(host_id), str(replica)))

    def percentile(self, metric: str, q: float, host_id=None,
                   replica=None) -> Optional[float]:
        """Digest percentile for one replica, or fleet-merged when no
        identity is given."""
        if self.stale_after_s is not None:
            self.evict_stale()
        if host_id is not None or replica is not None:
            snap = self.replica_snapshot(host_id, replica)
            if snap is None:
                return None
            h = snap.get("histograms", {}).get(metric)
            if not h or not h.get("digest"):
                return None
            return QuantileDigest.from_dict(h["digest"]).quantile(q)
        merged = self._merged_histogram(metric)
        if not merged or not merged.get("digest"):
            return None
        return QuantileDigest.from_dict(merged["digest"]).quantile(q)

    def _merged_histogram(self, metric: str) -> Optional[dict]:
        parts = [s["histograms"][metric] for s in self._snaps.values()
                 if metric in s.get("histograms", {})]
        return _merge_hist_snaps(parts) if parts else None

    def fleet_snapshot(self) -> dict:
        """Everything a gateway needs in one dict: per-replica series
        plus the digest-merged fleet rollup."""
        if self.stale_after_s is not None:
            self.evict_stale()
        replicas = {}
        counters: Dict[str, float] = {}
        gauges: Dict[str, List[float]] = {}
        hist_names = set()
        for (host, rep), snap in sorted(self._snaps.items()):
            replicas[f"{host}/{rep}"] = {
                "host_id": host, "replica": rep,
                "ts": snap.get("ts"), "pid": snap.get("pid"),
                "counters": snap.get("counters", {}),
                "gauges": snap.get("gauges", {}),
                "histograms": snap.get("histograms", {}),
            }
            for name, v in snap.get("counters", {}).items():
                counters[name] = counters.get(name, 0) + v
            for name, v in snap.get("gauges", {}).items():
                gauges.setdefault(name, []).append(v)
            hist_names.update(snap.get("histograms", {}))
        fleet_hists = {name: self._merged_histogram(name)
                       for name in sorted(hist_names)}
        return {
            "ts": time.time(),
            "n_replicas": len(self._snaps),
            "replicas": replicas,
            "fleet": {
                "counters": counters,
                "gauges": {n: (sum(vs) / len(vs) if vs else None)
                           for n, vs in gauges.items()},
                "histograms": fleet_hists,
            },
        }

    # -- straggler detection ----------------------------------------------
    def straggler_report(self, metric: str = "train/step_ms",
                         factor: float = 1.5) -> dict:
        """Per-rank digest comparison: flag replicas whose `metric` p95
        exceeds `factor` x the fleet median p95."""
        per_rank = {}
        p95s = []
        for (host, rep), snap in sorted(self._snaps.items()):
            h = snap.get("histograms", {}).get(metric)
            if not h or not h.get("digest"):
                continue
            dg = QuantileDigest.from_dict(h["digest"])
            row = {"count": dg.count, "p50": dg.quantile(0.5),
                   "p95": dg.quantile(0.95), "max": dg.max}
            per_rank[f"{host}/{rep}"] = row
            p95s.append((row["p95"], f"{host}/{rep}"))
        if not p95s:
            return {"metric": metric, "per_rank": {}, "stragglers": [],
                    "median_p95": None}
        vals = sorted(v for v, _ in p95s)
        median = vals[len(vals) // 2]
        stragglers = [k for v, k in p95s
                      if median and v > factor * median]
        return {"metric": metric, "per_rank": per_rank,
                "stragglers": sorted(stragglers), "median_p95": median,
                "factor": factor}


# -- clock-offset estimation ---------------------------------------------

def _recv_wait(transport, src: int, channel: str, timeout_s: float = 5.0):
    """recv that tolerates empty loopback queues (LoopbackTransport
    raises instead of blocking); real transports block internally."""
    from ..distributed.resilience.errors import TransportClosedError

    deadline = time.perf_counter() + timeout_s
    while True:
        try:
            return transport.recv(src, channel=channel)
        except TransportClosedError:
            if time.perf_counter() > deadline:
                raise
            time.sleep(0.001)


def serve_clock(transport, peer: int, n: int = 4,
                channel: str = CLOCK_CHANNEL, skew_s: float = 0.0) -> None:
    """Answer `n` clock pings from `peer`: echo the originator's t0 with
    this process's receive/send timestamps. `skew_s` offsets the local
    clock reading (tests use it to simulate an unsynchronized host).
    Ping and reply ride separate sub-channels so a loopback transport
    (one queue per channel) can't hand a sender back its own frame."""
    for _ in range(n):
        frame = np.asarray(
            _recv_wait(transport, peer, channel + "/req"), dtype=np.float64)
        t_rx = time.perf_counter() + skew_s
        t_tx = time.perf_counter() + skew_s
        reply = np.array([frame[0], t_rx, t_tx], dtype=np.float64)
        transport.send(reply, peer, channel=channel + "/rsp")


def estimate_clock_offset(transport, peer: int, n: int = 4,
                          channel: str = CLOCK_CHANNEL) -> float:
    """NTP-style offset of `peer`'s clock relative to ours, in seconds
    (add the result to *our* timestamps to land on the peer's
    timeline). Uses the minimum-RTT sample — the one least polluted by
    queueing delay."""
    best = None
    for _ in range(max(1, n)):
        t0 = time.perf_counter()
        transport.send(np.array([t0], dtype=np.float64), peer,
                       channel=channel + "/req")
        frame = np.asarray(
            _recv_wait(transport, peer, channel + "/rsp"), dtype=np.float64)
        t3 = time.perf_counter()
        t_rx, t_tx = float(frame[1]), float(frame[2])
        rtt = (t3 - t0) - (t_tx - t_rx)
        offset = ((t_rx - t0) + (t_tx - t3)) / 2.0
        if best is None or rtt < best[0]:
            best = (rtt, offset)
    offset = best[1]
    _metrics.gauge("fleet/clock_offset_ms").set(offset * 1e3)
    return offset
