"""Always-on runtime metrics: a process-wide registry of counters,
gauges and histograms behind the `paddle.profiler` orchestrator.

Reference analog: the C++ layered tracers under platform/profiler/ keep
host-side statistic tables that survive independently of whether a trace
is being recorded; production serving stacks additionally export them as
Prometheus text. Here the registry is the single sink every instrumented
layer writes to — op dispatch (`dispatch/*`), the compile bridge
(`jit/*`), collectives (`comm/*`) and the serving engine (`serving/*`)
— cheap enough (one lock + int add per event) to stay on at all times.

Crash safety: `enable_periodic_flush(path)` starts a daemon thread that
atomically rewrites a JSON snapshot every interval (tmp file +
``os.replace``), so a process killed mid-run still leaves its last
complete snapshot behind — the failure mode that lost an entire bench
run when results were only emitted as one final line. Env flags
``PT_METRICS_FLUSH_PATH`` / ``PT_METRICS_FLUSH_INTERVAL`` arm the
flusher at import time.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from .digest import QuantileDigest

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "registry", "counter", "gauge", "histogram", "child",
    "inc", "set_gauge", "observe", "timed",
    "snapshot", "to_json", "to_prometheus_text", "snapshot_to_file",
    "enable_periodic_flush", "disable_periodic_flush", "reset",
]


# default latency buckets (ms): microseconds through minutes
DEFAULT_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0,
                   50.0, 100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0,
                   10000.0, 60000.0)


class Counter:
    """Monotonic counter. `inc` is thread-exact (lock-guarded add)."""

    __slots__ = ("name", "_value", "_lock")
    kind = "counter"

    def __init__(self, name: str):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, v=1):
        with self._lock:
            self._value += v

    @property
    def value(self):
        with self._lock:
            return self._value

    def _reset(self):
        with self._lock:
            self._value = 0

    def _snap(self):
        with self._lock:
            return self._value


class Gauge:
    """Last-write-wins scalar."""

    __slots__ = ("name", "_value", "_lock")
    kind = "gauge"

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, v):
        with self._lock:
            self._value = v

    def inc(self, v=1):
        with self._lock:
            self._value += v

    @property
    def value(self):
        with self._lock:
            return self._value

    def _reset(self):
        with self._lock:
            self._value = 0.0

    def _snap(self):
        with self._lock:
            return self._value


class Histogram:
    """Fixed-bucket histogram tracking count/sum/min/max, plus a
    mergeable t-digest for honest tail quantiles.

    Buckets are upper bounds (le); `observe` finds the first bound >= v
    with a linear scan (bucket lists are short and observation cost must
    stay O(ns), not O(log n) with allocation). The digest rides along so
    `quantile(0.99)` answers from the actual value stream instead of a
    bucket upper bound, and so per-replica histograms merge into fleet
    percentiles in `profiler.aggregate`.
    """

    __slots__ = ("name", "buckets", "_counts", "_count", "_sum",
                 "_min", "_max", "_digest", "_win_digest", "_lock")
    kind = "histogram"

    def __init__(self, name: str, buckets: Tuple[float, ...] = None):
        self.name = name
        self.buckets = tuple(sorted(buckets or DEFAULT_BUCKETS))
        self._counts = [0] * (len(self.buckets) + 1)   # +inf tail
        self._count = 0
        self._sum = 0.0
        self._min = None
        self._max = None
        self._digest = QuantileDigest()
        # second, drainable digest over the observations since the last
        # drain_window() — t-digests merge but do NOT subtract, so a
        # trailing-window quantile can only be honest if each window
        # keeps its own sketch (profiler/timeline.py drains one per
        # sampling tick and merges window sketches on query)
        self._win_digest = QuantileDigest()
        self._lock = threading.Lock()

    def observe(self, v):
        v = float(v)
        i = 0
        for b in self.buckets:
            if v <= b:
                break
            i += 1
        with self._lock:
            self._counts[i] += 1
            self._count += 1
            self._sum += v
            if self._min is None or v < self._min:
                self._min = v
            if self._max is None or v > self._max:
                self._max = v
            self._digest.observe(v)
            self._win_digest.observe(v)

    def drain_window(self) -> QuantileDigest:
        """Hand over (and reset) the digest of observations since the
        previous drain — single-consumer semantics: whoever samples the
        registry owns the window boundaries.  The cumulative digest is
        untouched."""
        with self._lock:
            wd = self._win_digest
            self._win_digest = QuantileDigest()
        return wd

    @property
    def count(self):
        with self._lock:
            return self._count

    @property
    def sum(self):
        with self._lock:
            return self._sum

    def quantile(self, q: float):
        """Digest-estimated quantile of the observed stream (honest
        p50/p95/p99, not a bucket bound); None while empty."""
        with self._lock:
            return self._digest.quantile(q)

    def _reset(self):
        with self._lock:
            self._counts = [0] * (len(self.buckets) + 1)
            self._count = 0
            self._sum = 0.0
            self._min = None
            self._max = None
            self._digest._reset()
            self._win_digest._reset()

    def _snap(self):
        with self._lock:
            return {
                "count": self._count,
                "sum": round(self._sum, 6),
                "avg": round(self._sum / self._count, 6)
                if self._count else None,
                "min": self._min, "max": self._max,
                "buckets": {str(b): c for b, c in
                            zip(self.buckets, self._counts)},
                "inf": self._counts[-1],
                "p50": self._digest.quantile(0.5),
                "p95": self._digest.quantile(0.95),
                "p99": self._digest.quantile(0.99),
                "digest": self._digest.to_dict(),
            }


class _FanoutCounter:
    """Child-registry counter: writes land on the local (per-namespace)
    counter AND roll up into the parent registry's same-name counter.
    Reads delegate to the local metric."""

    __slots__ = ("local", "up")
    kind = "counter"

    def __init__(self, local, up):
        self.local = local
        self.up = up

    def inc(self, v=1):
        self.local.inc(v)
        self.up.inc(v)

    def __getattr__(self, item):
        return getattr(object.__getattribute__(self, "local"), item)


class _FanoutGauge:
    __slots__ = ("local", "up")
    kind = "gauge"

    def __init__(self, local, up):
        self.local = local
        self.up = up

    def set(self, v):
        self.local.set(v)
        self.up.set(v)

    def inc(self, v=1):
        self.local.inc(v)
        self.up.inc(v)

    def __getattr__(self, item):
        return getattr(object.__getattribute__(self, "local"), item)


class _FanoutHistogram:
    __slots__ = ("local", "up")
    kind = "histogram"

    def __init__(self, local, up):
        self.local = local
        self.up = up

    def observe(self, v):
        self.local.observe(v)
        self.up.observe(v)

    def quantile(self, q):
        return self.local.quantile(q)

    def __getattr__(self, item):
        return getattr(object.__getattribute__(self, "local"), item)


_FANOUT = {"counter": _FanoutCounter, "gauge": _FanoutGauge,
           "histogram": _FanoutHistogram}


class MetricsRegistry:
    """Process-wide, thread-safe name -> metric table.

    Lookup (`counter`/`gauge`/`histogram`) is get-or-create; hot call
    sites should hold the returned object instead of re-looking-up per
    event. Requesting an existing name as a different kind raises.

    `child(namespace)` returns a namespaced child registry whose metric
    writes fan out to both the child's own metrics and this registry's
    same-name metrics — the mechanism that keeps co-hosted serving
    replicas from conflating their `serving/*` series while the global
    rollup stays intact.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}
        self._children: Dict[str, "ChildRegistry"] = {}
        self.namespace: Optional[str] = None
        self._flush_thread: Optional[threading.Thread] = None
        self._flush_stop = threading.Event()
        self._flush_path: Optional[str] = None

    def _get(self, name, cls, *args):
        m = self._metrics.get(name)
        if m is not None:
            if not isinstance(m, cls):
                raise TypeError(
                    f"metric '{name}' already registered as {m.kind}")
            return m
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, *args)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric '{name}' already registered as {m.kind}")
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str, buckets=None) -> Histogram:
        return self._get(name, Histogram, buckets)

    def child(self, namespace: str) -> "ChildRegistry":
        """Get-or-create the namespaced child registry (e.g. one per
        serving replica). Stable: the same namespace always returns the
        same child, so a FleetSupervisor-restarted engine re-binds to
        its replica's existing series."""
        with self._lock:
            c = self._children.get(namespace)
            if c is None:
                c = self._children[namespace] = ChildRegistry(
                    self, namespace)
            return c

    def children(self) -> Dict[str, "ChildRegistry"]:
        with self._lock:
            return dict(self._children)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def reset(self):
        """Zero every metric IN PLACE (instrumented modules hold direct
        references to metric objects, so they must not be replaced).
        Child registries are zeroed too."""
        with self._lock:
            metrics = list(self._metrics.values())
            children = list(self._children.values())
        for m in metrics:
            m._reset()
        for c in children:
            c.reset()

    # -- exporters --------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            metrics = dict(self._metrics)
        out = {"ts": time.time(), "pid": os.getpid(),
               "counters": {}, "gauges": {}, "histograms": {}}
        if self.namespace is not None:
            out["namespace"] = self.namespace
        for name in sorted(metrics):
            m = metrics[name]
            out[m.kind + "s"][name] = m._snap()
        return out

    def to_json(self, indent=None) -> str:
        return json.dumps(self.snapshot(), indent=indent)

    def to_prometheus_text(self) -> str:
        """Prometheus exposition format; '/'/'-' in names map to '_'."""
        def san(name):
            out = []
            for ch in name:
                out.append(ch if (ch.isalnum() or ch == "_") else "_")
            s = "".join(out)
            return ("_" + s) if s[:1].isdigit() else s

        with self._lock:
            metrics = dict(self._metrics)
        lines = []
        for name in sorted(metrics):
            m = metrics[name]
            p = san(name)
            if m.kind == "counter":
                lines.append(f"# TYPE {p} counter")
                lines.append(f"{p} {m.value}")
            elif m.kind == "gauge":
                lines.append(f"# TYPE {p} gauge")
                lines.append(f"{p} {m.value}")
            else:
                lines.append(f"# TYPE {p} histogram")
                acc = 0
                with m._lock:
                    counts = list(m._counts)
                    total, hsum = m._count, m._sum
                for b, c in zip(m.buckets, counts):
                    acc += c
                    lines.append(f'{p}_bucket{{le="{b}"}} {acc}')
                lines.append(f'{p}_bucket{{le="+Inf"}} {total}')
                lines.append(f"{p}_sum {hsum}")
                lines.append(f"{p}_count {total}")
        return "\n".join(lines) + "\n"

    def snapshot_to_file(self, path: str):
        """Atomic JSON snapshot: write tmp in the same directory, fsync,
        os.replace — a crash mid-write can never leave a torn file."""
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = os.path.join(d, f".{os.path.basename(path)}.{os.getpid()}.tmp")
        data = self.to_json()
        with open(tmp, "w") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    # -- crash-safe periodic flusher --------------------------------------
    def enable_periodic_flush(self, path: str, interval_s: float = 10.0):
        """Start (or retarget) the daemon flusher: every `interval_s` the
        registry is snapshotted atomically to `path`, and once more on
        interpreter exit, so a killed process still leaves its last
        complete interval behind."""
        self._flush_path = path
        if self._flush_thread is not None and self._flush_thread.is_alive():
            return
        self._flush_stop.clear()

        def loop():
            while not self._flush_stop.wait(interval_s):
                try:
                    self.snapshot_to_file(self._flush_path)
                except OSError:
                    pass

        self._flush_thread = threading.Thread(
            target=loop, name="pt_metrics_flush", daemon=True)
        self._flush_thread.start()
        import atexit

        atexit.register(self._final_flush)

    def _final_flush(self):
        if self._flush_path:
            try:
                self.snapshot_to_file(self._flush_path)
            except OSError:
                pass

    def disable_periodic_flush(self, final_flush: bool = True):
        self._flush_stop.set()
        if self._flush_thread is not None:
            self._flush_thread.join(timeout=2.0)
            self._flush_thread = None
        if final_flush:
            self._final_flush()
        self._flush_path = None


class ChildRegistry(MetricsRegistry):
    """Namespaced registry whose metrics fan out to a parent.

    `child.counter("serving/requests").inc()` bumps both the child's
    local counter (per-replica truth, what `snapshot()` reports) and
    the parent registry's counter of the same name (the global rollup
    existing dashboards and tests read)."""

    def __init__(self, parent: MetricsRegistry, namespace: str):
        super().__init__()
        self.parent = parent
        self.namespace = namespace

    def _get(self, name, cls, *args):
        m = self._metrics.get(name)
        if m is not None:
            if m.kind != cls.kind:
                raise TypeError(
                    f"metric '{name}' already registered as {m.kind}")
            return m
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                up = self.parent._get(name, cls, *args)
                local = cls(name, *args)
                m = self._metrics[name] = _FANOUT[cls.kind](local, up)
            elif m.kind != cls.kind:
                raise TypeError(
                    f"metric '{name}' already registered as {m.kind}")
            return m


_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    return _REGISTRY


def child(namespace: str) -> ChildRegistry:
    """Namespaced child of the process-wide registry."""
    return _REGISTRY.child(namespace)


def counter(name: str) -> Counter:
    return _REGISTRY.counter(name)


def gauge(name: str) -> Gauge:
    return _REGISTRY.gauge(name)


def histogram(name: str, buckets=None) -> Histogram:
    return _REGISTRY.histogram(name, buckets)


def inc(name: str, v=1):
    _REGISTRY.counter(name).inc(v)


def set_gauge(name: str, v):
    _REGISTRY.gauge(name).set(v)


def observe(name: str, v):
    _REGISTRY.histogram(name).observe(v)


class timed:
    """Context manager: wall-clock milliseconds into a histogram.

        with metrics.timed("jit/compile_ms"):
            compile()
    """

    __slots__ = ("hist", "_t0")

    def __init__(self, name_or_hist):
        self.hist = name_or_hist if isinstance(name_or_hist, Histogram) \
            else _REGISTRY.histogram(name_or_hist)

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.hist.observe((time.perf_counter() - self._t0) * 1e3)
        return False


def snapshot() -> dict:
    return _REGISTRY.snapshot()


def to_json(indent=None) -> str:
    return _REGISTRY.to_json(indent)


def to_prometheus_text() -> str:
    return _REGISTRY.to_prometheus_text()


def snapshot_to_file(path: str):
    _REGISTRY.snapshot_to_file(path)


def enable_periodic_flush(path: str, interval_s: float = 10.0):
    _REGISTRY.enable_periodic_flush(path, interval_s)


def disable_periodic_flush(final_flush: bool = True):
    _REGISTRY.disable_periodic_flush(final_flush)


def reset():
    _REGISTRY.reset()


# env-armed crash-safe flush: PT_METRICS_FLUSH_PATH=/path/metrics.json
# [PT_METRICS_FLUSH_INTERVAL=10]
_env_path = os.environ.get("PT_METRICS_FLUSH_PATH")
if _env_path:
    try:
        enable_periodic_flush(
            _env_path,
            float(os.environ.get("PT_METRICS_FLUSH_INTERVAL", "10") or 10))
    except (OSError, ValueError):
        pass
