"""SLO engine: per-(tenant × class) objectives, attainment accounting,
error budgets, and multi-window burn-rate alerts.

The gateway resolves every request to exactly one reason-coded terminal
outcome — ``completed`` / ``deadline_missed`` / ``shed`` /
``rejected(reason)`` / ``drained`` — and publishes it to registered
outcome listeners.  `SLOTracker.attach(gateway)` subscribes and turns
the stream into judgment:

  * **Goodness.**  A request is GOOD iff its outcome is completed or
    drained AND (when the objective sets a TTFT bound) its first token
    arrived within it.  Attainment = good/total over a window; the
    error budget is ``1 - target``.
  * **Burn rate.**  ``burn = bad_fraction / error_budget`` — 1.0 burns
    the budget exactly at the sustainable rate, 10x eats a day's budget
    in ~2.4 hours.
  * **Multi-window alerts.**  An alert RAISES only when BOTH the fast
    window (default 5m — catches the storm now) and the slow window
    (default 1h — proves it is not a blip) burn at/above the
    threshold, and CLEARS only after `clear_after` consecutive calm
    evaluations with the fast burn at/below ``threshold *
    exit_ratio`` — the same enter-high/exit-low hysteresis as the
    brownout ladder, so a single storm spike cannot flap the pager.

Alerts are structured `SLOAlert`s; raising/clearing also lands a
flight-recorder note and a timeline event, so the black box and the
postmortem spill both carry the judgment next to the raw telemetry.
Clocks are injectable everywhere; nothing reads wall-clock unless the
default is used.
"""
from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from . import metrics as _metrics
from . import timeline as _timeline
from . import tracing as _tracing

__all__ = ["SLOObjective", "SLOAlert", "SLOTracker",
           "OUTCOMES", "GOOD_OUTCOMES"]

# the gateway's terminal-outcome vocabulary
OUTCOMES = ("completed", "deadline_missed", "shed", "rejected", "drained")
GOOD_OUTCOMES = frozenset(("completed", "drained"))

_m_recorded = _metrics.counter("slo/outcomes_recorded")
_m_raised = _metrics.counter("slo/alerts_raised")
_m_cleared = _metrics.counter("slo/alerts_cleared")
_m_active = _metrics.gauge("slo/active_alerts")


@dataclass
class SLOObjective:
    """One objective: required good fraction (`target`, e.g. 0.99) and
    an optional per-request TTFT bound folded into goodness — a
    completed request slower than `ttft_ms` still burns budget."""

    target: float = 0.99
    ttft_ms: Optional[float] = None

    @property
    def error_budget(self) -> float:
        return max(1e-9, 1.0 - self.target)


@dataclass
class SLOAlert:
    """A raised burn-rate alert; `cleared_t is None` while active."""

    tenant: str
    slo_class: str
    kind: str
    raised_t: float
    fast_burn: float
    slow_burn: float
    threshold: float
    cleared_t: Optional[float] = None

    @property
    def active(self) -> bool:
        return self.cleared_t is None

    def to_dict(self) -> dict:
        return {"tenant": self.tenant, "slo_class": self.slo_class,
                "kind": self.kind, "raised_t": self.raised_t,
                "fast_burn": round(self.fast_burn, 4),
                "slow_burn": round(self.slow_burn, 4),
                "threshold": self.threshold,
                "cleared_t": self.cleared_t, "active": self.active}


class SLOTracker:
    """Attainment + burn-rate state machine over outcome events.

    tracker = SLOTracker(
        class_objectives={"interactive": SLOObjective(0.999, ttft_ms=200)},
        fast_window_s=300, slow_window_s=3600, burn_threshold=10.0,
    ).attach(gateway)
    ...
    tracker.evaluate()            # call periodically (per timeline tick)
    tracker.attainment("acme", "interactive")
    tracker.report()
    """

    def __init__(self,
                 objectives: Optional[Dict[Tuple[str, str],
                                           SLOObjective]] = None,
                 class_objectives: Optional[Dict[str, SLOObjective]] = None,
                 default: Optional[SLOObjective] = None,
                 clock: Callable[[], float] = time.monotonic,
                 fast_window_s: float = 300.0,
                 slow_window_s: float = 3600.0,
                 burn_threshold: float = 10.0,
                 exit_ratio: float = 0.5,
                 clear_after: int = 3,
                 count_synthetic: bool = True,
                 max_events: int = 65536):
        self.objectives = dict(objectives or {})      # (tenant, class) ->
        self.class_objectives = dict(class_objectives or {})
        self.default = default or SLOObjective()
        self._clock = clock
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.burn_threshold = float(burn_threshold)
        self.exit_ratio = float(exit_ratio)
        self.clear_after = max(1, int(clear_after))
        self.count_synthetic = count_synthetic
        # (t, tenant, slo, outcome, reason, good)
        self._events: deque = deque(maxlen=max(1024, int(max_events)))
        # (tenant, slo) -> {"alert": SLOAlert|None, "calm": int}
        self._state: Dict[Tuple[str, str], dict] = {}
        self.alerts: List[SLOAlert] = []    # every alert ever raised

    # -- objective lookup -------------------------------------------------
    def objective(self, tenant: str, slo: str) -> SLOObjective:
        obj = self.objectives.get((tenant, slo))
        if obj is None:
            obj = self.class_objectives.get(slo)
        return obj or self.default

    # -- ingestion --------------------------------------------------------
    def attach(self, gateway) -> "SLOTracker":
        """Subscribe to a FleetGateway's outcome events."""
        gateway.outcome_listeners.append(self.record)
        return self

    def record(self, ev: dict) -> None:
        """Ingest one gateway outcome event (the listener callback)."""
        if ev.get("synthetic") and not self.count_synthetic:
            return
        tenant = str(ev.get("tenant"))
        slo = str(ev.get("slo"))
        outcome = str(ev.get("outcome"))
        good = outcome in GOOD_OUTCOMES
        if good:
            obj = self.objective(tenant, slo)
            ttft = ev.get("ttft_ms")
            if obj.ttft_ms is not None and ttft is not None \
                    and ttft > obj.ttft_ms:
                good = False
        self._events.append((self._clock(), tenant, slo, outcome,
                             ev.get("reason"), good))
        _m_recorded.inc()

    # -- attainment -------------------------------------------------------
    def _select(self, tenant=None, slo=None, window_s=None, now=None):
        if window_s is not None and now is None:
            now = self._clock()
        out = []
        for t, tn, sc, outcome, reason, good in self._events:
            if tenant is not None and tn != tenant:
                continue
            if slo is not None and sc != slo:
                continue
            if window_s is not None and t < now - window_s:
                continue
            out.append((t, tn, sc, outcome, reason, good))
        return out

    def attainment(self, tenant: Optional[str] = None,
                   slo: Optional[str] = None,
                   window_s: Optional[float] = None,
                   now: Optional[float] = None) -> Optional[float]:
        """good/total over the (optionally trailing, optionally
        filtered) outcome stream; None with no traffic."""
        evs = self._select(tenant, slo, window_s, now)
        if not evs:
            return None
        return sum(1 for e in evs if e[5]) / len(evs)

    def _burn(self, evs) -> Tuple[float, int]:
        if not evs:
            return 0.0, 0
        bad = sum(1 for e in evs if not e[5])
        return bad / len(evs), len(evs)

    # -- the alert state machine ------------------------------------------
    def evaluate(self, now: Optional[float] = None) -> List[SLOAlert]:
        """One evaluation pass: recompute fast/slow burns per
        (tenant × class), raise/clear with hysteresis.  Returns the
        active alerts."""
        if now is None:
            now = self._clock()
        by_key: Dict[Tuple[str, str], list] = {}
        for ev in self._events:
            if ev[0] < now - self.slow_window_s:
                continue
            by_key.setdefault((ev[1], ev[2]), []).append(ev)
        thr = self.burn_threshold
        for key in set(by_key) | set(self._state):
            evs = by_key.get(key, [])
            budget = self.objective(*key).error_budget
            fast_frac, _ = self._burn(
                [e for e in evs if e[0] >= now - self.fast_window_s])
            slow_frac, _ = self._burn(evs)
            fast_burn = fast_frac / budget
            slow_burn = slow_frac / budget
            st = self._state.setdefault(key, {"alert": None, "calm": 0})
            alert = st["alert"]
            if alert is None:
                if fast_burn >= thr and slow_burn >= thr:
                    alert = SLOAlert(key[0], key[1], "burn_rate", now,
                                     fast_burn, slow_burn, thr)
                    st["alert"] = alert
                    st["calm"] = 0
                    self.alerts.append(alert)
                    _m_raised.inc()
                    note = alert.to_dict()
                    note["alert_kind"] = note.pop("kind")
                    _tracing.flight_note("slo_alert", **note)
                    _timeline.emit_event("slo_alert", **note)
            else:
                alert.fast_burn = fast_burn
                alert.slow_burn = slow_burn
                if fast_burn <= thr * self.exit_ratio:
                    st["calm"] += 1
                    if st["calm"] >= self.clear_after:
                        alert.cleared_t = now
                        st["alert"] = None
                        st["calm"] = 0
                        _m_cleared.inc()
                        note = alert.to_dict()
                        note["alert_kind"] = note.pop("kind")
                        _tracing.flight_note("slo_alert_cleared", **note)
                        _timeline.emit_event("slo_alert_cleared", **note)
                else:
                    st["calm"] = 0
        active = self.active_alerts()
        _m_active.set(len(active))
        return active

    def active_alerts(self) -> List[SLOAlert]:
        return [a for a in self.alerts if a.active]

    # -- reporting --------------------------------------------------------
    def report(self, window_s: Optional[float] = None,
               now: Optional[float] = None) -> dict:
        """The dashboard document: per-(tenant × class) attainment vs
        objective with burns, a per-class rollup, and the alert
        census."""
        if now is None:
            now = self._clock()
        keys = sorted({(e[1], e[2]) for e in self._events})
        per_tenant = {}
        for tenant, slo in keys:
            evs = self._select(tenant, slo, window_s, now)
            obj = self.objective(tenant, slo)
            att = (sum(1 for e in evs if e[5]) / len(evs)) if evs else None
            fast_frac, _ = self._burn(
                [e for e in evs if e[0] >= now - self.fast_window_s])
            slow_frac, _ = self._burn(
                [e for e in evs if e[0] >= now - self.slow_window_s])
            st = self._state.get((tenant, slo), {})
            outcomes: Dict[str, int] = {}
            for e in evs:
                outcomes[e[3]] = outcomes.get(e[3], 0) + 1
            per_tenant[f"{tenant}/{slo}"] = {
                "tenant": tenant, "slo_class": slo,
                "total": len(evs),
                "good": sum(1 for e in evs if e[5]),
                "attainment": round(att, 4) if att is not None else None,
                "target": obj.target,
                "error_budget": round(obj.error_budget, 6),
                "fast_burn": round(fast_frac / obj.error_budget, 4),
                "slow_burn": round(slow_frac / obj.error_budget, 4),
                "outcomes": outcomes,
                "alert_active": st.get("alert") is not None,
            }
        per_class: Dict[str, dict] = {}
        for slo in sorted({k[1] for k in keys}):
            evs = self._select(None, slo, window_s, now)
            att = (sum(1 for e in evs if e[5]) / len(evs)) if evs else None
            per_class[slo] = {
                "total": len(evs),
                "good": sum(1 for e in evs if e[5]),
                "attainment": round(att, 4) if att is not None else None,
            }
        return {
            "per_tenant": per_tenant,
            "per_class": per_class,
            "alerts": {
                "raised": len(self.alerts),
                "active": len(self.active_alerts()),
                "cleared": sum(1 for a in self.alerts if not a.active),
                "log": [a.to_dict() for a in self.alerts],
            },
        }
