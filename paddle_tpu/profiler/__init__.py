"""Profiler (reference: python/paddle/profiler/profiler.py:346 + the C++
layered tracers in paddle/fluid/platform/profiler/).

TPU-native mapping (SURVEY.md §5): device-side tracing is jax.profiler
(XPlane -> TensorBoard/perfetto, the CUPTI analog); host spans are
RecordEvent instrumentation aggregated into a summary table. Both run under
one Profiler orchestrator with the reference's scheduler-state API."""
from __future__ import annotations

import contextlib
import enum
import os
import threading
import time
from collections import defaultdict
from typing import Callable, Optional

import jax

from . import metrics

__all__ = ["Profiler", "ProfilerTarget", "ProfilerState", "RecordEvent",
           "make_scheduler", "export_chrome_tracing", "export_protobuf",
           "load_profiler_result", "SummaryView", "metrics",
           "host_tracing_active", "tracing", "digest", "aggregate",
           "timeline", "slo", "headroom", "TraceContext"]


class ProfilerTarget(enum.Enum):
    CPU = 0
    GPU = 1
    TPU = 2
    CUSTOM_DEVICE = 3


class ProfilerState(enum.Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class SummaryView(enum.Enum):
    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6


def make_scheduler(closed: int, ready: int, record: int, repeat: int = 0,
                   skip_first: int = 0) -> Callable[[int], ProfilerState]:
    def scheduler(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        cycle = closed + ready + record
        if repeat and s >= cycle * repeat:
            return ProfilerState.CLOSED
        pos = s % cycle
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == cycle - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD
    return scheduler


class _HostEventCollector(threading.local):
    def __init__(self):
        self.events = []
        self.active = False


_collector = _HostEventCollector()


def host_tracing_active() -> bool:
    """True while a Profiler is collecting host spans — instrumented hot
    paths check this before opening per-event RecordEvent spans so the
    always-on cost is one attribute read."""
    return _collector.active


class RecordEvent:
    """Host instrumentation span (reference: platform/profiler RecordEvent)."""

    def __init__(self, name: str, event_type=None):
        self.name = name
        self.begin = None

    def __enter__(self):
        self.begin = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.end()
        return False

    def end(self):
        if self.begin is not None and _collector.active:
            _collector.events.append(
                (self.name, self.begin, time.perf_counter()))
            self.begin = None


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None):
    def handler(prof):
        prof._export_dir = dir_name
        prof.export(os.path.join(
            dir_name, (worker_name or "worker") + ".json"))
    return handler


def export_protobuf(dir_name: str, worker_name: Optional[str] = None):
    return export_chrome_tracing(dir_name, worker_name)


def load_profiler_result(filename: str):
    import json

    with open(filename) as f:
        return json.load(f)


class Profiler:
    """Orchestrator with scheduler states. Device tracing = jax.profiler
    (XPlane); host spans = RecordEvent collection."""

    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 record_shapes=False, profile_memory=False, timer_only=False,
                 emit_nvtx=False, custom_device_types=None, with_flops=False):
        self.targets = targets or [ProfilerTarget.CPU, ProfilerTarget.TPU]
        if isinstance(scheduler, (tuple, list)):
            lo, hi = scheduler
            self.scheduler = make_scheduler(closed=max(lo, 0), ready=0,
                                            record=hi - lo, repeat=1)
        else:
            self.scheduler = scheduler or (
                lambda step: ProfilerState.RECORD)
        self.on_trace_ready = on_trace_ready
        self.timer_only = timer_only
        self.step_num = 0
        self.state = ProfilerState.CLOSED
        self._jax_tracing = False
        self._trace_dir = None
        self._step_times = []
        self._last_step_t = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    def _jax_start(self):
        if not self._jax_tracing and not self.timer_only:
            self._trace_dir = os.environ.get(
                "PT_PROFILE_DIR", "/tmp/paddle_tpu_profile")
            try:
                jax.profiler.start_trace(self._trace_dir)
                self._jax_tracing = True
            except Exception:
                self._jax_tracing = False

    def _jax_stop(self):
        if self._jax_tracing:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._jax_tracing = False

    def start(self):
        _collector.active = True
        _collector.events = []
        self.state = self.scheduler(self.step_num)
        if self.state in (ProfilerState.RECORD,
                          ProfilerState.RECORD_AND_RETURN):
            self._jax_start()
        self._last_step_t = time.perf_counter()

    def step(self, num_samples: Optional[int] = None):
        now = time.perf_counter()
        if self._last_step_t is not None:
            self._step_times.append((now - self._last_step_t, num_samples))
        self._last_step_t = now
        self.step_num += 1
        new_state = self.scheduler(self.step_num)
        if new_state != self.state:
            if new_state in (ProfilerState.RECORD,
                             ProfilerState.RECORD_AND_RETURN):
                self._jax_start()
            elif self.state in (ProfilerState.RECORD,
                                ProfilerState.RECORD_AND_RETURN):
                self._jax_stop()
                if self.on_trace_ready:
                    self.on_trace_ready(self)
            self.state = new_state

    def stop(self):
        self._jax_stop()
        _collector.active = False
        if self.on_trace_ready and self.state in (
                ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN):
            self.on_trace_ready(self)

    def export(self, path: str, format: str = "json"):
        """Export host spans as chrome-trace; XPlane files live in the
        jax.profiler trace dir."""
        import json

        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        events = []
        for name, b, e in _collector.events:
            events.append({
                "name": name, "ph": "X", "pid": 0, "tid": 0,
                "ts": b * 1e6, "dur": (e - b) * 1e6,
            })
        with open(path, "w") as f:
            json.dump({"traceEvents": events,
                       "xplane_dir": self._trace_dir}, f)

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms", views=None):
        agg = defaultdict(lambda: [0.0, 0])
        for name, b, e in _collector.events:
            agg[name][0] += (e - b) * 1e3
            agg[name][1] += 1
        lines = [f"{'Name':<40} {'Calls':>8} {'Total(ms)':>12} {'Avg(ms)':>12}"]
        for name, (total, calls) in sorted(agg.items(),
                                           key=lambda kv: -kv[1][0]):
            lines.append(
                f"{name:<40} {calls:>8} {total:>12.3f} "
                f"{total / max(calls, 1):>12.3f}")
        table = "\n".join(lines)
        print(table)
        return table

    # throughput timer (reference: profiler/timer.py benchmark hooks)
    def step_info(self, unit="samples"):
        if not self._step_times:
            return "no steps recorded"
        import numpy as np

        times = np.asarray([t for t, _ in self._step_times[-20:]])
        ips = None
        samples = [n for _, n in self._step_times[-20:] if n]
        if samples:
            ips = np.asarray(samples) / times[-len(samples):]
        msg = f"avg step: {times.mean() * 1e3:.2f} ms"
        if ips is not None:
            msg += f", ips: {ips.mean():.1f} {unit}/s"
        return msg


# fleet observability plane — imported last: tracing layers TraceContext
# propagation on RecordEvent (above), aggregate ships registry snapshots
# across processes, digest is the mergeable quantile sketch both use.
from . import digest           # noqa: E402
from . import tracing          # noqa: E402
from . import aggregate        # noqa: E402
# the SLO engine (ISSUE 16): timeline = the time dimension over the
# registry, slo = objectives/attainment/burn alerts over gateway
# outcomes, headroom = the AutoScaler advisory interface
from . import timeline         # noqa: E402
from . import slo              # noqa: E402
from . import headroom         # noqa: E402
from .tracing import TraceContext  # noqa: E402
from .aggregate import FleetAggregator  # noqa: E402
from .timeline import Timeline, load_spill  # noqa: E402
from .slo import SLOAlert, SLOObjective, SLOTracker  # noqa: E402
from .headroom import ScaleAdvice, ScaleAdvisor  # noqa: E402
