"""Fixed-size mergeable streaming quantile sketch (merging t-digest).

The metrics registry's `Histogram` answers "how many observations fell
in each latency band" but its bucket-estimated p50/p95 are only as good
as the bucket edges — the fleet bench's `tpot_ms_min/max` stopgap exists
because the edges were too coarse to quote an honest p99. This sketch
gives honest tail quantiles from O(compression) memory regardless of
stream length, and — critically for the fleet aggregation plane — two
sketches merge into one that is as accurate as a sketch built from the
concatenated stream, so per-replica digests roll up into fleet-wide
percentiles without shipping raw samples.

Algorithm: the "merging" t-digest variant. Incoming values buffer in a
flat list; on overflow (or any read) the buffer and existing centroids
are sorted by mean and re-clustered under the k1 scale function
``k(q) = (compression / 2π) · asin(2q − 1)``, which keeps clusters tiny
at the tails (exact min/max, tight p99) and coarse in the middle. Memory
is bounded: after compression the centroid count is < 2·compression and
the buffer never exceeds a fixed cap, independent of how many values
were observed.

Serialization (`to_dict` / `from_dict`) is plain JSON so digests travel
inside metrics snapshots over the CRC/ACK transport.
"""
from __future__ import annotations

import math
from typing import Iterable, List, Optional, Tuple

__all__ = ["QuantileDigest"]


class QuantileDigest:
    """Mergeable streaming quantile sketch with bounded memory."""

    __slots__ = ("compression", "_means", "_weights", "_buf_v", "_buf_w",
                 "_buf_cap", "_count", "_min", "_max")

    def __init__(self, compression: int = 128):
        if compression < 8:
            raise ValueError("compression must be >= 8")
        self.compression = int(compression)
        self._means: List[float] = []      # sorted centroid means
        self._weights: List[float] = []    # parallel centroid weights
        self._buf_v: List[float] = []      # unmerged values
        self._buf_w: List[float] = []      # parallel weights
        self._buf_cap = max(512, 4 * self.compression)
        self._count = 0.0
        self._min: Optional[float] = None
        self._max: Optional[float] = None

    # -- ingestion --------------------------------------------------------
    def observe(self, v) -> None:
        v = float(v)
        self._buf_v.append(v)
        self._buf_w.append(1.0)
        self._count += 1.0
        if self._min is None or v < self._min:
            self._min = v
        if self._max is None or v > self._max:
            self._max = v
        if len(self._buf_v) >= self._buf_cap:
            self._compress()

    def update_many(self, values: Iterable[float]) -> None:
        """Bulk ingest; chunks through the buffer so a 1e6-value stream
        never holds more than buffer + centroids in memory at once."""
        for v in values:
            self.observe(v)

    def merge(self, other: "QuantileDigest") -> "QuantileDigest":
        """Fold `other` into this digest in place (returns self)."""
        if other._count == 0:
            return self
        self._buf_v.extend(other._means)
        self._buf_w.extend(other._weights)
        self._buf_v.extend(other._buf_v)
        self._buf_w.extend(other._buf_w)
        self._count += other._count
        if other._min is not None and (self._min is None
                                       or other._min < self._min):
            self._min = other._min
        if other._max is not None and (self._max is None
                                       or other._max > self._max):
            self._max = other._max
        self._compress()
        return self

    # -- compression ------------------------------------------------------
    def _k(self, q: float) -> float:
        q = min(1.0, max(0.0, q))
        return self.compression / (2.0 * math.pi) * math.asin(2.0 * q - 1.0)

    def _compress(self) -> None:
        if not self._buf_v and len(self._means) < 2 * self.compression:
            return
        pts: List[Tuple[float, float]] = list(zip(self._means, self._weights))
        pts.extend(zip(self._buf_v, self._buf_w))
        self._buf_v = []
        self._buf_w = []
        if not pts:
            return
        pts.sort(key=lambda p: p[0])
        total = sum(w for _, w in pts)
        means: List[float] = []
        weights: List[float] = []
        cum = 0.0                       # weight strictly before current cluster
        cur_m, cur_w = pts[0]
        k_lo = self._k(0.0)
        for m, w in pts[1:]:
            q_hi = (cum + cur_w + w) / total
            if self._k(q_hi) - k_lo <= 1.0:
                # weighted-mean merge into the open cluster
                cur_m += (m - cur_m) * (w / (cur_w + w))
                cur_w += w
            else:
                means.append(cur_m)
                weights.append(cur_w)
                cum += cur_w
                cur_m, cur_w = m, w
                k_lo = self._k(cum / total)
        means.append(cur_m)
        weights.append(cur_w)
        self._means = means
        self._weights = weights

    # -- queries ----------------------------------------------------------
    @property
    def count(self) -> int:
        return int(self._count)

    @property
    def min(self) -> Optional[float]:
        return self._min

    @property
    def max(self) -> Optional[float]:
        return self._max

    def size(self) -> int:
        """Retained points (centroids + buffered) — the memory bound."""
        return len(self._means) + len(self._buf_v)

    def quantile(self, q: float) -> Optional[float]:
        """Estimate the q-quantile (q in [0, 1]); None when empty."""
        if self._count == 0:
            return None
        self._compress()
        means, weights = self._means, self._weights
        if len(means) == 1:
            return means[0]
        q = min(1.0, max(0.0, q))
        total = sum(weights)
        target = q * total
        # centroid i "lives" at cumulative position cum_i + w_i / 2
        cum = 0.0
        prev_pos = 0.0
        prev_mean = self._min
        for m, w in zip(means, weights):
            pos = cum + w / 2.0
            if target < pos:
                span = pos - prev_pos
                frac = (target - prev_pos) / span if span > 0 else 0.0
                return prev_mean + (m - prev_mean) * frac
            prev_pos, prev_mean = pos, m
            cum += w
        # above the last centroid's midpoint: interpolate toward max
        span = total - prev_pos
        frac = (target - prev_pos) / span if span > 0 else 1.0
        return prev_mean + (self._max - prev_mean) * min(1.0, frac)

    def quantiles(self, qs: Iterable[float]) -> List[Optional[float]]:
        return [self.quantile(q) for q in qs]

    # -- serialization ----------------------------------------------------
    def to_dict(self) -> dict:
        self._compress()
        return {
            "compression": self.compression,
            "count": self._count,
            "min": self._min,
            "max": self._max,
            "centroids": [[round(m, 9), w] for m, w in
                          zip(self._means, self._weights)],
        }

    @classmethod
    def from_dict(cls, d: dict) -> "QuantileDigest":
        dg = cls(int(d.get("compression", 128)))
        cents = d.get("centroids", [])
        dg._means = [float(m) for m, _ in cents]
        dg._weights = [float(w) for _, w in cents]
        dg._count = float(d.get("count", sum(dg._weights)))
        dg._min = d.get("min")
        dg._max = d.get("max")
        return dg

    def copy(self) -> "QuantileDigest":
        return QuantileDigest.from_dict(self.to_dict())

    def _reset(self) -> None:
        self._means = []
        self._weights = []
        self._buf_v = []
        self._buf_w = []
        self._count = 0.0
        self._min = None
        self._max = None

    def __repr__(self):
        return (f"QuantileDigest(compression={self.compression}, "
                f"count={self.count}, size={self.size()})")
