"""Fleet-wide distributed tracing: trace contexts, a span ring, and a
crash flight recorder.

PR 1's `RecordEvent` spans are process-local and only recorded while a
`Profiler` session is active. The fleet stack (router → replica →
disagg prefill/decode → drain/migrate) moves one request through many
engines and, in production, many processes — so spans here carry a
`TraceContext` (trace_id / span_id / parent_id) that is

  * propagated inside a process through a contextvar (`span(...)`
    context manager — which still drives `RecordEvent`, so Profiler
    chrome traces keep working),
  * serialized into cross-process hand-off payloads (disagg migration
    meta, drain/requeue info dicts) via `inject`/`extract`, and
  * attached to request-lifecycle spans (`record_span`) that the
    serving engine emits at phase boundaries: admission → queue →
    prefill → migrate → decode.

All finished spans land in an always-on bounded ring (no Profiler
session required; capacity `PT_TRACE_RING`, default 4096) and export to
chrome-trace JSON with the ids in `args`, so `tools/trace_report.py`
can merge multi-host traces onto one timeline and a migrated request's
pre- and post-migration spans join under one trace id.

The `FlightRecorder` keeps a second bounded ring of annotated events
(span completions are mirrored into it, hooks add notes) and dumps
ring + counter deltas + a full metrics snapshot to disk when something
dies: `EngineDeadError` drains, comm-watchdog escalation, quorum loss.
Dumps go to `PT_FLIGHT_DIR` (or a directory set via
`set_flight_dir`); with neither configured the dump is a no-op so the
hot path never grows a hard filesystem dependency.
"""
from __future__ import annotations

import contextvars
import json
import os
import threading
import time
import uuid
from collections import deque
from typing import Optional

from . import RecordEvent
from . import metrics as _metrics

__all__ = [
    "TraceContext", "current", "use_context", "span", "record_span",
    "child_of", "inject", "extract", "ring_spans", "clear_ring",
    "export_chrome", "FlightRecorder", "flight", "flight_note",
    "flight_dump", "set_flight_dir",
]

_m_spans = _metrics.counter("trace/spans")
_m_dumps = _metrics.counter("trace/flight_dumps")
_m_dump_errors = _metrics.counter("trace/flight_dump_errors")


def _new_id() -> str:
    return uuid.uuid4().hex[:16]


class TraceContext:
    """Identity of one span inside one trace.

    `trace_id` names the whole request/operation tree; `span_id` names
    this span; `parent_id` links to the enclosing span (None at roots).
    """

    __slots__ = ("trace_id", "span_id", "parent_id")

    def __init__(self, trace_id: str, span_id: str,
                 parent_id: Optional[str] = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id

    @classmethod
    def new_root(cls) -> "TraceContext":
        return cls(_new_id(), _new_id(), None)

    def child(self) -> "TraceContext":
        return TraceContext(self.trace_id, _new_id(), self.span_id)

    def to_dict(self) -> dict:
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "parent_id": self.parent_id}

    @classmethod
    def from_dict(cls, d: dict) -> "TraceContext":
        return cls(d["trace_id"], d["span_id"], d.get("parent_id"))

    def __repr__(self):
        return (f"TraceContext({self.trace_id}/{self.span_id}"
                f"<-{self.parent_id})")


_current: contextvars.ContextVar = contextvars.ContextVar(
    "pt_trace_ctx", default=None)


def current() -> Optional[TraceContext]:
    """The TraceContext of the innermost open `span(...)`, if any."""
    return _current.get()


def child_of(ctx) -> TraceContext:
    """Mint a child context of `ctx` (a TraceContext, a dict from
    `to_dict`, or None → fresh root)."""
    if ctx is None:
        return TraceContext.new_root()
    if isinstance(ctx, dict):
        ctx = TraceContext.from_dict(ctx)
    return ctx.child()


class use_context:
    """Install `ctx` as the ambient trace context for a `with` block."""

    __slots__ = ("ctx", "_token")

    def __init__(self, ctx: Optional[TraceContext]):
        self.ctx = ctx

    def __enter__(self):
        self._token = _current.set(self.ctx)
        return self.ctx

    def __exit__(self, *exc):
        _current.reset(self._token)
        return False


# -- span ring ------------------------------------------------------------

_RING_CAP = int(os.environ.get("PT_TRACE_RING", "4096") or 4096)
_ring = deque(maxlen=max(64, _RING_CAP))
_ring_lock = threading.Lock()


def _push(span_dict: dict) -> None:
    with _ring_lock:
        _ring.append(span_dict)
    _m_spans.inc()
    flight.note("span", **span_dict)


def ring_spans():
    """Snapshot of the bounded span ring (list of span dicts)."""
    with _ring_lock:
        return list(_ring)


def clear_ring():
    with _ring_lock:
        _ring.clear()


def record_span(name: str, begin: float, end: float, ctx=None, parent=None,
                args: Optional[dict] = None) -> TraceContext:
    """Record a completed span directly (no context manager).

    `begin`/`end` are `time.perf_counter()` seconds. Identity: pass
    `ctx` to use it as-is, or `parent` (TraceContext/dict/None) to mint
    a child; with neither, the ambient context parents the span.
    Returns the span's context so callers can chain children off it.
    """
    if ctx is None:
        ctx = child_of(parent if parent is not None else _current.get())
    elif isinstance(ctx, dict):
        ctx = TraceContext.from_dict(ctx)
    d = {"name": name, "ts": float(begin),
         "dur": max(0.0, float(end) - float(begin)),
         "trace_id": ctx.trace_id, "span_id": ctx.span_id,
         "parent_id": ctx.parent_id, "pid": os.getpid()}
    if args:
        d["args"] = dict(args)
    _push(d)
    return ctx


class span:
    """Context manager: a traced span that nests via the contextvar and
    also drives `RecordEvent` so active Profiler sessions see it."""

    __slots__ = ("name", "args", "ctx", "_t0", "_token", "_rev")

    def __init__(self, name: str, **args):
        self.name = name
        self.args = args

    def __enter__(self):
        self.ctx = child_of(_current.get())
        self._token = _current.set(self.ctx)
        self._rev = RecordEvent(self.name)
        self._rev.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        end = time.perf_counter()
        self._rev.__exit__(*exc)
        _current.reset(self._token)
        record_span(self.name, self._t0, end, ctx=self.ctx,
                    args=self.args or None)
        return False


# -- cross-process propagation -------------------------------------------

TRACE_META_KEY = "trace"


def inject(meta: dict, ctx: Optional[TraceContext] = None) -> dict:
    """Serialize `ctx` (default: ambient) into a hand-off payload."""
    if ctx is None:
        ctx = _current.get()
    if ctx is not None:
        meta[TRACE_META_KEY] = ctx.to_dict()
    return meta


def extract(meta: Optional[dict]) -> Optional[TraceContext]:
    """Recover a TraceContext from a hand-off payload (or None)."""
    if not meta:
        return None
    d = meta.get(TRACE_META_KEY)
    return TraceContext.from_dict(d) if d else None


# -- chrome export --------------------------------------------------------

def export_chrome(path: Optional[str] = None, spans=None,
                  clock_offset_s: float = 0.0, pid=None) -> dict:
    """Render spans (default: the ring) as chrome-trace JSON with the
    trace/span/parent ids in each event's `args`. `clock_offset_s`
    shifts timestamps so multi-host traces merge onto one timeline."""
    evs = []
    for s in (ring_spans() if spans is None else spans):
        ev = {"name": s["name"], "ph": "X",
              "pid": pid if pid is not None else s.get("pid", 0),
              "tid": 0,
              "ts": (s["ts"] + clock_offset_s) * 1e6,
              "dur": s["dur"] * 1e6,
              "args": {"trace_id": s.get("trace_id"),
                       "span_id": s.get("span_id"),
                       "parent_id": s.get("parent_id"),
                       **(s.get("args") or {})}}
        evs.append(ev)
    trace = {"traceEvents": evs}
    if path:
        with open(path, "w") as f:
            json.dump(trace, f)
    return trace


# -- flight recorder ------------------------------------------------------

class FlightRecorder:
    """Bounded ring of recent annotated events + span completions,
    dumped to disk with counter deltas when the process hits a fatal
    fault. One dump file per incident:
    ``<dir>/flight_<reason>_<pid>_<seq>.json``."""

    def __init__(self, capacity: int = 512):
        self._ring = deque(maxlen=max(16, capacity))
        self._lock = threading.Lock()
        self._dir: Optional[str] = None
        self._seq = 0
        self._base_counters = {}
        # named section providers: zero-arg callables evaluated at dump
        # time whose JSON-safe return value is embedded in the doc
        # (profiler/timeline.py attaches its last-N-windows view here,
        # so every black box carries the minutes before the incident)
        self._sections = {}

    def configure(self, directory: Optional[str]) -> None:
        self._dir = directory

    def attach(self, name: str, provider) -> None:
        """Register `provider` (zero-arg, JSON-safe return) to be
        evaluated and embedded as ``doc[name]`` in every future dump."""
        with self._lock:
            self._sections[name] = provider

    def detach(self, name: str) -> None:
        with self._lock:
            self._sections.pop(name, None)

    def note(self, kind: str, **payload) -> None:
        with self._lock:
            self._ring.append({"t": time.perf_counter(), "kind": kind,
                               **payload})

    def events(self):
        with self._lock:
            return list(self._ring)

    def _counter_deltas(self, snap: dict) -> dict:
        # read-modify-write on the delta baseline: two concurrent dumps
        # (e.g. a crash handler racing a periodic dump) would otherwise
        # double-count or drop deltas
        cur = snap.get("counters", {})
        deltas = {}
        with self._lock:
            for name, v in cur.items():
                d = v - self._base_counters.get(name, 0)
                if d:
                    deltas[name] = d
            self._base_counters = dict(cur)
        return deltas

    def dump(self, reason: str, path: Optional[str] = None,
             **meta) -> Optional[str]:
        """Write the black box. Returns the file path, or None when no
        destination is configured (PT_FLIGHT_DIR / set_flight_dir /
        explicit `path`). Never raises: a postmortem writer must not
        take down the crash handler that called it."""
        directory = None
        if path is None:
            directory = self._dir or os.environ.get("PT_FLIGHT_DIR")
            if not directory:
                return None
        try:
            snap = _metrics.snapshot()
            with self._lock:
                self._seq += 1
                seq = self._seq
                events = list(self._ring)
            doc = {
                "reason": reason,
                "ts": time.time(),
                "pid": os.getpid(),
                "meta": meta,
                "events": events,
                "spans": ring_spans(),
                "counter_deltas": self._counter_deltas(snap),
                "metrics": snap,
            }
            with self._lock:
                sections = dict(self._sections)
            for name, provider in sections.items():
                if name in doc:
                    continue
                try:
                    doc[name] = provider()
                except Exception:
                    doc[name] = {"error": "section provider failed"}
            if path is None:
                os.makedirs(directory, exist_ok=True)
                path = os.path.join(
                    directory, f"flight_{reason}_{os.getpid()}_{seq}.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            _m_dumps.inc()
            return path
        except (OSError, TypeError, ValueError):
            _m_dump_errors.inc()
            return None


flight = FlightRecorder()


def flight_note(kind: str, **payload) -> None:
    flight.note(kind, **payload)


def flight_dump(reason: str, **meta) -> Optional[str]:
    return flight.dump(reason, **meta)


def set_flight_dir(directory: Optional[str]) -> None:
    flight.configure(directory)
