"""Telemetry timeline: a bounded time-series ring over registry
snapshots, the time dimension PR 10's observability plane lacked.

`FleetAggregator` answers "what does the fleet look like NOW";
autoscaler rules, SLO attainment, and capacity plans all need "how did
it behave over the last five minutes".  A `Timeline` closes that gap:

  * **Periodic sampling.**  `sample()` snapshots the registry under an
    injectable clock (`clock=` — tests and the bench drive it with a
    synthetic step counter; nothing here reads wall-clock in a hot
    path) and appends one window record: cumulative counters, gauges,
    and the per-window histogram digests.
  * **Honest window quantiles.**  t-digests merge but do NOT subtract,
    so a trailing-window p95 cannot be derived by differencing
    cumulative sketches — instead every `Histogram` keeps a second,
    drainable window digest (`drain_window()`, metrics.py) that
    `sample()` collects, and `percentile(name, q, window_s)` MERGES the
    retained window sketches: real t-digest math over the window's
    observations, not an average of averages.
  * **Counter rates.**  `rate(name, window_s)` reads the cumulative
    counter delta between the window's boundary samples.
  * **Point events.**  Router/supervisor health transitions and
    brownout moves land via the module-level `emit_event` sink and ride
    inside the next window, so a postmortem sees "replica demoted"
    between the p95 spike and the burn alert.
  * **Crash spill.**  With `spill_dir` set, each window appends to a
    JSONL file and then republishes `MANIFEST.json` atomically
    (recovery.py's manifest-last discipline: the manifest counts the
    published windows, so `load_spill` replays exactly the complete
    prefix and a torn tail line is ignored).  `attach_flight()` also
    embeds the last N windows into every FlightRecorder dump.

Single consumer by design: `sample()` drains the registry's window
digests, so exactly one Timeline should own a given registry.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from . import metrics as _metrics
from . import tracing as _tracing
from .digest import QuantileDigest

__all__ = ["Timeline", "load_spill", "emit_event", "install",
           "uninstall", "SPILL_FILE"]

SPILL_FILE = "windows.jsonl"

_m_samples = _metrics.counter("timeline/samples")
_m_events = _metrics.counter("timeline/events")
_m_spilled = _metrics.counter("timeline/windows_spilled")
_m_spill_errors = _metrics.counter("timeline/spill_errors")

# module-level event sink: instrumented layers (router demotions, the
# brownout ladder) call emit_event without holding a Timeline reference;
# installed timelines fold the events into their next window
_sinks: List["Timeline"] = []
_sinks_lock = threading.Lock()


def install(tl: "Timeline") -> "Timeline":
    """Route subsequent `emit_event` calls into `tl` (idempotent)."""
    with _sinks_lock:
        if tl not in _sinks:
            _sinks.append(tl)
    return tl


def uninstall(tl: "Timeline") -> None:
    with _sinks_lock:
        if tl in _sinks:
            _sinks.remove(tl)


def emit_event(kind: str, **payload) -> None:
    """Record a point event (JSON-safe payload) on every installed
    timeline.  No-op (beyond a counter) when none is installed, so the
    emitting hot paths never grow a hard dependency."""
    _m_events.inc()
    with _sinks_lock:
        sinks = list(_sinks)
    for tl in sinks:
        tl.event(kind, **payload)


class Timeline:
    """Bounded in-memory ring of sampled windows + optional JSONL spill.

    tl = Timeline(clock=my_clock, spill_dir="/var/pt/timeline")
    tl.sample()                       # one window per call
    tl.rate("gateway/outcome/completed", window_s=60)
    tl.percentile("serving/ttft_ms", 0.95, window_s=60)
    """

    def __init__(self, registry=None,
                 clock: Callable[[], float] = time.monotonic,
                 capacity: int = 720, spill_dir: Optional[str] = None,
                 max_events: int = 4096):
        self.registry = registry if registry is not None \
            else _metrics.registry()
        self._clock = clock
        self._windows: deque = deque(maxlen=max(2, int(capacity)))
        self._pending_events: deque = deque(maxlen=max(16, int(max_events)))
        self._lock = threading.Lock()
        self._seq = 0
        self._spilled = 0
        self._spill_dir = spill_dir
        if spill_dir:
            os.makedirs(spill_dir, exist_ok=True)

    # -- ingestion --------------------------------------------------------
    def event(self, kind: str, **payload) -> None:
        """Queue a point event; it rides inside the next window."""
        with self._lock:
            self._pending_events.append(
                {"t": self._clock(), "kind": kind, **payload})

    def sample(self) -> dict:
        """Snapshot the registry into one window record: cumulative
        counters, gauges, drained per-window digests, queued events.
        The window's `t` is its END; it covers observations since the
        previous sample."""
        now = self._clock()
        snap = self.registry.snapshot()
        digests: Dict[str, dict] = {}
        for name in snap.get("histograms", {}):
            wd = self.registry.histogram(name).drain_window()
            if wd.count:
                digests[name] = wd.to_dict()
        with self._lock:
            self._seq += 1
            win = {"seq": self._seq, "t": now,
                   "counters": dict(snap.get("counters", {})),
                   "gauges": dict(snap.get("gauges", {})),
                   "digests": digests,
                   "events": list(self._pending_events)}
            self._pending_events.clear()
            self._windows.append(win)
        _m_samples.inc()
        if self._spill_dir:
            self._spill(win)
        return win

    # -- queries ----------------------------------------------------------
    def windows(self, window_s: Optional[float] = None,
                now: Optional[float] = None) -> List[dict]:
        """Retained windows, oldest first; `window_s` keeps only those
        ENDING within the trailing window (measured from the newest
        sample unless `now` is given)."""
        with self._lock:
            wins = list(self._windows)
        if window_s is None or not wins:
            return wins
        if now is None:
            now = wins[-1]["t"]
        return [w for w in wins if w["t"] >= now - window_s]

    def rate(self, name: str, window_s: Optional[float] = None,
             now: Optional[float] = None) -> Optional[float]:
        """Counter increments per second over the trailing window: the
        cumulative delta between the boundary samples (None until two
        samples exist)."""
        wins = self.windows(None, None)
        if now is not None:
            wins = [w for w in wins if w["t"] <= now]
        if len(wins) < 2:
            return None
        last = wins[-1]
        base = wins[0]
        if window_s is not None:
            t_cut = last["t"] - window_s
            for w in wins[:-1]:
                if w["t"] <= t_cut:
                    base = w
                else:
                    break
        dt = last["t"] - base["t"]
        if dt <= 0:
            return None
        return (last["counters"].get(name, 0)
                - base["counters"].get(name, 0)) / dt

    def percentile(self, name: str, q: float,
                   window_s: Optional[float] = None,
                   now: Optional[float] = None) -> Optional[float]:
        """Honest trailing-window quantile: merge the per-window
        digests covered by the window — t-digest math over the window's
        actual observation stream."""
        merged: Optional[QuantileDigest] = None
        for w in self.windows(window_s, now):
            d = w["digests"].get(name)
            if not d:
                continue
            part = QuantileDigest.from_dict(d)
            merged = part if merged is None else merged.merge(part)
        return merged.quantile(q) if merged is not None else None

    def series(self, name: str,
               window_s: Optional[float] = None) -> List[Tuple[float, float]]:
        """[(t, value)] per window for a gauge (falling back to the
        cumulative counter of the same name)."""
        out = []
        for w in self.windows(window_s):
            v = w["gauges"].get(name)
            if v is None:
                v = w["counters"].get(name)
            if v is not None:
                out.append((w["t"], v))
        return out

    def events(self, window_s: Optional[float] = None,
               kind: Optional[str] = None) -> List[dict]:
        out = []
        for w in self.windows(window_s):
            for ev in w.get("events", ()):
                if kind is None or ev.get("kind") == kind:
                    out.append(ev)
        return out

    def recent(self, n: int = 20) -> List[dict]:
        """The last `n` windows with digests summarized to quantiles —
        the compact view FlightRecorder dumps embed."""
        out = []
        for w in self.windows()[-max(1, n):]:
            dg = {}
            for name, d in w["digests"].items():
                part = QuantileDigest.from_dict(d)
                dg[name] = {"count": part.count,
                            "p50": part.quantile(0.5),
                            "p95": part.quantile(0.95),
                            "p99": part.quantile(0.99)}
            out.append({"seq": w["seq"], "t": w["t"],
                        "counters": w["counters"], "gauges": w["gauges"],
                        "digests": dg, "events": w["events"]})
        return out

    def attach_flight(self, n: int = 20, recorder=None) -> "Timeline":
        """Embed this timeline's last `n` windows in every future
        FlightRecorder dump (section key ``timeline``)."""
        rec = recorder if recorder is not None else _tracing.flight
        rec.attach("timeline", lambda: self.recent(n))
        return self

    # -- crash spill ------------------------------------------------------
    def _spill(self, win: dict) -> None:
        """Append-only JSONL + manifest-last: data line first, then the
        manifest republishes atomically with the published count.  A
        crash between the two leaves an unpublished tail line that
        `load_spill` ignores — the manifest IS the completeness
        marker."""
        from ..distributed.resilience import recovery as _recovery

        try:
            path = os.path.join(self._spill_dir, SPILL_FILE)
            with open(path, "a") as f:
                f.write(json.dumps(win) + "\n")
                f.flush()
                os.fsync(f.fileno())
            self._spilled += 1
            _recovery.publish_manifest(self._spill_dir, {
                "kind": "timeline", "windows": self._spilled,
                "last_seq": win["seq"], "last_t": win["t"]})
            _m_spilled.inc()
        except (OSError, TypeError, ValueError):
            _m_spill_errors.inc()


def load_spill(path: str) -> List[dict]:
    """Replay a timeline spill directory: the complete prefix of
    windows the manifest published.  Returns [] for a torn spill (no
    manifest); a trailing line written after the last manifest publish,
    or torn mid-write, is ignored."""
    from ..distributed.resilience import recovery as _recovery

    man = _recovery.read_manifest(path)
    if man is None:
        return []
    out: List[dict] = []
    published = int(man.get("windows", 0))
    try:
        f = open(os.path.join(path, SPILL_FILE))
    except OSError:
        return []
    with f:
        for line in f:
            if len(out) >= published:
                break
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                break          # torn line: nothing after it is trusted
    return out
