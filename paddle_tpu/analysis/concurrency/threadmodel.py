"""Class-level threading model shared by the PT7xx/PT8xx rules.

For every class in a module this builds a ``ClassModel``:

- **lock inventory** — attributes assigned ``threading.Lock()`` /
  ``RLock()`` / ``Condition()`` / ``Semaphore()`` (instance or class
  level), dict-of-locks attrs (``self._x[k] = Lock()`` /
  ``setdefault(k, Lock())``), and which lock a Condition wraps;
- **per-access held-lock sets** — every ``self.<attr>`` read/write in
  every method, annotated with the set of locks lexically held
  (``with self._lock:`` scopes, multi-item ``with`` included);
- **intra-class lock propagation** — a private helper whose in-class
  call sites ALL hold lock L is analyzed as running under L (the
  "called with self.cond held" docstring convention, made checkable);
- **thread entry points** — ``run()`` of ``threading.Thread``
  subclasses and any method passed as ``Thread(target=self.m)`` or a
  nested ``def`` passed as a target (tracked as pseudo-method
  ``outer.inner``), plus transitive reachability over ``self.m()``
  calls;
- **guard map** — attr -> the locks under which it is written outside
  ``__init__``: the inferred synchronization discipline the PT701
  checker holds every other access to;
- **acquisition graph** — lock -> lock edges for nested acquisitions
  (PT702 deadlock cycles), thread store/start/join events (PT703),
  and condition notify/wait sites (PT704).

Everything is module-local and stdlib-``ast`` only, matching the rest
of the ptlint engine: the analyzer never imports the code it checks.
"""
from __future__ import annotations

import ast
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from ..engine import call_name, dotted_name

__all__ = ["Access", "MethodModel", "ClassModel", "class_models",
           "module_thread_reachable"]

_LOCK_CTORS = {"Lock", "RLock", "Semaphore", "BoundedSemaphore"}
_COND_CTORS = {"Condition"}
_MUTATORS = {
    "append", "appendleft", "extend", "insert", "add", "discard",
    "remove", "pop", "popleft", "popitem", "clear", "update",
    "setdefault", "sort", "reverse",
}
# mutators whose return value is routinely used; the rest only count
# as writes in statement position (`rows = self.rule.update(...)` is an
# optimizer step, not a dict mutation)
_VALUE_MUTATORS = {"pop", "popleft", "popitem", "setdefault"}
_COND_OPS = {"notify", "notify_all", "wait", "wait_for"}
# join evidence: a literal join(), or delegating shutdown to the thread
# object itself (TCPStore.close() -> self._server.stop() which joins)
_JOINERS = {"join", "stop", "close", "shutdown", "disable", "terminate"}
# methods where unguarded writes are construction, not sharing
_CONSTRUCTION = {"__init__", "__new__", "__post_init__"}
# lifecycle roots from which a service thread's join() must be reachable
_LIFECYCLE_STEMS = ("close", "stop", "shutdown", "abort", "disable",
                    "drain", "terminate", "join", "__exit__", "__del__")
# methods whose start() of a stored thread demands join-on-close
_STARTER_STEMS = ("__init__", "start", "open", "enable", "run_forever")


class Access:
    """One ``self.<attr>`` read or write with its held-lock set."""

    __slots__ = ("attr", "write", "method", "line", "col", "held")

    def __init__(self, attr: str, write: bool, method: str,
                 line: int, col: int, held: FrozenSet[str]):
        self.attr = attr
        self.write = write
        self.method = method
        self.line = line
        self.col = col
        self.held = held


class MethodModel:
    """Per-method event log the class-level passes aggregate."""

    def __init__(self, name: str, node):
        self.name = name
        self.node = node
        self.accesses: List[Access] = []
        # (callee, held, line, col) for self.callee(...) calls
        self.calls: List[Tuple[str, FrozenSet[str], int, int]] = []
        # (lock, held_before, line, col) for each with-acquisition
        self.acquisitions: List[Tuple[str, FrozenSet[str], int, int]] = []
        # (cond_attr, op, held, line, col)
        self.cond_ops: List[Tuple[str, str, FrozenSet[str], int, int]] = []
        # thread lifecycle facts
        self.thread_attrs: Dict[str, Tuple[int, int]] = {}  # stored+line
        self.started_attrs: Set[str] = set()
        self.join_attrs: Set[str] = set()
        # nested defs passed as Thread targets resolve to pseudo-methods
        self.local_targets: Set[str] = set()


class ClassModel:
    def __init__(self, name: str, node: ast.ClassDef):
        self.name = name
        self.node = node
        self.lock_attrs: Set[str] = set()
        self.cond_attrs: Set[str] = set()
        self.lockdict_attrs: Set[str] = set()
        self.cond_wraps: Dict[str, str] = {}
        self.method_names: Set[str] = set()
        self.methods: Dict[str, MethodModel] = {}
        self.is_thread_subclass = False
        self.entries: Set[str] = set()
        self.thread_reachable: Set[str] = set()
        self.ctx_locks: Dict[str, FrozenSet[str]] = {}
        # attr -> guard locks / representative guarded write
        self.guard_map: Dict[str, FrozenSet[str]] = {}
        self.guard_sites: Dict[str, Access] = {}

    # -- derived views -----------------------------------------------------
    def effective_held(self, acc_or_held, method: str) -> FrozenSet[str]:
        held = acc_or_held.held if isinstance(acc_or_held, Access) \
            else acc_or_held
        return held | self.ctx_locks.get(method, frozenset())

    def accesses(self, attr: Optional[str] = None):
        for mm in self.methods.values():
            for a in mm.accesses:
                if attr is None or a.attr == attr:
                    yield a

    def lifecycle_methods(self) -> Set[str]:
        roots = {m for m in self.methods
                 if m.split(".")[0].startswith(_LIFECYCLE_STEMS)}
        return self._closure(roots)

    def _closure(self, roots: Set[str]) -> Set[str]:
        seen = set(roots)
        frontier = list(roots)
        while frontier:
            m = frontier.pop()
            mm = self.methods.get(m)
            if mm is None:
                continue
            for callee, _, _, _ in mm.calls:
                if callee in self.methods and callee not in seen:
                    seen.add(callee)
                    frontier.append(callee)
            # a nested def belongs to its container's flow
            for sub in self.methods:
                if sub.startswith(m + ".") and sub not in seen:
                    seen.add(sub)
                    frontier.append(sub)
        return seen


def _is_lock_ctor(node) -> Optional[str]:
    """'lock' / 'cond' when `node` constructs a threading primitive."""
    if not isinstance(node, ast.Call):
        return None
    name = call_name(node)
    if name in _COND_CTORS:
        return "cond"
    if name in _LOCK_CTORS:
        dn = dotted_name(node.func)
        if dn is None or dn == name or "." in dn:
            return "lock"
    return None


def _is_thread_ctor(node) -> bool:
    if not isinstance(node, ast.Call) or call_name(node) != "Thread":
        return False
    dn = dotted_name(node.func)
    return dn in ("Thread", "threading.Thread") or \
        (dn is not None and dn.endswith(".Thread"))


def _thread_target(node: ast.Call):
    for kw in node.keywords:
        if kw.arg == "target":
            return kw.value
    return None


def _self_attr(node) -> Optional[str]:
    """attr name for a `self.<attr>` Attribute node."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


class _MethodWalker:
    """Recursive walker threading the lexically-held lock set."""

    def __init__(self, cm: ClassModel, mm: MethodModel,
                 register, thread_classes: Set[str]):
        self.cm = cm
        self.mm = mm
        self.register = register          # registers pseudo-methods
        self.thread_classes = thread_classes
        self.local_threads: Set[str] = set()
        self.var_attr_alias: Dict[str, str] = {}   # v = self.T / loop var
        self.nested: Dict[str, str] = {}  # local def name -> pseudo name

    def _is_thread(self, node) -> bool:
        """threading.Thread(...) or a module-local Thread subclass."""
        if _is_thread_ctor(node):
            return True
        return isinstance(node, ast.Call) and \
            isinstance(node.func, ast.Name) and \
            node.func.id in self.thread_classes

    # -- lock-token matching ----------------------------------------------
    def _lock_tokens(self, expr) -> Optional[Tuple[str, FrozenSet[str]]]:
        """(primary_token, all_tokens) acquired by `with expr:`."""
        attr = _self_attr(expr)
        if attr is not None:
            if attr in self.cm.cond_attrs:
                toks = {attr}
                wrapped = self.cm.cond_wraps.get(attr)
                if wrapped:
                    toks.add(wrapped)
                return attr, frozenset(toks)
            if attr in self.cm.lock_attrs:
                return attr, frozenset({attr})
        if isinstance(expr, ast.Subscript):
            attr = _self_attr(expr.value)
            if attr is not None and attr in self.cm.lockdict_attrs:
                tok = attr + "[]"
                return tok, frozenset({tok})
        return None

    # -- the walk ----------------------------------------------------------
    def walk(self, node, held: FrozenSet[str]):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            added: Set[str] = set()
            for item in node.items:
                toks = self._lock_tokens(item.context_expr)
                if toks is not None:
                    primary, all_toks = toks
                    self.mm.acquisitions.append(
                        (primary, held | frozenset(added),
                         item.context_expr.lineno,
                         item.context_expr.col_offset))
                    added |= all_toks
                else:
                    self.walk(item.context_expr, held)
                if item.optional_vars is not None:
                    self.walk(item.optional_vars, held)
            for stmt in node.body:
                self.walk(stmt, held | frozenset(added))
            return

        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def: runs later, with no lock held at entry
            pseudo = f"{self.mm.name}.{node.name}"
            self.nested[node.name] = pseudo
            self.register(pseudo, node)
            return

        if isinstance(node, ast.For):
            # `for t in self._threads:` aliases t -> _threads for join()
            it_attr = next((a for a in ast.walk(node.iter)
                            if _self_attr(a) is not None), None)
            if it_attr is not None and isinstance(node.target, ast.Name):
                self.var_attr_alias[node.target.id] = _self_attr(it_attr)

        if isinstance(node, ast.Assign):
            self._handle_assign(node, held)
        elif isinstance(node, ast.Call):
            self._handle_call(node, held)
        elif isinstance(node, ast.Attribute):
            self._handle_attribute(node, held)

        for child in ast.iter_child_nodes(node):
            self.walk(child, held)

    # -- node handlers -----------------------------------------------------
    def _handle_assign(self, node: ast.Assign, held):
        is_thread = self._is_thread(node.value)
        src_name = node.value.id if isinstance(node.value, ast.Name) \
            else None
        for tgt in node.targets:
            attr = _self_attr(tgt)
            if attr is not None:
                if is_thread or (src_name in self.local_threads):
                    self.mm.thread_attrs[attr] = (node.lineno,
                                                  node.col_offset)
                    if src_name in self.local_threads:
                        self.var_attr_alias[src_name] = attr
                        if src_name in self.mm.started_attrs:
                            self.mm.started_attrs.add(attr)
                continue
            if isinstance(tgt, ast.Name):
                if is_thread:
                    self.local_threads.add(tgt.id)
                src_attr = _self_attr(node.value)
                if src_attr is not None:
                    self.var_attr_alias[tgt.id] = src_attr

    def _handle_call(self, node: ast.Call, held):
        fn = node.func
        name = call_name(node)

        if self._is_thread(node):
            target = _thread_target(node)
            t_attr = _self_attr(target) if target is not None else None
            if t_attr is not None:
                self.cm.entries.add(t_attr)
            elif isinstance(target, ast.Name):
                if target.id in self.nested:
                    self.cm.entries.add(self.nested[target.id])
                else:
                    self.mm.local_targets.add(target.id)
            return

        if isinstance(fn, ast.Attribute):
            # self.helper(...) — intra-class call (the callee is the
            # attribute of `fn` itself, not of its receiver)
            callee = _self_attr(fn)
            if callee is not None and callee in self.cm.method_names:
                self.mm.calls.append((callee, held, node.lineno,
                                      node.col_offset))
            recv = fn.value
            recv_attr = _self_attr(recv)
            # condition ops
            if name in _COND_OPS and recv_attr in self.cm.cond_attrs:
                self.mm.cond_ops.append(
                    (recv_attr, name, held, node.lineno, node.col_offset))
            # thread start/join bookkeeping
            if name == "start":
                if recv_attr is not None:
                    self.mm.started_attrs.add(recv_attr)
                elif isinstance(recv, ast.Name):
                    if recv.id in self.local_threads:
                        self.mm.started_attrs.add(
                            self.var_attr_alias.get(recv.id, recv.id))
            elif name in _JOINERS:
                if recv_attr is not None:
                    self.mm.join_attrs.add(recv_attr)
                elif isinstance(recv, ast.Name) and \
                        recv.id in self.var_attr_alias:
                    self.mm.join_attrs.add(self.var_attr_alias[recv.id])
            # self._threads.append(t) with t a local Thread
            elif name in ("append", "add"):
                holder = _self_attr(recv)
                if holder is not None and any(
                        isinstance(a, ast.Name) and
                        a.id in self.local_threads for a in node.args):
                    self.mm.thread_attrs.setdefault(
                        holder, (node.lineno, node.col_offset))
                    for a in node.args:
                        if isinstance(a, ast.Name) and \
                                a.id in self.local_threads:
                            self.var_attr_alias[a.id] = holder
                            if a.id in self.mm.started_attrs:
                                self.mm.started_attrs.add(holder)

    def _handle_attribute(self, node: ast.Attribute, held):
        attr = _self_attr(node)
        if attr is None:
            return
        cm = self.cm
        if attr in cm.lock_attrs or attr in cm.cond_attrs or \
                attr in cm.lockdict_attrs or attr in cm.method_names:
            return
        parent = getattr(node, "_pt_parent", None)
        write = isinstance(node.ctx, (ast.Store, ast.Del))
        if not write and isinstance(parent, ast.Subscript) and \
                parent.value is node and \
                isinstance(parent.ctx, (ast.Store, ast.Del)):
            write = True
        if not write and isinstance(parent, ast.Attribute) and \
                parent.value is node and parent.attr in _MUTATORS:
            gp = getattr(parent, "_pt_parent", None)
            if isinstance(gp, ast.Call) and gp.func is parent:
                ggp = getattr(gp, "_pt_parent", None)
                if parent.attr in _VALUE_MUTATORS or \
                        isinstance(ggp, ast.Expr):
                    write = True
        self.mm.accesses.append(Access(
            attr, write, self.mm.name, node.lineno, node.col_offset, held))


def _scan_primitives(cm: ClassModel, cls: ast.ClassDef):
    """Find lock/cond/lock-dict attributes anywhere in the class."""
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign):
            kind = _is_lock_ctor(node.value)
            for tgt in node.targets:
                attr = _self_attr(tgt)
                if attr is None and isinstance(tgt, ast.Name):
                    attr = tgt.id      # class-level `_lock = Lock()`
                if attr is not None and kind is not None:
                    (cm.cond_attrs if kind == "cond"
                     else cm.lock_attrs).add(attr)
                    if kind == "cond" and isinstance(node.value, ast.Call) \
                            and node.value.args:
                        wrapped = _self_attr(node.value.args[0])
                        if wrapped:
                            cm.cond_wraps[attr] = wrapped
                # dict-of-locks: self._x[k] = Lock()
                if kind == "lock" and isinstance(tgt, ast.Subscript):
                    holder = _self_attr(tgt.value)
                    if holder:
                        cm.lockdict_attrs.add(holder)
        elif isinstance(node, ast.Call) and \
                call_name(node) == "setdefault" and node.args:
            if len(node.args) >= 2 and _is_lock_ctor(node.args[1]):
                holder = _self_attr(node.func.value) \
                    if isinstance(node.func, ast.Attribute) else None
                if holder:
                    cm.lockdict_attrs.add(holder)
    # locks are not shared state; neither are the dict holders
    cm.lockdict_attrs -= cm.lock_attrs | cm.cond_attrs


def _build_class(mod, cls: ast.ClassDef,
                 thread_classes: Set[str]) -> ClassModel:
    cm = ClassModel(cls.name, cls)
    for base in cls.bases:
        dn = dotted_name(base)
        if dn and dn.split(".")[-1] == "Thread":
            cm.is_thread_subclass = True
    cm.method_names = {n.name for n in cls.body
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))}
    _scan_primitives(cm, cls)

    pending = [(n.name, n) for n in cls.body
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    while pending:
        mname, fnode = pending.pop(0)
        mm = MethodModel(mname, fnode)
        cm.methods[mname] = mm
        walker = _MethodWalker(
            cm, mm, lambda pname, pnode: pending.append((pname, pnode)),
            thread_classes)
        for stmt in fnode.body:
            walker.walk(stmt, frozenset())

    if cm.is_thread_subclass and "run" in cm.methods:
        cm.entries.add("run")

    _propagate_ctx(cm)
    cm.thread_reachable = cm._closure(set(cm.entries))
    _infer_guard_map(cm)
    return cm


def _propagate_ctx(cm: ClassModel):
    """Fixpoint: a private helper whose in-class call sites all hold L
    runs under L.  Entries and public methods are callable from
    anywhere, so their incoming context stays empty."""
    sites: Dict[str, List[Tuple[str, FrozenSet[str]]]] = {}
    for mname, mm in cm.methods.items():
        for callee, held, _, _ in mm.calls:
            sites.setdefault(callee, []).append((mname, held))
    ctx = {m: frozenset() for m in cm.methods}
    for _ in range(8):
        changed = False
        for m in cm.methods:
            if not m.startswith("_") or m.startswith("__") or \
                    m in cm.entries or "." in m:
                continue
            callers = sites.get(m)
            if not callers:
                continue
            new: Optional[FrozenSet[str]] = None
            for caller, held in callers:
                eff = held | ctx.get(caller, frozenset())
                new = eff if new is None else (new & eff)
            new = new or frozenset()
            if new != ctx[m]:
                ctx[m] = new
                changed = True
        if not changed:
            break
    cm.ctx_locks = ctx


def _infer_guard_map(cm: ClassModel):
    by_attr: Dict[str, List[Access]] = {}
    for a in cm.accesses():
        by_attr.setdefault(a.attr, []).append(a)
    for attr, accs in by_attr.items():
        guards: Set[str] = set()
        site: Optional[Access] = None
        for a in accs:
            if not a.write or a.method.split(".")[0] in _CONSTRUCTION:
                continue
            eff = cm.effective_held(a, a.method)
            if eff:
                guards |= eff
                if site is None:
                    site = a
        if guards and site is not None:
            cm.guard_map[attr] = frozenset(guards)
            cm.guard_sites[attr] = site


def class_models(mod) -> List[ClassModel]:
    """All ClassModels for a ModuleInfo, cached on the module."""
    cached = getattr(mod, "_pt_class_models", None)
    if cached is not None:
        return cached
    classes = [node for node in ast.walk(mod.tree)
               if isinstance(node, ast.ClassDef)]
    # module-local Thread subclasses count as thread ctors (transitive:
    # a subclass of a local subclass is still a thread)
    thread_classes: Set[str] = set()
    for _ in range(3):
        for cls in classes:
            for base in cls.bases:
                dn = dotted_name(base)
                if dn and (dn.split(".")[-1] == "Thread" or
                           dn in thread_classes):
                    thread_classes.add(cls.name)
    models = [_build_class(mod, node, thread_classes) for node in classes]
    mod._pt_class_models = models
    return models


def module_thread_reachable(mod) -> Set[str]:
    """Module-level functions reachable from a bare
    ``Thread(target=fn)`` — the module-function analogue of a class's
    thread-reachable set."""
    cached = getattr(mod, "_pt_mod_thread_reachable", None)
    if cached is not None:
        return cached
    roots: Set[str] = set()
    for node in ast.walk(mod.tree):
        if _is_thread_ctor(node):
            target = _thread_target(node)
            if isinstance(target, ast.Name):
                roots.add(target.id)
    seen = set(r for r in roots if r in mod.functions)
    frontier = list(seen)
    while frontier:
        fn = mod.functions.get(frontier.pop())
        if fn is None:
            continue
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Name) and \
                    node.func.id in mod.functions and \
                    node.func.id not in seen:
                seen.add(node.func.id)
                frontier.append(node.func.id)
    mod._pt_mod_thread_reachable = seen
    return seen
