"""ptrace — concurrency + fleet-protocol static analysis (PT7xx/PT8xx).

Two rule families on top of the ptlint engine:

- **PT7xx lock-consistency races** (``race_rules``): infers each
  class's *guard map* — which attributes are written under which
  ``with self._lock:`` scope — then flags accesses that skip the
  guard, lock-order cycles, never-joined service threads, and
  condition ops outside the condition's lock.  The model
  (``threadmodel``) is RacerD-shaped: lock *consistency* proven from
  source, no happens-before runtime needed.
- **PT8xx fleet-protocol invariants** (``protocol_rules``): the
  hand-maintained conventions the fleet tier's correctness rests on —
  manifest-last persistence, hand-off payload identity keys
  (salt/trace/weight-version), generation-fenced store writes, atomic
  metrics updates from threads.

Run with ``python -m paddle_tpu.analysis --conc`` or the jax-free
``tools/ptrace.py``; both share the ptlint baseline/SARIF/CI
machinery.
"""
