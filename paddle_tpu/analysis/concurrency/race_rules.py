"""PT7xx — lock-consistency race detection over the class threading
model (threadmodel.py).

The guard map is the inferred synchronization discipline: if a class
writes ``self._msgs`` under ``with self._cond:`` in one method, every
other read/write of ``_msgs`` is held to that discipline.  This is the
RacerD framing — prove lock *consistency* from source, don't wait for
a happens-before violation at runtime; PR 5's "dup-frame counter race"
(``_seen_fseq`` mutated from recv threads without ``_seen_lock``) is
exactly the shape PT701 flags.

- PT701  guarded attribute accessed without its guard
- PT702  lock-order cycle across methods (potential deadlock)
- PT703  service thread started but never joined from close()/stop()
- PT704  Condition notify/wait outside the condition's lock
"""
from __future__ import annotations

from typing import Dict, List, Tuple

from ..engine import rule
from .threadmodel import (_CONSTRUCTION, _LIFECYCLE_STEMS, _STARTER_STEMS,
                          class_models)


@rule("PT701", "error",
      "attribute accessed without the lock that guards its writes")
def check_lock_consistency(mod):
    for cm in class_models(mod):
        for attr, guards in sorted(cm.guard_map.items()):
            accs = list(cm.accesses(attr))
            threaded = bool(cm.entries)
            shared = any(a.method in cm.thread_reachable for a in accs)
            if threaded and not shared:
                # visible threads never touch this attr: the guard is
                # protecting against something we can't see — leave it
                # to the consistency tier below only when lock-only
                continue
            # double-checked-locking allowance: a method that also
            # takes the guard for this attr re-validates its unguarded
            # read under the lock (MetricsRegistry._get pattern)
            guarded_methods = {
                a.method for a in accs
                if cm.effective_held(a, a.method) & guards}
            site = cm.guard_sites[attr]
            guard_name = "/".join(f"self.{g}" for g in sorted(guards))
            for a in accs:
                if a.method.split(".")[0] in _CONSTRUCTION:
                    continue
                if cm.effective_held(a, a.method) & guards:
                    continue
                if a.method in guarded_methods:
                    continue
                via = ""
                if a.method in cm.thread_reachable and cm.entries:
                    ent = sorted(cm.entries)[0]
                    via = (f"; '{a.method}()' is reachable from thread "
                           f"entry '{ent}()'")
                verb = "written" if a.write else "read"
                yield (a.line, a.col,
                       f"'{cm.name}.{attr}' is written under "
                       f"{guard_name} ('{site.method}()' line "
                       f"{site.line}) but {verb} here without it{via}",
                       ((mod.relpath, site.line,
                         f"guarded write of '{attr}' in "
                         f"'{site.method}()'"),))


def _find_cycles(edges: Dict[str, Dict[str, Tuple[int, int, str]]]
                 ) -> List[List[str]]:
    """Elementary cycles (length <= 4) in the acquisition graph,
    deduplicated by lock set."""
    seen = set()
    out: List[List[str]] = []
    for start in sorted(edges):
        stack = [(start, [start])]
        while stack:
            node, path = stack.pop()
            for nxt in sorted(edges.get(node, ())):
                if nxt == start and len(path) > 1:
                    key = frozenset(path)
                    if key not in seen:
                        seen.add(key)
                        out.append(path)
                elif nxt not in path and len(path) < 4:
                    stack.append((nxt, path + [nxt]))
    return out


@rule("PT702", "warning",
      "lock-order cycle across methods (potential deadlock)")
def check_lock_order(mod):
    for cm in class_models(mod):
        edges: Dict[str, Dict[str, Tuple[int, int, str]]] = {}
        for mname, mm in cm.methods.items():
            for lock, held, line, col in mm.acquisitions:
                for h in cm.effective_held(held, mname):
                    if h == lock:
                        continue
                    # a Condition and the lock it wraps are one lock
                    if cm.cond_wraps.get(h) == lock or \
                            cm.cond_wraps.get(lock) == h:
                        continue
                    edges.setdefault(h, {}).setdefault(
                        lock, (line, col, mname))
        for cycle in _find_cycles(edges):
            sites = []
            for i, lk in enumerate(cycle):
                nxt = cycle[(i + 1) % len(cycle)]
                line, col, mname = edges[lk][nxt]
                sites.append((lk, nxt, line, col, mname))
            order = " -> ".join(cycle + [cycle[0]])
            related = tuple(
                (mod.relpath, s[2],
                 f"acquires 'self.{s[1]}' while holding 'self.{s[0]}' "
                 f"in '{s[4]}()'") for s in sites)
            yield (sites[0][2], sites[0][3],
                   f"lock-order cycle in class '{cm.name}': {order} — "
                   f"two threads taking these locks in different "
                   f"orders deadlock", related)


@rule("PT703", "warning",
      "service thread started but never joined from a lifecycle method")
def check_thread_join(mod):
    for cm in class_models(mod):
        stored: Dict[str, Tuple[int, int]] = {}
        for mm in cm.methods.values():
            for attr, lc in mm.thread_attrs.items():
                stored.setdefault(attr, lc)
        if not stored:
            continue
        lifecycle = cm.lifecycle_methods()
        joined = set()
        for m in lifecycle:
            joined |= cm.methods[m].join_attrs
        has_lifecycle = any(
            m.split(".")[0].startswith(_LIFECYCLE_STEMS)
            for m in cm.methods)
        start_sites: Dict[str, str] = {}
        for mname, mm in cm.methods.items():
            for attr in mm.started_attrs:
                start_sites.setdefault(attr, mname)
        for attr, smethod in sorted(start_sites.items()):
            if attr not in stored:
                continue          # fire-and-forget local, not stored
            if not smethod.split(".")[0].startswith(_STARTER_STEMS):
                continue
            if attr in joined:
                continue
            line, col = stored[attr]
            hint = ("no close()/stop()/abort() method exists to join "
                    "it from" if not has_lifecycle else
                    "no join() (or delegated stop()/close()) on it is "
                    "reachable from close()/stop()/abort()")
            yield (line, col,
                   f"thread '{cm.name}.{attr}' is started in "
                   f"'{smethod}()' but {hint} — the thread outlives "
                   f"the object and shutdown is nondeterministic")


@rule("PT704", "error",
      "Condition notify/wait outside the condition's lock")
def check_condition_discipline(mod):
    for cm in class_models(mod):
        for mname, mm in cm.methods.items():
            for cond, op, held, line, col in mm.cond_ops:
                eff = cm.effective_held(held, mname)
                if cond in eff:
                    continue
                wrapped = cm.cond_wraps.get(cond)
                if wrapped and wrapped in eff:
                    continue
                yield (line, col,
                       f"'self.{cond}.{op}()' called without holding "
                       f"'with self.{cond}:' — raises RuntimeError at "
                       f"runtime and loses wakeups")
