"""PT8xx — fleet-protocol invariant checks (distributed/, inference/,
profiler/).

These encode the hand-maintained conventions the fleet tier's
correctness rests on — each one retrofitted by hand at least once
before it became a rule:

- PT801  manifest-last discipline: payload files must be durable
  BEFORE ``publish_manifest`` republishes the completeness marker; a
  write after the publish re-opens the torn-state window recovery.py
  closed.
- PT802  hand-off payload completeness: a request/weight-set dict that
  crosses a process boundary must carry its identity — ``salt_rid`` /
  ``salt_seed`` (bitwise replay), a weight-version pin, and a trace
  context (``tracing.inject`` or a ``trace`` key).  PRs 10/11/15 each
  had to retrofit one of these.
- PT803  ``fenced_set`` without a generation derived from the
  supervisor epoch: a literal (or missing) ``gen`` defeats the fence —
  a zombie from generation N-1 could still win the write.
- PT804  read-modify-write on a metrics instrument
  (``g.set(g.value + d)``) from thread-reachable code: ``.set`` is
  last-write-wins, so concurrent increments are lost; ``.inc(d)`` is
  the atomic form.
"""
from __future__ import annotations

import ast
import re
from typing import Optional

from ..engine import call_name, dotted_name, rule
from .threadmodel import class_models, module_thread_reachable

_SCOPED_DIRS = ("distributed/", "inference/", "profiler/")


def _in_scope(mod) -> bool:
    path = mod.relpath.replace("\\", "/")
    return any(d in path for d in _SCOPED_DIRS)


def _body_walk(fn):
    """Walk a function body without descending into nested defs —
    the enclosing function's own control flow only."""
    stack = list(fn.body)
    while stack:
        n = stack.pop(0)
        yield n
        for c in ast.iter_child_nodes(n):
            if not isinstance(c, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                stack.append(c)


def _functions(mod):
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


# ---------------------------------------------------------------------------
# PT801 — manifest-last discipline
# ---------------------------------------------------------------------------

_MANIFEST_CALLS = {"publish_manifest", "write_manifest"}
_WRITE_CALLS = {"savez", "savez_compressed", "tofile",
                "copyfile", "copy2", "copytree"}


def _is_payload_write(node: ast.Call) -> bool:
    name = call_name(node)
    if name == "open":
        mode: Optional[str] = None
        if len(node.args) >= 2 and isinstance(node.args[1], ast.Constant) \
                and isinstance(node.args[1].value, str):
            mode = node.args[1].value
        for kw in node.keywords:
            if kw.arg == "mode" and isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, str):
                mode = kw.value.value
        return mode is not None and any(c in mode for c in "wax")
    if name in _WRITE_CALLS:
        return True
    if name == "save":
        dn = dotted_name(node.func)
        return dn in ("np.save", "numpy.save")
    return False


@rule("PT801", "error",
      "payload file written AFTER the manifest publish (manifest-last "
      "discipline violated)")
def check_manifest_last(mod):
    if not _in_scope(mod):
        return
    for fn in _functions(mod):
        manifests = [n for n in _body_walk(fn)
                     if isinstance(n, ast.Call) and
                     call_name(n) in _MANIFEST_CALLS]
        if not manifests:
            continue
        for n in _body_walk(fn):
            if not (isinstance(n, ast.Call) and _is_payload_write(n)):
                continue
            prior = [m for m in manifests if m.lineno < n.lineno]
            if not prior:
                continue
            m = prior[-1]
            yield (n.lineno, n.col_offset,
                   f"payload write after the manifest publish (line "
                   f"{m.lineno}) in '{fn.name}()' — a crash between "
                   f"them leaves a manifest that claims data that "
                   f"isn't durable; write payloads first, publish the "
                   f"manifest last",
                   ((mod.relpath, m.lineno,
                     f"manifest published here in '{fn.name}()'"),))


# ---------------------------------------------------------------------------
# PT802 — hand-off payload completeness
# ---------------------------------------------------------------------------

_HANDOFF_FN_RE = re.compile(
    r"migrate|requeue|hand_?off|receive|publish|send", re.IGNORECASE)
_TRANSPORT_CALLS = {"send", "sendall", "dumps"}


def _str_keys(d: ast.Dict):
    keys = set()
    for k in d.keys:
        if k is None:
            return None          # **spread: completeness unknowable
        if isinstance(k, ast.Constant) and isinstance(k.value, str):
            keys.add(k.value)
    return keys


@rule("PT802", "error",
      "cross-process hand-off payload is missing required identity keys")
def check_handoff_payload(mod):
    if not _in_scope(mod):
        return
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Dict):
            continue
        keys = _str_keys(node)
        if keys is None:
            continue
        fn = mod.enclosing_function(node)
        fname = fn.name if fn is not None else ""
        handoffy = bool(_HANDOFF_FN_RE.search(fname)) or (
            fn is not None and any(
                isinstance(n, ast.Call) and
                call_name(n) in _TRANSPORT_CALLS
                for n in ast.walk(fn)))
        if not handoffy:
            continue
        missing = []
        if "prompt" in keys and keys & {"sampling", "generated",
                                        "max_new"}:
            # request hand-off dict (migration / drain-requeue)
            for req in ("salt_rid", "salt_seed"):
                if req not in keys:
                    missing.append(req)
            if not any("version" in k for k in keys):
                missing.append("weight_version (pin)")
            has_inject = fn is not None and any(
                isinstance(n, ast.Call) and call_name(n) == "inject"
                for n in ast.walk(fn))
            if "trace" not in keys and not has_inject:
                missing.append("trace (tracing.inject)")
            kind = "request hand-off"
        elif "dtypes" in keys and "shapes" in keys:
            # weight-set meta (live weight publishing)
            missing = [k for k in ("version", "crcs") if k not in keys]
            kind = "weight-set meta"
        else:
            continue
        if missing:
            yield (node.lineno, node.col_offset,
                   f"{kind} payload in '{fname}()' is missing "
                   f"{', '.join(missing)} — the receiving side can't "
                   f"reproduce identity (salted sampling / weight "
                   f"pin / trace join) without them")


# ---------------------------------------------------------------------------
# PT803 — generation-fenced store writes
# ---------------------------------------------------------------------------

@rule("PT803", "error",
      "fenced_set without a generation derived from the supervisor epoch")
def check_fenced_generation(mod):
    if not _in_scope(mod):
        return
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Call) and
                call_name(node) == "fenced_set"):
            continue
        fn = mod.enclosing_function(node)
        if fn is not None and fn.name == "fenced_set":
            continue             # the definition/forwarder itself
        gen = node.args[3] if len(node.args) >= 4 else None
        for kw in node.keywords:
            if kw.arg == "gen":
                gen = kw.value
        if gen is None:
            yield (node.lineno, node.col_offset,
                   "fenced_set called without a generation argument — "
                   "the write bypasses the fence entirely")
        elif isinstance(gen, ast.Constant) and \
                isinstance(gen.value, (int, float)) and \
                not isinstance(gen.value, bool):
            yield (node.lineno, node.col_offset,
                   f"fenced_set generation is the literal "
                   f"{gen.value!r} — derive it from the supervisor "
                   f"epoch (generation()/reserve gen) or a zombie "
                   f"from an older generation can still win the write")


# ---------------------------------------------------------------------------
# PT804 — atomic metrics updates from threads
# ---------------------------------------------------------------------------

def _rmw_set_sites(fn_node):
    for node in _body_walk(fn_node):
        if not (isinstance(node, ast.Call) and
                isinstance(node.func, ast.Attribute) and
                node.func.attr == "set"):
            continue
        recv = dotted_name(node.func.value)
        if recv is None:
            continue
        for arg in node.args:
            for sub in ast.walk(arg):
                if isinstance(sub, ast.Attribute) and \
                        sub.attr == "value" and \
                        dotted_name(sub.value) == recv:
                    yield node, recv
                    break


@rule("PT804", "warning",
      "non-atomic read-modify-write on a metrics instrument from "
      "thread-reachable code")
def check_atomic_metrics(mod):
    if not _in_scope(mod):
        return
    emitted = set()

    def emit(node, recv, where):
        if id(node) in emitted:
            return None
        emitted.add(id(node))
        return (node.lineno, node.col_offset,
                f"'{recv}.set({recv}.value + ...)' in {where} is "
                f"last-write-wins: concurrent updates are lost — use "
                f"the atomic '{recv}.inc(delta)' instead")

    for cm in class_models(mod):
        for mname in sorted(cm.thread_reachable):
            mm = cm.methods.get(mname)
            if mm is None:
                continue
            for node, recv in _rmw_set_sites(mm.node):
                out = emit(node, recv,
                           f"thread-reachable '{cm.name}.{mname}()'")
                if out:
                    yield out
    for fname in sorted(module_thread_reachable(mod)):
        fn = mod.functions.get(fname)
        if fn is None:
            continue
        for node, recv in _rmw_set_sites(fn):
            out = emit(node, recv, f"thread-target '{fname}()'")
            if out:
                yield out
