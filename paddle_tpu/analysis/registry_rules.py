"""PT4xx — registry and observability consistency.

The op registry (ops/registry.py) is a name -> jax-function table and
the *entire* dispatch story on TPU: `register()` happily overwrites, so
a duplicate name is a silent kernel replacement decided by import order
(PT401).  Everything registered is eventually called through the
dispatcher funnel `core.dispatch.apply(fn, *tensor_args)`, so an entry
whose signature cannot take a single positional argument — or that is a
generator — can never be dispatched (PT402).

PT403 guards the observability contract from the other side: every
metric name emitted in code must be declared in
``tools/trace_report.py``'s ``KNOWN_METRICS`` (the set the triage
report and the README document).  A counter that isn't in the known set
is invisible to the tooling — exactly the drift the README's
one-source-of-truth policy exists to prevent.  Dynamic names (f-strings,
concatenation) are out of static reach and are covered by the ``*``
patterns in the known set.

PT404 extends the same policy to trace spans: the names passed to the
tracing helpers (``tracing.span`` / ``tracing.record_span`` /
``RecordEvent``) must be literal strings.  Span names are the join key
for everything downstream — the flight recorder's counter deltas, the
chrome-trace merge in ``tools/trace_report.py``, and the span summary
table all aggregate BY NAME — so a name built at runtime (f-string per
request, concatenated ids) explodes the cardinality of every one of
those views and makes cross-host merges meaningless.  Variable data
belongs in the span's ``args``, not its name.  A literal family prefix
(``RecordEvent("op::" + name)``) is allowed — the prefix keeps the
family aggregatable, the same escape hatch the ``*`` patterns give
KNOWN_METRICS.
"""
from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Tuple

from .engine import call_name, match_known, rule

_METRIC_FACTORIES = {"counter", "gauge", "histogram"}
_METRIC_EMITTERS = {"inc", "set_gauge", "observe"}


# ---------------------------------------------------------------------------
# registration extraction (static)
# ---------------------------------------------------------------------------

def _literal_all(mod) -> List[str]:
    """Module __all__ when it is a literal list/tuple of strings."""
    for node in mod.tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__":
                    v = node.value
                    if isinstance(v, (ast.List, ast.Tuple)):
                        return [e.value for e in v.elts
                                if isinstance(e, ast.Constant)
                                and isinstance(e.value, str)]
    return []


def _register_wrappers(mod) -> set:
    """Local functions that forward their first parameter as the name of
    a register() call (e.g. ops/nn_compat.py `_reg`)."""
    out = set()
    for name, fn in mod.functions.items():
        params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
        if not params:
            continue
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and \
                    call_name(node) == "register" and node.args and \
                    isinstance(node.args[0], ast.Name) and \
                    node.args[0].id == params[0]:
                out.add(name)
                break
    return out


def _loop_values_for(mod, call: ast.Call, var: str) -> List[str]:
    """String values `var` takes when it is the target (or a member of a
    tuple target) of an enclosing literal-iterable For loop."""
    node = call
    while node is not None:
        node = getattr(node, "_pt_parent", None)
        if not isinstance(node, ast.For):
            continue
        target, it = node.target, node.iter
        pos = None
        if isinstance(target, ast.Name) and target.id == var:
            pos = -1                      # scalar target
        elif isinstance(target, ast.Tuple):
            for i, el in enumerate(target.elts):
                if isinstance(el, ast.Name) and el.id == var:
                    pos = i
        if pos is None:
            continue
        if isinstance(it, ast.Name) and it.id == "__all__":
            return list(_literal_all(mod)) if pos == -1 else []
        if not isinstance(it, (ast.List, ast.Tuple)):
            return []
        vals = []
        for el in it.elts:
            if pos == -1:
                if isinstance(el, ast.Constant) and \
                        isinstance(el.value, str):
                    vals.append(el.value)
            elif isinstance(el, (ast.Tuple, ast.List)) and \
                    pos < len(el.elts):
                item = el.elts[pos]
                if isinstance(item, ast.Constant) and \
                        isinstance(item.value, str):
                    vals.append(item.value)
        return vals
    return []


def _registrations(mod) -> List[Tuple[str, ast.Call, Optional[str]]]:
    """(op_name, call_node, fn_source_name) triples statically provable
    in this module. fn_source_name is the module-level function the
    second argument resolves to ('<same>' when it equals op_name via
    globals()[var])."""
    if mod.relpath.endswith("ops/registry.py"):
        return []       # the definition site, not a user
    wrappers = _register_wrappers(mod)
    reg_names = {"register"} | wrappers
    out = []
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Call)
                and call_name(node) in reg_names and node.args):
            continue
        # inside a wrapper definition, the register(name, ...) call's
        # name is the wrapper's parameter — skip; the wrapper's callers
        # are the real registration sites
        fn = mod.enclosing_function(node)
        if fn is not None and fn.name in wrappers and \
                call_name(node) == "register":
            continue
        name_arg = node.args[0]
        fn_src = _fn_source(node, name_arg)
        if isinstance(name_arg, ast.Constant) and \
                isinstance(name_arg.value, str):
            out.append((name_arg.value, node, fn_src))
        elif isinstance(name_arg, ast.Name):
            for v in _loop_values_for(mod, node, name_arg.id):
                out.append((v, node, fn_src))
    return out


def _fn_source(call: ast.Call, name_arg) -> Optional[str]:
    """How the registered callable is named: a plain Name, or '<same>'
    for the globals()[<name var>] idiom (fn name == op name)."""
    if len(call.args) < 2:
        return None
    fn_arg = call.args[1]
    if isinstance(fn_arg, ast.Name):
        return fn_arg.id
    if isinstance(fn_arg, ast.Subscript) and \
            isinstance(fn_arg.value, ast.Call) and \
            call_name(fn_arg.value) == "globals" and \
            isinstance(name_arg, ast.Name):
        sl = fn_arg.slice
        if isinstance(sl, ast.Name) and sl.id == name_arg.id:
            return "<same>"
    return None


@rule("PT401", "error",
      "duplicate op registration: register() overwrites silently, the "
      "surviving kernel is decided by import order", scope="project")
def check_duplicate_registrations(project):
    seen: Dict[str, Tuple[str, int]] = {}
    for mod in project.modules:
        for name, call, _src in _registrations(mod):
            prev = seen.get(name)
            here = (mod.relpath, call.lineno)
            if prev is not None and prev != here:
                yield (mod, call.lineno, call.col_offset,
                       f"op '{name}' registered here and at "
                       f"{prev[0]}:{prev[1]}; register() overwrites "
                       f"silently — rename one or drop the loser")
            else:
                seen[name] = here


def _signature_problem(fn) -> Optional[str]:
    """Why this def can't be called through apply(fn, *tensors)."""
    a = fn.args
    n_pos = len(a.posonlyargs) + len(a.args)
    if n_pos == 0 and a.vararg is None:
        return "takes no positional arguments, so apply(fn, tensor) " \
               "cannot pass the operand"
    required_kwonly = [p.arg for p, d in zip(a.kwonlyargs, a.kw_defaults)
                       if d is None]
    if required_kwonly:
        return (f"has required keyword-only parameter(s) "
                f"{required_kwonly} the dispatcher funnel never passes")
    for node in ast.walk(fn):
        if isinstance(node, (ast.Yield, ast.YieldFrom)):
            owner = node
            while owner is not None and not isinstance(
                    owner, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
                owner = getattr(owner, "_pt_parent", None)
            if owner is fn:
                return "is a generator; generators cannot be traced " \
                       "through the dispatch funnel"
    return None


@rule("PT402", "error",
      "registered op whose signature cannot satisfy the dispatcher "
      "funnel (core.dispatch.apply)")
def check_registered_signatures(mod):
    for name, call, fn_src in _registrations(mod):
        if fn_src is None:
            continue
        target_name = name if fn_src == "<same>" else fn_src
        fn = mod.functions.get(target_name)
        if fn is None:
            continue
        problem = _signature_problem(fn)
        if problem:
            yield (call.lineno, call.col_offset,
                   f"registered op '{name}' -> {target_name}() "
                   f"{problem}")


# ---------------------------------------------------------------------------
# PT403 — metric names vs tools/trace_report.py KNOWN_METRICS
# ---------------------------------------------------------------------------

def _find_known_metrics(start_path: str) -> Optional[Tuple[str, List[str]]]:
    """Walk up from a module path for tools/trace_report.py and pull its
    KNOWN_METRICS literal (statically — the linter imports nothing)."""
    cur = os.path.dirname(os.path.abspath(start_path))
    for _ in range(12):
        cand = os.path.join(cur, "tools", "trace_report.py")
        if os.path.isfile(cand):
            try:
                tree = ast.parse(open(cand, encoding="utf-8").read())
            except SyntaxError:
                return None
            for node in tree.body:
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Name) and \
                                t.id == "KNOWN_METRICS":
                            v = node.value
                            if isinstance(v, ast.Call) and v.args:
                                v = v.args[0]   # frozenset({...})
                            if isinstance(v, (ast.Set, ast.List,
                                              ast.Tuple)):
                                return cand, [
                                    e.value for e in v.elts
                                    if isinstance(e, ast.Constant)
                                    and isinstance(e.value, str)]
            return None
        nxt = os.path.dirname(cur)
        if nxt == cur:
            return None
        cur = nxt
    return None


def _is_metrics_receiver(node) -> bool:
    """`_metrics.counter`, `metrics.gauge`, `profiler.metrics.inc`, ..."""
    if isinstance(node, ast.Name):
        return node.id in ("_metrics", "metrics")
    if isinstance(node, ast.Attribute):
        return node.attr in ("metrics", "_metrics")
    return False


@rule("PT403", "warning",
      "metric name emitted in code but absent from "
      "tools/trace_report.py KNOWN_METRICS")
def check_metric_names(mod):
    found = _find_known_metrics(mod.path)
    if found is None:
        return
    _, known = found
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _METRIC_FACTORIES | _METRIC_EMITTERS
                and _is_metrics_receiver(node.func.value)
                and node.args):
            continue
        arg = node.args[0]
        if not (isinstance(arg, ast.Constant)
                and isinstance(arg.value, str)):
            continue    # dynamic name: covered by '*' patterns
        if not match_known(arg.value, known):
            yield (node.lineno, node.col_offset,
                   f"metric '{arg.value}' is not in "
                   f"tools/trace_report.py KNOWN_METRICS — the triage "
                   f"report and README metric inventory won't know it; "
                   f"add it there (or fix the name)")


# ---------------------------------------------------------------------------
# PT404 — span names passed to tracing helpers must be literal strings
# ---------------------------------------------------------------------------

_SPAN_HELPERS = {"span", "record_span"}


def _is_tracing_receiver(node) -> bool:
    """`tracing.span`, `_tracing.record_span`, `profiler.tracing.span`"""
    if isinstance(node, ast.Name):
        return node.id in ("tracing", "_tracing")
    if isinstance(node, ast.Attribute):
        return node.attr in ("tracing", "_tracing")
    return False


@rule("PT404", "warning",
      "span name built at runtime: tracing helpers aggregate by name, "
      "so non-literal names explode trace cardinality")
def check_span_name_literals(mod):
    if mod.relpath.endswith("profiler/tracing.py"):
        return      # the definition site forwards caller-supplied names
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        f = node.func
        is_helper = (isinstance(f, ast.Attribute)
                     and f.attr in _SPAN_HELPERS
                     and _is_tracing_receiver(f.value)) \
            or (isinstance(f, ast.Name) and f.id == "RecordEvent") \
            or (isinstance(f, ast.Attribute) and f.attr == "RecordEvent")
        if not is_helper:
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            continue
        # literal family prefix: "op::" + name stays aggregatable
        if isinstance(arg, ast.BinOp) and isinstance(arg.op, ast.Add) \
                and isinstance(arg.left, ast.Constant) \
                and isinstance(arg.left.value, str) and arg.left.value:
            continue
        helper = f.attr if isinstance(f, ast.Attribute) else f.id
        yield (node.lineno, node.col_offset,
               f"span name passed to {helper}() is not a string "
               f"literal — span names are the aggregation key for the "
               f"flight recorder, trace merge, and span summary; put "
               f"variable data in the span's args instead")
