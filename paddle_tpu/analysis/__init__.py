"""ptlint — framework-aware static analysis for paddle_tpu.

Four rule families, each targeting a failure class that runtime testing
on the CPU mesh structurally cannot catch:

- **PT1xx trace-safety** — Python that silently mis-traces or breaks
  ``@to_static`` capture (jit/api.py can only count the breakage at
  runtime via ``jit/graph_break_count``).
- **PT2xx SPMD-collective ordering** — collectives under rank-dependent
  control flow: the single-controller test mesh executes them as local
  identities, a v5p pod deadlocks.
- **PT3xx Pallas grid contracts** — ``seq // block`` grids whose block
  merely *fits* instead of *dividing* (the varlen 640/768/896
  tail-truncation bug class), unguarded BlockSpec clamps, and
  version-fragile ``pltpu`` attribute use.
- **PT4xx registry consistency** — duplicate ``register()`` names,
  entries the dispatcher funnel can't call, and metric names missing
  from ``tools/trace_report.py``'s ``KNOWN_METRICS``.

Usage::

    python -m paddle_tpu.analysis paddle_tpu/          # or tools/ptlint.py
    python -m paddle_tpu.analysis paddle_tpu/ --format json
    python -m paddle_tpu.analysis paddle_tpu/ --write-baseline

Suppress a finding in place with ``# ptlint: disable=PT105`` (family
form ``PT1xx`` and ``all`` also work).  Grandfathered findings live in
the committed ``.ptlint-baseline.json``; regenerate it with
``--write-baseline`` after an intentional change, and shrink it over
time — baselined findings never fail CI but still show in reports.
"""
from .engine import (BASELINE_NAME, Finding, Report, all_rules,
                     load_baseline, render_json, render_text, run,
                     write_baseline)
from .main import main

__all__ = ["BASELINE_NAME", "Finding", "Report", "all_rules",
           "load_baseline", "main", "render_json", "render_text", "run",
           "write_baseline"]
