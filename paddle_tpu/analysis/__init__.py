"""ptlint + ptprog — framework-aware static analysis for paddle_tpu.

Two surfaces share this package, its reporters (text/json/sarif) and
the committed-baseline workflow:

**ptlint** (source level, jax-free): five AST rule families, each
targeting a failure class that runtime testing on the CPU mesh
structurally cannot catch:

- **PT1xx trace-safety** — Python that silently mis-traces or breaks
  ``@to_static`` capture (jit/api.py can only count the breakage at
  runtime via ``jit/graph_break_count``).
- **PT2xx SPMD-collective ordering** — collectives under rank-dependent
  control flow: the single-controller test mesh executes them as local
  identities, a v5p pod deadlocks.
- **PT3xx Pallas grid contracts** — ``seq // block`` grids whose block
  merely *fits* instead of *dividing* (the varlen 640/768/896
  tail-truncation bug class), unguarded BlockSpec clamps, and
  version-fragile ``pltpu`` attribute use.
- **PT4xx registry consistency** — duplicate ``register()`` names,
  entries the dispatcher funnel can't call, and metric names missing
  from ``tools/trace_report.py``'s ``KNOWN_METRICS``.
- **PT5xx error surfacing** — swallowed exceptions in distributed/.

**ptrace** (source level, jax-free, ``--conc`` / ``tools/ptrace.py``):
the concurrency families over the class threading model built in
``paddle_tpu.analysis.concurrency``:

- **PT7xx race detection** — lock-consistency (RacerD-style inferred
  guard maps) for attributes shared with service threads, lock-order
  deadlock cycles, thread join discipline, Condition usage.
- **PT8xx fleet-protocol invariants** — manifest-last persistence,
  hand-off payload identity (salt/version/trace), generation-fenced
  store writes, atomic metrics updates (scoped to distributed/,
  inference/, profiler/).

**ptprog** (IR level, ``paddle_tpu.analysis.program``): the PT6xx
passes over a *recorded* ``static.Program`` op list — shape/dtype
dataflow via ``jax.eval_shape`` (the infermeta analog), liveness-based
peak-memory estimation with a device-budget check, collective/sharding
consistency against the mesh (including dynamically-built groups the
AST cannot see), and the pass-equivalence verifier behind
``PassManager.run(program, verify=True)``.

Usage::

    python -m paddle_tpu.analysis paddle_tpu/          # or tools/ptlint.py
    python -m paddle_tpu.analysis paddle_tpu/ --format sarif
    python -m paddle_tpu.analysis paddle_tpu/ --write-baseline
    python -m paddle_tpu.analysis paddle_tpu/ --update-baseline
    python -m paddle_tpu.analysis --program llama      # or tools/ptprog.py
    python -m paddle_tpu.analysis --program llama --budget-gb 16

Suppress a source finding in place with ``# ptlint: disable=PT105``
(family form ``PT1xx`` and ``all`` also work).  Grandfathered findings
live in the committed ``.ptlint-baseline.json``; regenerate it with
``--write-baseline`` after an intentional change, and shrink it over
time with ``--update-baseline``, which prunes entries whose findings
no longer fire — baselined findings never fail CI but still show in
reports.
"""
from .engine import (BASELINE_NAME, PTPROG_RULES, Finding, Report,
                     all_rules, load_baseline, render_json, render_sarif,
                     render_text, run, write_baseline)
from .main import main

__all__ = ["BASELINE_NAME", "PTPROG_RULES", "Finding", "Report",
           "all_rules", "load_baseline", "main", "render_json",
           "render_sarif", "render_text", "run", "write_baseline"]
