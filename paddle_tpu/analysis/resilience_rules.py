"""PT5xx — error-surfacing rules for the resilience-bearing layers.

The fault-tolerance contract (distributed/resilience/) is that failures
surface as structured errors or at least as metric counts — never
vanish. A ``try: ... except Exception: pass`` in transport, elastic, or
the launch controller is exactly how a real failure mode (dead peer,
store hiccup, torn frame) turns into an undebuggable hang three layers
up: the recovery loop can only react to failures it can see.

Scope: files under a ``distributed/`` directory (the subsystem where
every swallowed error is a potential silent desync) AND under
``inference/`` — the serving fleet runs the same recovery loop
(EngineDeadError -> drain -> restart, see inference/fleet_supervisor)
and a swallowed error there silently strands in-flight requests.
Sites that are genuinely by-design (e.g. best-effort probes on a hot
poll path) are grandfathered in ``.ptlint-baseline.json`` or
suppressed in place with an explained ``# ptlint: disable=PT5xx``.
"""
from __future__ import annotations

import ast

from .engine import call_name, rule

_BROAD = ("Exception", "BaseException")

_SCOPED_DIRS = ("distributed/", "inference/")


def _in_scope(mod) -> bool:
    path = "/" + mod.relpath
    return any(d in path for d in _SCOPED_DIRS)


def _is_broad(handler: ast.ExceptHandler) -> bool:
    """except:, except Exception:, except BaseException:, or a tuple
    containing one of those."""
    t = handler.type
    if t is None:
        return True
    names = []
    if isinstance(t, ast.Tuple):
        names = [e.id for e in t.elts if isinstance(e, ast.Name)]
    elif isinstance(t, ast.Name):
        names = [t.id]
    return any(n in _BROAD for n in names)


def _body_swallows(handler: ast.ExceptHandler) -> bool:
    """True when the handler body does NOTHING with the error: only
    pass / continue / a bare constant (docstring, Ellipsis). Any call,
    assignment, return-of-a-fallback, raise, or logging counts as
    surfacing/handling."""
    for stmt in handler.body:
        if isinstance(stmt, (ast.Pass, ast.Continue)):
            continue
        if isinstance(stmt, ast.Expr) \
                and isinstance(stmt.value, ast.Constant):
            continue
        return False
    return True


@rule("PT501", "error",
      "bare 'except:' in distributed//inference/ — also traps "
      "SystemExit/"
      "KeyboardInterrupt, so a killed rank can't even die")
def check_bare_except(mod):
    if not _in_scope(mod):
        return
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            yield (node.lineno, node.col_offset,
                   "bare 'except:' traps SystemExit and "
                   "KeyboardInterrupt — in the distributed layer this "
                   "can keep a rank half-alive after the launcher "
                   "killed it; catch Exception (or narrower) instead")


def _sleep_calls(loop) -> list:
    """Constant-argument ``time.sleep`` calls lexically inside `loop`,
    not nested in an inner function/class (those have their own loop
    context). A sleep whose argument is an expression (e.g.
    ``_backoff(attempt)``) is the sanctioned shape and is skipped."""
    out = []
    stack = list(ast.iter_child_nodes(loop))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "sleep" \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == "time" \
                and node.args \
                and isinstance(node.args[0], ast.Constant):
            out.append(node)
        stack.extend(ast.iter_child_nodes(node))
    return out


def _has_handler(loop) -> bool:
    stack = list(ast.iter_child_nodes(loop))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        if isinstance(node, ast.Try):
            return True
        stack.extend(ast.iter_child_nodes(node))
    return False


@rule("PT503", "warning",
      "constant time.sleep retry loop in distributed//inference/ — "
      "use the "
      "resilience.backoff helpers so retries back off exponentially")
def check_constant_sleep_retry(mod):
    """A loop that catches errors and re-tries after a CONSTANT
    ``time.sleep`` hammers a dead peer at a fixed frequency — exactly
    wrong while the elastic controller needs seconds to relaunch it.
    ``resilience/backoff.py`` is the one sanctioned policy (the
    transport redial and store connect paths go through it); a sleep
    whose argument is computed (``time.sleep(_backoff(attempt))``) is
    fine. Pure poll loops (no exception handler) are not flagged."""
    if not _in_scope(mod):
        return
    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.While, ast.For)):
            continue
        if not _has_handler(node):
            continue
        for call in _sleep_calls(node):
            yield (call.lineno, call.col_offset,
                   "retry loop sleeps a constant "
                   f"{call.args[0].value!r}s between attempts — route "
                   "it through resilience.backoff.delay/sleep_backoff "
                   "(exponential, capped) so a dead peer being "
                   "relaunched isn't hammered at a fixed frequency")


@rule("PT504", "warning",
      "direct TCPStore(...) construction in distributed//inference/ — "
      "connect to the rendezvous store via store.connect_store so the "
      "client fails over to the standby replica")
def check_direct_tcpstore(mod):
    """A client holding a raw ``TCPStore`` socket dies with the store
    host: the whole point of the hot-standby replica
    (``store.StandbyStore`` + ``store.FailoverStore``) is that clients
    redial the survivor instead.  ``connect_store(...)`` is the one
    sanctioned constructor — it wraps the same endpoint (plus any
    ``PT_STORE_STANDBY`` endpoints) in the failover client.  The store
    module itself is exempt: the wrapper has to construct the thing it
    wraps."""
    if not _in_scope(mod):
        return
    if mod.relpath.endswith("distributed/store.py"):
        return
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call) \
                and call_name(node) == "TCPStore":
            yield (node.lineno, node.col_offset,
                   "direct TCPStore(...) pins this client to a single "
                   "store host — use distributed.store.connect_store "
                   "(same arguments, plus standby=) so a store-host "
                   "death fails over to the replica instead of taking "
                   "the rendezvous plane down with it")


@rule("PT502", "warning",
      "'except Exception: pass' in distributed//inference/ — the "
      "error must be "
      "surfaced (raise/log) or counted (profiler metrics)")
def check_swallowed_exception(mod):
    if not _in_scope(mod):
        return
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if _is_broad(node) and _body_swallows(node):
            yield (node.lineno, node.col_offset,
                   "broad except with a body that only passes: in the "
                   "distributed layer a swallowed error here is a "
                   "silent desync/hang later — surface it as a "
                   "structured error (resilience/errors.py), log it, "
                   "or count it via profiler metrics; if genuinely "
                   "by-design, suppress with an explained "
                   "'# ptlint: disable=PT502'")
