"""PT3xx — Pallas kernel grid/block contracts.

The bug class behind round 5's high-severity varlen-attention advisory:
a Pallas grid of ``seq // block`` whole tiles *floor-truncates* — if the
block does not divide the packed length exactly, the trailing
``seq % block`` tokens are silently never computed (640/768/896-token
packs dropped their tails while every 512-aligned test passed).  The
fixed contract (ops/pallas/varlen_attention.py `_vfa_block`) is: a block
must be *selected to divide* (``s % b == 0``) or the call must fall back
to the dense reference.

These rules enforce that contract statically:

- PT301: ``x // y`` inside a ``pallas_call`` ``grid=`` expression whose
  divisor has no reachable divisibility guard (a ``% y`` check in the
  module, a guarded block-selector feeding it, or a guard on the callee
  parameter it binds to).
- PT302: ``pl.BlockSpec`` block shapes built from ``min(...)``/
  ``max(...)`` clamps without a ``%`` guard — "merely fits" is exactly
  the pre-fix varlen bug.
- PT303: version-fragile ``pltpu`` attribute access: jax renamed
  ``TPUCompilerParams`` -> ``CompilerParams``; direct attribute use of
  either breaks on the other side of the rename (use the getattr
  pattern in ops/pallas/flash_attention.py `_dim_semantics`).
"""
from __future__ import annotations

import ast
from typing import Optional

from .engine import call_name, rule

_PLTPU_RENAMED = {"CompilerParams", "TPUCompilerParams"}


# ---------------------------------------------------------------------------
# guard resolution
# ---------------------------------------------------------------------------

def _mod_ops_with_divisor(tree, name: str):
    """All `<x> % <name>` BinOps in the subtree."""
    for node in ast.walk(tree):
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mod) \
                and isinstance(node.right, ast.Name) \
                and node.right.id == name:
            yield node


def _has_mod_guard(tree, name: str) -> bool:
    return any(True for _ in _mod_ops_with_divisor(tree, name))


def _has_any_divisibility_compare(fn) -> bool:
    """Does this function body contain a `x % y == 0`-shaped compare
    (the block-selector pattern, e.g. varlen `_vfa_block`)?"""
    for node in ast.walk(fn):
        if isinstance(node, ast.Compare) and \
                isinstance(node.left, ast.BinOp) and \
                isinstance(node.left.op, ast.Mod):
            return True
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mod):
            parent = getattr(node, "_pt_parent", None)
            if isinstance(parent, ast.Compare):
                return True
    return False


def _selector_functions(mod) -> set:
    """Module functions whose body proves divisibility (contain a
    `% ... == 0`-style compare) — calls to these are trusted block
    sources."""
    cached = getattr(mod, "_pt_selectors", None)
    if cached is not None:
        return cached
    out = {name for name, fn in mod.functions.items()
           if _has_any_divisibility_compare(fn)}
    mod._pt_selectors = out
    return out


def _expr_calls_selector(expr, selectors: set) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Call) and call_name(node) in selectors:
            return True
    return False


def _local_assignment(fn, name: str) -> Optional[ast.expr]:
    """Last simple assignment `name = <expr>` in the function body."""
    found = None
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == name:
                    found = node.value
        elif isinstance(node, ast.AnnAssign) and \
                isinstance(node.target, ast.Name) and \
                node.target.id == name and node.value is not None:
            found = node.value
    return found


def _param_index(fn: ast.FunctionDef, name: str) -> Optional[int]:
    params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    try:
        return params.index(name)
    except ValueError:
        return None


def _call_sites(mod, func_name: str):
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call) and call_name(node) == func_name:
            yield node


def _arg_for_param(call: ast.Call, fn: ast.FunctionDef, name: str):
    idx = _param_index(fn, name)
    if idx is not None and idx < len(call.args):
        return call.args[idx]
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _divisor_guarded(mod, fn, name: str, depth: int = 0) -> bool:
    """Is block-size `name`, used as a divisor/block inside `fn`, covered
    by a divisibility guard anywhere reachable?

    1. a `% name` anywhere in the module (e.g. flash_attention
       `_pallas_ok`'s `q.shape[2] % block_q == 0`, rms_norm's
       `n % block != 0` fallback);
    2. `name` passed onward to a module function whose matching
       parameter is `%`-guarded in that callee;
    3. `name` assigned from a call to a guarded block-selector
       (varlen `_vfa_block`: selected so `s % b == 0`);
    4. `name` is a parameter of `fn` and every module call site binds it
       to a guarded expression (selector call or a name guarded in the
       calling function).
    """
    if _has_mod_guard(mod.tree, name):
        return True
    selectors = _selector_functions(mod)
    # (2) forwarded into a guarded callee parameter
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            cn = call_name(node)
            callee = mod.functions.get(cn) if cn else None
            if callee is None or callee is fn:
                continue
            for i, a in enumerate(node.args):
                if isinstance(a, ast.Name) and a.id == name:
                    params = [p.arg for p in callee.args.posonlyargs
                              + callee.args.args]
                    if i < len(params) and \
                            _has_mod_guard(callee, params[i]):
                        return True
    # (3) assigned from a guarded selector
    assigned = _local_assignment(fn, name)
    if assigned is not None and _expr_calls_selector(assigned, selectors):
        return True
    # (4) parameter: every call site must hand in a guarded value
    if depth < 2 and _param_index(fn, name) is not None:
        sites = list(_call_sites(mod, fn.name))
        if sites:
            ok = True
            for call in sites:
                arg = _arg_for_param(call, fn, name)
                if arg is None:
                    ok = False
                    break
                if _expr_calls_selector(arg, selectors):
                    continue
                caller = mod.enclosing_function(call)
                if caller is not None and isinstance(arg, ast.Name) and \
                        _divisor_guarded(mod, caller, arg.id, depth + 1):
                    continue
                ok = False
                break
            if ok:
                return True
    return False


# ---------------------------------------------------------------------------
# locating pallas grids / block specs
# ---------------------------------------------------------------------------

def _pallas_calls(mod):
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call) and call_name(node) == "pallas_call":
            yield node


def _grid_expr(mod, call: ast.Call):
    for kw in call.keywords:
        if kw.arg == "grid":
            v = kw.value
            if isinstance(v, ast.Name):
                fn = mod.enclosing_function(call)
                if fn is not None:
                    resolved = _local_assignment(fn, v.id)
                    if resolved is not None:
                        return resolved
            return v
    return None


@rule("PT301", "error",
      "pallas grid `x // block` without a divisibility guard "
      "floor-truncates: trailing x % block elements are never computed")
def check_grid_floor_division(mod):
    for call in _pallas_calls(mod):
        grid = _grid_expr(mod, call)
        if grid is None:
            continue
        fn = mod.enclosing_function(call)
        if fn is None:
            continue
        for node in ast.walk(grid):
            if not (isinstance(node, ast.BinOp)
                    and isinstance(node.op, ast.FloorDiv)):
                continue
            div = node.right
            if isinstance(div, ast.Constant):
                # constant divisor: accept only if the module carries any
                # %-based divisibility compare at all
                if any(_has_any_divisibility_compare(f)
                       for f in mod.functions.values()):
                    continue
                name = repr(div.value)
            elif isinstance(div, ast.Name):
                if _divisor_guarded(mod, fn, div.id):
                    continue
                name = div.id
            else:
                continue  # complex divisor expression: out of scope
            yield (node.lineno, node.col_offset,
                   f"grid uses '// {name}' with no reachable "
                   f"divisibility guard ('% {name} == 0' check, guarded "
                   f"block selector, or reference fallback): a block "
                   f"that merely fits silently drops the trailing "
                   f"remainder rows (the varlen 640/768/896 bug); "
                   f"select the block so it divides, or gate with a "
                   f"fallback")


@rule("PT302", "error",
      "BlockSpec block built from an unguarded min()/max() clamp")
def check_blockspec_clamp(mod):
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Call)
                and call_name(node) == "BlockSpec" and node.args):
            continue
        shape = node.args[0]
        elements = shape.elts if isinstance(shape, (ast.Tuple, ast.List)) \
            else [shape]
        fn = mod.enclosing_function(node)
        for el in elements:
            clamp = None
            name = None
            if isinstance(el, ast.Call) and \
                    call_name(el) in ("min", "max"):
                clamp = el
            elif isinstance(el, ast.Name) and fn is not None:
                assigned = _local_assignment(fn, el.id)
                if isinstance(assigned, ast.Call) and \
                        call_name(assigned) in ("min", "max"):
                    clamp = assigned
                    name = el.id
            if clamp is None:
                continue
            if name is not None and fn is not None and \
                    _divisor_guarded(mod, fn, name):
                continue
            what = name or "an inline min()/max()"
            yield (el.lineno, el.col_offset,
                   f"BlockSpec block '{what}' comes from a "
                   f"{call_name(clamp)}() clamp with no '%' divisibility "
                   f"guard: a clamp guarantees the block fits, not that "
                   f"it divides — the grid drops the remainder (pre-fix "
                   f"varlen pattern)")


@rule("PT303", "warning",
      "version-fragile pltpu attribute (TPUCompilerParams/CompilerParams "
      "rename) used directly")
def check_pltpu_renamed_attr(mod):
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "pltpu" and \
                node.attr in _PLTPU_RENAMED:
            yield (node.lineno, node.col_offset,
                   f"direct 'pltpu.{node.attr}' breaks across the jax "
                   f"TPUCompilerParams->CompilerParams rename; resolve "
                   f"via getattr with a fallback "
                   f"(ops/pallas/flash_attention.py _dim_semantics)")
