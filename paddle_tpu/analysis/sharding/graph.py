"""The propagator's program view: a jax-free shadow of ``ProgramIR``.

``ShardGraph`` keeps exactly what sharding propagation needs — op list
with input/output uids, per-uid shapes and itemsizes, feed/external/
fetch roots and the recorded collective metadata — as plain ints and
tuples.  Two construction paths:

- :func:`graph_from_ir` bridges a ``ProgramIR`` plus its abstract
  environment (jax needed once, at capture time);
- :meth:`ShardGraph.from_json` loads a serialized graph, which is how
  ``tools/ptshard.py`` analyzes a capture with no jax in the process
  and how the fixture matrix builds violating programs by hand.

Per-op attrs (``perm`` for transpose-family, ``axis`` for
index_select/softmax) are recovered from the recorded closure when
available — the same closure-recovery discipline as
``ir.collective_info``.
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = ["ShardOp", "ShardGraph", "graph_from_ir"]


@dataclass
class ShardOp:
    index: int
    name: str
    in_uids: Tuple[int, ...]
    out_uids: Tuple[int, ...]
    attrs: Dict[str, Any] = field(default_factory=dict)


@dataclass
class ShardGraph:
    name: str
    ops: List[ShardOp] = field(default_factory=list)
    shapes: Dict[int, Tuple[int, ...]] = field(default_factory=dict)
    itemsize: Dict[int, int] = field(default_factory=dict)
    feeds: Dict[str, int] = field(default_factory=dict)      # name -> uid
    externals: List[int] = field(default_factory=list)
    fetches: List[int] = field(default_factory=list)
    collectives: List[Dict[str, Any]] = field(default_factory=list)

    def __post_init__(self):
        self.producer: Dict[int, int] = {}
        self.consumers: Dict[int, List[int]] = {}
        self._reindex()

    def _reindex(self):
        self.producer.clear()
        self.consumers.clear()
        for op in self.ops:
            for u in op.out_uids:
                self.producer.setdefault(u, op.index)
            for u in op.in_uids:
                self.consumers.setdefault(u, []).append(op.index)

    def shape(self, uid: int) -> Tuple[int, ...]:
        return tuple(self.shapes.get(uid, ()))

    def nbytes(self, uid: int) -> int:
        n = self.itemsize.get(uid, 4)
        for d in self.shape(uid):
            n *= int(d)
        return int(n)

    def seed_uids(self) -> List[Tuple[int, str]]:
        """(uid, label) for every value live before op 0 — feeds first
        (labelled by feed name), then externals."""
        out = [(u, f"feed:{n}") for n, u in self.feeds.items()]
        ext = {u for u, _ in out}
        out += [(u, f"external:{u}") for u in self.externals
                if u not in ext]
        return out

    def meta_for(self, op_index: int) -> Optional[Dict[str, Any]]:
        for m in self.collectives:
            if int(m.get("op_index", -1)) == op_index:
                return m
        return None

    def last_use(self) -> Dict[int, int]:
        n = len(self.ops)
        out = {u: max(idxs) for u, idxs in self.consumers.items()}
        for u in self.fetches:
            out[u] = n - 1 if n else 0
        return out

    # -- serialization ----------------------------------------------------
    def to_json(self) -> str:
        return json.dumps({
            "version": 1,
            "name": self.name,
            "ops": [{"index": o.index, "name": o.name,
                     "ins": list(o.in_uids), "outs": list(o.out_uids),
                     "attrs": o.attrs} for o in self.ops],
            "shapes": {str(u): list(s) for u, s in self.shapes.items()},
            "itemsize": {str(u): n for u, n in self.itemsize.items()},
            "feeds": self.feeds,
            "externals": list(self.externals),
            "fetches": list(self.fetches),
            "collectives": self.collectives,
        }, indent=1)

    @classmethod
    def from_json(cls, text: str) -> "ShardGraph":
        d = json.loads(text)
        return cls(
            name=d.get("name", "graph"),
            ops=[ShardOp(int(o["index"]), o["name"],
                         tuple(int(u) for u in o["ins"]),
                         tuple(int(u) for u in o["outs"]),
                         dict(o.get("attrs") or {}))
                 for o in d.get("ops", [])],
            shapes={int(u): tuple(int(x) for x in s)
                    for u, s in d.get("shapes", {}).items()},
            itemsize={int(u): int(n)
                      for u, n in d.get("itemsize", {}).items()},
            feeds={str(n): int(u) for n, u in d.get("feeds", {}).items()},
            externals=[int(u) for u in d.get("externals", [])],
            fetches=[int(u) for u in d.get("fetches", [])],
            collectives=list(d.get("collectives", [])),
        )


# op name -> closure freevars worth lifting into attrs, with the
# canonical attr each maps to
_ATTR_VARS = {
    "transpose": {"p": "perm", "perm": "perm"},
    "moveaxis": {"source": "source", "destination": "destination"},
    "swapaxes": {"axis0": "axis0", "axis1": "axis1"},
    "index_select": {"axis": "axis"},
    "softmax": {"axis": "axis"},
    "argmax": {"axis": "axis"},
    "argmin": {"axis": "axis"},
    "mean": {"axis": "axis"},
    "sum": {"axis": "axis"},
    "concat": {"axis": "axis"},
    "split": {"axis": "axis"},
}


def _closure_attrs(name: str, fn) -> Dict[str, Any]:
    want = _ATTR_VARS.get(name)
    if not want:
        return {}
    code = getattr(fn, "__code__", None)
    cells = getattr(fn, "__closure__", None) or ()
    if code is None:
        return {}
    out: Dict[str, Any] = {}
    for var, cell in zip(code.co_freevars, cells):
        if var not in want:
            continue
        try:
            val = cell.cell_contents
        except ValueError:
            continue
        if isinstance(val, int) and not isinstance(val, bool):
            out[want[var]] = int(val)
        elif isinstance(val, (tuple, list)) and all(
                isinstance(v, int) for v in val):
            out[want[var]] = [int(v) for v in val]
    # normalize the transpose family to one canonical "perm"
    if name == "swapaxes" and {"axis0", "axis1"} <= out.keys():
        out = {"swap": [out["axis0"], out["axis1"]]}
    return out


def graph_from_ir(ir, env) -> ShardGraph:
    """Bridge a ``ProgramIR`` + abstract environment (from
    ``dataflow.abstract_run``) into the jax-free graph.  Values whose
    abstract evaluation failed are simply absent from ``shapes``; the
    propagator replicates them."""
    import numpy as np

    shapes: Dict[int, Tuple[int, ...]] = {}
    itemsize: Dict[int, int] = {}
    for u, aval in env.items():
        shape = getattr(aval, "shape", None)
        if shape is None:
            continue
        shapes[u] = tuple(int(d) for d in shape)
        try:
            itemsize[u] = int(np.dtype(aval.dtype).itemsize)
        except Exception:
            itemsize[u] = 4

    ops = []
    for op in ir.ops:
        ops.append(ShardOp(
            index=op.index, name=op.name,
            in_uids=tuple(int(u) for u in op.in_uids),
            out_uids=tuple(int(u) for u in op.out_uids),
            attrs=_closure_attrs(op.name, op.fn)))

    return ShardGraph(
        name=ir.name, ops=ops, shapes=shapes, itemsize=itemsize,
        feeds={str(n): int(u) for n, u in ir.feed_uids.items()},
        externals=[int(u) for u in ir.external_uids],
        fetches=[int(u) for u in ir.fetch_uids],
        collectives=[dict(m) for m in ir.collectives],
    )
