"""PartitionSpec-style sharding vocabulary for the PT9xx analyzer.

Two deliberately tiny value types:

- :class:`MeshSpec` — an ordered ``name -> size`` view of a device mesh,
  plus a per-axis *tier* tag (``"ici"`` within a slice, ``"dcn"`` across
  slices) so the propagator can price a reshard on the right fabric.
  Built from a live ``jax.sharding.Mesh`` (``from_mesh`` reads only
  ``mesh.shape``, so a duck-typed stand-in works), or parsed from the
  CLI string form ``"dp=2,mp=4"`` / ``"dp=2@dcn,mp=4"``.
- :class:`ShardSpec` — one PartitionSpec: a tuple with one entry per
  tensor dim, each ``None`` (replicated), an axis name, or a tuple of
  axis names (multi-axis sharding of one dim).

Deliberately stdlib-only: the jax-free ``tools/ptshard.py`` CLI and the
fixture tests load this without the framework.  ``validate`` returns the
raw PT901/PT903 issues; the propagator owns turning them into engine
Findings with op context.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = ["MeshSpec", "ShardSpec", "replicated", "parse_spec"]

_TIERS = ("ici", "dcn")


@dataclass(frozen=True)
class MeshSpec:
    """Ordered mesh axes with sizes and fabric tiers."""

    axes: Tuple[Tuple[str, int], ...]
    tiers: Tuple[Tuple[str, str], ...] = ()     # (axis, "ici"|"dcn")

    def __post_init__(self):
        seen = set()
        for name, size in self.axes:
            if name in seen:
                raise ValueError(f"duplicate mesh axis {name!r}")
            seen.add(name)
            if int(size) < 1:
                raise ValueError(f"mesh axis {name!r} has size {size}")
        for name, tier in self.tiers:
            if tier not in _TIERS:
                raise ValueError(f"unknown tier {tier!r} for axis {name!r}")

    @property
    def sizes(self) -> Dict[str, int]:
        return {n: int(s) for n, s in self.axes}

    @property
    def names(self) -> Tuple[str, ...]:
        return tuple(n for n, _ in self.axes)

    @property
    def n_devices(self) -> int:
        n = 1
        for _, s in self.axes:
            n *= int(s)
        return n

    def has(self, name: str) -> bool:
        return any(n == name for n, _ in self.axes)

    def size(self, name: str) -> int:
        for n, s in self.axes:
            if n == name:
                return int(s)
        raise KeyError(name)

    def tier(self, name: str) -> str:
        for n, t in self.tiers:
            if n == name:
                return t
        return "ici"

    def describe(self) -> str:
        parts = []
        for n, s in self.axes:
            t = self.tier(n)
            parts.append(f"{n}={s}" + (f"@{t}" if t != "ici" else ""))
        return ",".join(parts)

    @classmethod
    def from_mesh(cls, mesh) -> Optional["MeshSpec"]:
        """From a live (or duck-typed) jax Mesh.  Axes marked DCN by
        ``topology.build_hybrid_mesh`` (``mesh._pt_dcn_axes``) keep
        their tier."""
        if mesh is None:
            return None
        if isinstance(mesh, cls):
            return mesh
        shape = getattr(mesh, "shape", None)
        if shape is None:
            return None
        try:
            items = list(dict(shape).items())
        except Exception:
            return None
        dcn = tuple(getattr(mesh, "_pt_dcn_axes", ()) or ())
        return cls(axes=tuple((str(n), int(s)) for n, s in items),
                   tiers=tuple((str(a), "dcn") for a in dcn))

    @classmethod
    def parse(cls, text: str) -> "MeshSpec":
        """``"dp=2,mp=4"``; append ``@dcn`` to mark a cross-slice axis:
        ``"dp=2@dcn,pp=2,mp=2"``."""
        axes: List[Tuple[str, int]] = []
        tiers: List[Tuple[str, str]] = []
        for part in text.split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(f"bad mesh axis {part!r} (want name=size)")
            name, _, rest = part.partition("=")
            tier = "ici"
            if "@" in rest:
                rest, _, tier = rest.partition("@")
            axes.append((name.strip(), int(rest)))
            tiers.append((name.strip(), tier.strip() or "ici"))
        return cls(axes=tuple(axes),
                   tiers=tuple((n, t) for n, t in tiers if t != "ici"))


def _as_dim(entry) -> Tuple[str, ...]:
    if entry is None:
        return ()
    if isinstance(entry, str):
        return (entry,)
    return tuple(entry)


@dataclass(frozen=True)
class ShardSpec:
    """One PartitionSpec: per-dim axis assignment."""

    dims: Tuple[Tuple[str, ...], ...] = ()

    @classmethod
    def of(cls, *entries) -> "ShardSpec":
        """``ShardSpec.of('dp', None, ('mp', 'sep'))``."""
        return cls(dims=tuple(_as_dim(e) for e in entries))

    @property
    def rank(self) -> int:
        return len(self.dims)

    def normalized(self, rank: int) -> "ShardSpec":
        """Pad with replicated dims (or truncate trailing replicated
        dims) to match a tensor rank."""
        dims = tuple(self.dims[:rank]) + ((),) * max(0, rank - len(self.dims))
        return ShardSpec(dims=dims)

    def dim_axes(self, i: int) -> Tuple[str, ...]:
        if 0 <= i < len(self.dims):
            return self.dims[i]
        return ()

    def axes(self) -> Tuple[str, ...]:
        out: List[str] = []
        for d in self.dims:
            out.extend(d)
        return tuple(out)

    @property
    def is_replicated(self) -> bool:
        return not self.axes()

    def factor(self, mesh: MeshSpec) -> int:
        """Number of shards this spec splits the tensor into."""
        f = 1
        for a in self.axes():
            if mesh.has(a):
                f *= mesh.size(a)
        return f

    def dim_factor(self, i: int, mesh: MeshSpec) -> int:
        f = 1
        for a in self.dim_axes(i):
            if mesh.has(a):
                f *= mesh.size(a)
        return f

    def sharded_shape(self, shape: Sequence[int],
                      mesh: MeshSpec) -> Tuple[int, ...]:
        out = []
        for i, d in enumerate(shape):
            f = self.dim_factor(i, mesh)
            out.append(-(-int(d) // f))          # ceil: padding model
        return tuple(out)

    def shard_nbytes(self, shape: Sequence[int], itemsize: int,
                     mesh: MeshSpec) -> int:
        n = itemsize
        for d in self.sharded_shape(shape, mesh):
            n *= int(d)
        return int(n)

    def with_dim(self, i: int, axes) -> "ShardSpec":
        dims = list(self.dims)
        while len(dims) <= i:
            dims.append(())
        dims[i] = _as_dim(axes)
        return ShardSpec(dims=tuple(dims))

    def drop_axis(self, axis: str) -> "ShardSpec":
        return ShardSpec(dims=tuple(
            tuple(a for a in d if a != axis) for d in self.dims))

    def __str__(self):
        if self.is_replicated:
            return "P(replicated)"
        parts = []
        for d in self.dims:
            if not d:
                parts.append("-")
            elif len(d) == 1:
                parts.append(d[0])
            else:
                parts.append("(" + "+".join(d) + ")")
        return "P[" + ",".join(parts) + "]"


def replicated(rank: int = 0) -> ShardSpec:
    return ShardSpec(dims=((),) * rank)


def parse_spec(text: str) -> ShardSpec:
    """``"dp,-,mp"`` / ``"dp,None,mp+sep"`` — the CLI/plan string form."""
    entries = []
    for part in text.split(","):
        part = part.strip()
        if part in ("-", "", "None", "none", "*"):
            entries.append(None)
        elif "+" in part:
            entries.append(tuple(p.strip() for p in part.split("+")))
        else:
            entries.append(part)
    return ShardSpec.of(*entries)


def validate(spec: ShardSpec, shape: Sequence[int],
             mesh: MeshSpec) -> List[Tuple[str, str]]:
    """Raw PT901/PT903 issues for one (spec, shape) pair:
    ``[(rule_id, message), ...]`` — no op context, the caller adds it.

    PT901: a named axis is absent from the mesh, or one mesh axis is
    mapped to two tensor dims (each device would need two different
    slices of the same tensor).  PT903: a sharded dim is not divisible
    by the product of its mesh-axis sizes — jax ``shard_map`` rejects
    it, and GSPMD pads silently (wasted memory + compute).
    """
    issues: List[Tuple[str, str]] = []
    seen: Dict[str, int] = {}
    for i, d in enumerate(spec.dims):
        for a in d:
            if not mesh.has(a):
                tiers = mesh.describe()
                issues.append((
                    "PT901",
                    f"spec {spec} binds axis '{a}' (dim {i}) which is "
                    f"not on the mesh [{tiers}]"))
                continue
            if a in seen:
                issues.append((
                    "PT901",
                    f"spec {spec} maps mesh axis '{a}' to both dim "
                    f"{seen[a]} and dim {i} — an axis can shard at "
                    f"most one dim"))
            seen.setdefault(a, i)
    for i, d in enumerate(spec.dims):
        if i >= len(shape):
            break
        f = 1
        for a in d:
            if mesh.has(a):
                f *= mesh.size(a)
        if f > 1 and int(shape[i]) % f != 0:
            issues.append((
                "PT903",
                f"dim {i} of size {shape[i]} is sharded {spec} over "
                f"{f} shards ({'x'.join(d)}) — not divisible; each "
                f"shard pads to {-(-int(shape[i]) // f)} rows "
                f"(silent padding)"))
    return issues
