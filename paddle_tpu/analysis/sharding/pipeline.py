"""PT905: pipeline-stage boundary sharding consistency.

``ptprog.check_pipeline`` (PT623) proves every send has a matching
recv across stage sub-programs; this module checks what those matched
transfers *carry*: the sharding of stage *i*'s outputs must equal the
sharding stage *i+1* expects on its inputs.  A mismatch is not a
deadlock — the runtime reshards silently — but on a pp boundary the
reshard happens once per microbatch per step, usually over DCN, which
is exactly the "my pipeline is mysteriously 2x slower" class.

Boundary pairing is positional: stage *i*'s fetch list against stage
*i+1*'s feed list (same-shape pairs only; shape routing itself is
PT623/PT601 territory).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

from ..engine import Finding
from .graph import ShardGraph
from .propagate import ShardingReport, propagate, _collective_bytes
from .spec import MeshSpec

__all__ = ["check_stage_boundaries"]


def check_stage_boundaries(graphs: Sequence[ShardGraph],
                           mesh: MeshSpec,
                           plans: Optional[Sequence] = None,
                           reports: Optional[
                               Sequence[ShardingReport]] = None,
                           ) -> List[Finding]:
    """Propagate each stage graph (unless precomputed ``reports`` are
    given) and flag PT905 at every fetch->feed boundary whose specs
    disagree.  Per-stage propagation findings are included, so one call
    covers the whole PT9xx surface of a pipeline."""
    findings: List[Finding] = []
    if reports is None:
        reports = []
        for i, g in enumerate(graphs):
            plan = plans[i] if plans and i < len(plans) else None
            rep = propagate(g, mesh, plan)
            findings.extend(rep.findings)
            reports.append(rep)

    for i in range(len(graphs) - 1):
        src_g, dst_g = graphs[i], graphs[i + 1]
        src_r, dst_r = reports[i], reports[i + 1]
        dst_feeds = list(dst_g.feeds.items())    # insertion-ordered
        for pos, out_uid in enumerate(src_g.fetches):
            if pos >= len(dst_feeds):
                break
            feed_name, in_uid = dst_feeds[pos]
            if src_g.shape(out_uid) != dst_g.shape(in_uid):
                continue                         # not a boundary pair
            out_spec = src_r.specs.get(out_uid)
            in_spec = dst_r.specs.get(in_uid)
            if out_spec is None or in_spec is None:
                continue
            rank = len(src_g.shape(out_uid))
            if out_spec.normalized(rank) == in_spec.normalized(rank):
                continue
            moved = _collective_bytes(
                "reshard", src_g.nbytes(out_uid),
                max(out_spec.factor(mesh), in_spec.factor(mesh), 2))
            findings.append(Finding(
                "PT905", "error", f"program:{src_g.name}",
                len(src_g.ops), 0,
                f"pipeline boundary stage {i}->{i + 1}: output {pos} "
                f"leaves sharded {out_spec} but stage {i + 1} feed "
                f"'{feed_name}' expects {in_spec} — "
                f"~{moved / (1 << 20):.2f} MiB resharded per "
                f"microbatch per step on the stage boundary",
                line_text=f"boundary:{i}->{i + 1}:{feed_name}"))
    return findings
