"""Sharding plans: the seed specs propagation starts from.

A plan only pins down the program's *inputs* (feeds by name, externals
by uid); everything else is derived by propagation.  Two built-ins:

- :func:`replicated_plan` — nothing sharded.  The conservative CI
  default: zero findings unless the program carries explicitly
  redundant collectives (PT904) or declared specs are malformed.
- :func:`megatron_plan` — data parallel on the batch dim of every feed
  that divides, tensor parallel on the 2-D weight externals in the
  classic Megatron alternation: a weight consumed by an activation
  that is not yet tp-tainted is column-split ``[-, tp]``, one consumed
  by a tp-tainted activation is row-split ``[tp, -]`` (its matmul
  contracts over the sharded dim, producing the partial sum the
  propagator charges one all-reduce for).  The taint scan is a cheap
  forward walk over the op list — no propagation needed to build the
  plan, so planning stays O(ops) per candidate config in the tuner's
  grid.

Weights whose dims do not divide the tp axis are left replicated (the
plan degrades rather than generating PT903 noise); 1-D externals
(norm gains, biases) are tp-sharded only when they feed an
elementwise op whose other operand's *last dim* is tp-sharded.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from .graph import ShardGraph
from .spec import MeshSpec, ShardSpec

__all__ = ["ShardingPlan", "replicated_plan", "megatron_plan",
           "plan_by_name"]

_MATMUL = ("matmul", "linear", "bmm", "dense", "fc")


@dataclass
class ShardingPlan:
    name: str = "replicated"
    feed_specs: Dict[str, ShardSpec] = field(default_factory=dict)
    external_specs: Dict[int, ShardSpec] = field(default_factory=dict)

    def describe(self) -> str:
        parts = [f"plan={self.name}"]
        for n, s in self.feed_specs.items():
            parts.append(f"{n}:{s}")
        parts.append(f"{len(self.external_specs)} external spec(s)")
        return " ".join(parts)


def replicated_plan() -> ShardingPlan:
    return ShardingPlan(name="replicated")


def megatron_plan(graph: ShardGraph, mesh: MeshSpec,
                  tp_axis: str = "mp",
                  dp_axis: str = "dp") -> ShardingPlan:
    plan = ShardingPlan(name="megatron")
    tp = mesh.size(tp_axis) if mesh.has(tp_axis) else 1
    dp = mesh.size(dp_axis) if mesh.has(dp_axis) else 1

    # data parallel: shard dim 0 of every feed that divides — batch for
    # activations, (rows*blocks) for block tables, broadcast-aligned
    # leading dims for masks
    if dp > 1:
        for name, uid in graph.feeds.items():
            shape = graph.shape(uid)
            if shape and shape[0] % dp == 0 and shape[0] >= dp:
                plan.feed_specs[name] = ShardSpec.of(dp_axis)

    if tp <= 1:
        return plan

    externals = set(graph.externals)
    # forward taint scan: which uids carry tp-sharded content, and
    # whether their LAST dim is the tp-sharded one
    taint: Set[int] = set()
    lastdim_tp: Set[int] = set()
    for op in graph.ops:
        name = op.name.lower()
        t_ins = [u for u in op.in_uids if graph.shape(u)]
        is_mm = any(k in name for k in _MATMUL) and "fused" not in name
        w = None
        if is_mm and len(op.in_uids) >= 2:
            cand = op.in_uids[1]
            if cand in externals and len(graph.shape(cand)) == 2:
                w = cand
        if w is not None:
            act = op.in_uids[0]
            wsh = graph.shape(w)
            out = op.out_uids[0] if op.out_uids else None
            osh = graph.shape(out) if out is not None else ()
            if act in taint:
                # row-split: contraction dim sharded -> partial sum,
                # output whole again
                if wsh[0] % tp == 0 and w not in plan.external_specs:
                    plan.external_specs[w] = ShardSpec.of(tp_axis, None)
                if out is not None:
                    pass        # output untainted
            else:
                # column-split: output's last dim becomes tp-sharded
                if wsh[1] % tp == 0 and osh and osh[-1] % tp == 0 \
                        and w not in plan.external_specs:
                    plan.external_specs[w] = ShardSpec.of(None, tp_axis)
                    if out is not None:
                        taint.add(out)
                        lastdim_tp.add(out)
            continue
        # 1-D externals riding a tp-sharded last dim (bias, norm gain
        # applied after a column-split linear)
        if not is_mm and len(t_ins) >= 2:
            for u in t_ins:
                ush = graph.shape(u)
                if u in externals and len(ush) == 1 \
                        and u not in plan.external_specs:
                    others = [v for v in t_ins if v != u]
                    if any(v in lastdim_tp
                           and graph.shape(v)[-1:] == ush
                           for v in others) and ush[0] % tp == 0:
                        plan.external_specs[u] = ShardSpec.of(tp_axis)

        # generic taint flow
        tainted_in = any(u in taint for u in op.in_uids)
        if not tainted_in:
            continue
        for out in op.out_uids:
            taint.add(out)
        # track whether the last dim stays the tp-sharded one
        src = next((u for u in op.in_uids if u in taint), None)
        src_last = src in lastdim_tp
        for out in op.out_uids:
            osh = graph.shape(out)
            ish = graph.shape(src) if src is not None else ()
            if not osh:
                continue
            keep = False
            if op.name == "reshape" and ish:
                if len(osh) < len(ish):          # merge
                    keep = src_last or (osh[-1] % tp == 0
                                        and osh[-1] != ish[-1])
                elif len(osh) > len(ish):        # split
                    keep = False
                else:
                    keep = src_last
            elif op.name in ("transpose", "moveaxis", "swapaxes"):
                perm = op.attrs.get("perm")
                keep = bool(perm) and list(perm)[-1] == len(ish) - 1 \
                    and src_last
            elif len(osh) == len(ish):
                keep = src_last
            if keep:
                lastdim_tp.add(out)
    return plan


def plan_by_name(name: Optional[str], graph: ShardGraph,
                 mesh: MeshSpec) -> ShardingPlan:
    """CLI/driver entry: ``"replicated"`` | ``"megatron"``."""
    if name in (None, "", "replicated", "none"):
        return replicated_plan()
    if name == "megatron":
        return megatron_plan(graph, mesh)
    raise ValueError(
        f"unknown sharding plan {name!r} (want replicated|megatron)")
