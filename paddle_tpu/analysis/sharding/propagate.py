"""GSPMD-style static sharding propagation over a :class:`ShardGraph`.

Walks the recorded op list once, carrying a ``uid -> ShardSpec``
environment seeded from a :class:`~.plan.ShardingPlan`, and models how
each op transforms the sharding of its inputs — without compiling
anything.  Three kinds of output:

- **findings** — the PT9xx family.  PT901 (spec axis not on the mesh /
  one axis mapped to two dims) and PT903 (sharded dim not divisible —
  silent padding) fire on declared specs; PT902 fires when a
  producer's sharding contradicts what a consumer needs and the
  runtime would have to reshard implicitly, with the estimated
  all-gather bytes in the message; PT904 fires on redundant explicit
  collectives (all-reduce over an axis the operand is already
  replicated on, all-gather of an unsharded value).
- **comm events** — every modelled transfer (explicit collectives,
  implicit partial-sum all-reduces from contraction-dim sharding, and
  the resharding movements behind PT902), priced by
  ``cost_model.collective_bytes`` and tagged with the fabric tier
  (ICI vs DCN) of the mesh axes involved.  This is the communication
  volume the static auto-tuner ranks configs by.
- **per-op parallelism factors** — how many devices divide each op's
  compute, feeding the tuner's roofline estimate.

Partial sums are tracked explicitly: a matmul whose contraction dim is
sharded produces a *partial* value (Megatron row-parallel ``g``); an
explicit all-reduce consumes it silently, and any other consumer
triggers the implicit all-reduce the runtime would insert — charged as
an event, not flagged, because that is exactly the planned cost of
tensor parallelism.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..engine import Finding
from .graph import ShardGraph, ShardOp
from .spec import MeshSpec, ShardSpec, replicated, validate

__all__ = ["CommEvent", "ShardingReport", "propagate",
           "render_sharding_report", "COLLECTIVE_SET", "P2P_SET"]

COLLECTIVE_SET = frozenset({
    "all_reduce", "all_gather", "reduce_scatter", "all_to_all",
    "all_to_all_single", "broadcast", "scatter", "reduce"})
P2P_SET = frozenset({"send", "recv", "isend", "irecv"})

_ELEMENTWISE = frozenset({
    "add", "subtract", "multiply", "divide", "relu", "gelu", "silu",
    "sigmoid", "tanh", "exp", "log", "rsqrt", "sqrt", "pow", "abs",
    "neg", "maximum", "minimum", "cast", "scale", "dropout", "clip",
    "where", "swiglu", "fused_rope", "erf", "square"})
_MATMUL = ("matmul", "linear", "bmm", "dense", "fc")
_LASTDIM = frozenset({"softmax", "log_softmax", "rms_norm",
                      "layer_norm"})
_REDUCE_SUM = frozenset({"mean", "sum"})
_REDUCE_OTHER = frozenset({"max", "min", "prod", "argmax", "argmin",
                           "all", "any"})


def _collective_bytes(kind: str, nbytes: int, group_size: int) -> int:
    try:
        from ...cost_model import collective_bytes

        return collective_bytes(kind, nbytes, group_size)
    except Exception:
        # jax-free detached load without a cost_model module: the same
        # ring formulas, kept in sync with cost_model.collective_bytes
        n = max(int(group_size), 1)
        if n <= 1:
            return 0
        frac = (n - 1) / n
        if kind in ("all_reduce", "reduce"):
            return int(2 * nbytes * frac)
        if kind in ("all_gather", "reduce_scatter", "all_to_all",
                    "all_to_all_single", "reshard"):
            return int(nbytes * frac)
        return int(nbytes)


@dataclass
class CommEvent:
    op_index: int
    op_name: str
    kind: str                     # all_reduce | all_gather | reshard | ...
    axes: Tuple[str, ...]
    bytes: int
    tier: str = "ici"
    implicit: bool = False
    note: str = ""


@dataclass
class ShardingReport:
    name: str
    mesh: MeshSpec
    plan_name: str = "replicated"
    graph: Optional[ShardGraph] = None
    specs: Dict[int, ShardSpec] = field(default_factory=dict)
    partial: Dict[int, Tuple[str, ...]] = field(default_factory=dict)
    events: List[CommEvent] = field(default_factory=list)
    findings: List[Finding] = field(default_factory=list)
    op_parallel: Dict[int, int] = field(default_factory=dict)

    def sharded_nbytes(self, uid: int) -> int:
        if self.graph is None:
            return 0
        spec = self.specs.get(uid)
        shape = self.graph.shape(uid)
        item = self.graph.itemsize.get(uid, 4)
        if spec is None:
            return self.graph.nbytes(uid)
        return spec.shard_nbytes(shape, item, self.mesh)

    def comm_bytes(self, tier: Optional[str] = None) -> int:
        return sum(e.bytes for e in self.events
                   if tier is None or e.tier == tier)

    def comm_by_kind(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for e in self.events:
            out[e.kind] = out.get(e.kind, 0) + e.bytes
        return out


class _Propagator:
    def __init__(self, graph: ShardGraph, mesh: MeshSpec, plan):
        self.g = graph
        self.mesh = mesh
        self.plan = plan
        self.env: Dict[int, ShardSpec] = {}
        self.partial: Dict[int, Tuple[str, ...]] = {}
        self.findings: List[Finding] = []
        self.events: List[CommEvent] = []
        self.op_parallel: Dict[int, int] = {}

    # -- small helpers ----------------------------------------------------
    def _rank(self, uid: int) -> int:
        return len(self.g.shape(uid))

    def spec(self, uid: int) -> ShardSpec:
        s = self.env.get(uid)
        if s is None:
            s = replicated(self._rank(uid))
        return s

    def _nbytes_sharded(self, uid: int) -> int:
        return self.spec(uid).shard_nbytes(
            self.g.shape(uid), self.g.itemsize.get(uid, 4), self.mesh)

    def _tier(self, axes: Sequence[str]) -> str:
        return ("dcn" if any(self.mesh.tier(a) == "dcn" for a in axes)
                else "ici")

    def _axes_factor(self, axes: Sequence[str]) -> int:
        f = 1
        for a in axes:
            if self.mesh.has(a):
                f *= self.mesh.size(a)
        return f

    def _find(self, rule: str, sev: str, idx: int, msg: str, ctx: str):
        self.findings.append(Finding(
            rule, sev, f"program:{self.g.name}", idx + 1, 0, msg,
            line_text=ctx))

    def _event(self, op: Optional[ShardOp], kind: str,
               axes: Sequence[str], nbytes: int, implicit=False,
               note: str = ""):
        axes = tuple(axes)
        self.events.append(CommEvent(
            op_index=op.index if op else -1,
            op_name=op.name if op else "<seed>",
            kind=kind, axes=axes,
            bytes=_collective_bytes(kind, nbytes,
                                    self._axes_factor(axes)),
            tier=self._tier(axes), implicit=implicit, note=note))

    def _sanitize(self, spec: ShardSpec) -> ShardSpec:
        """Drop axes PT901 already flagged so propagation continues."""
        seen = set()
        dims = []
        for d in spec.dims:
            kept = []
            for a in d:
                if self.mesh.has(a) and a not in seen:
                    kept.append(a)
                    seen.add(a)
            dims.append(tuple(kept))
        return ShardSpec(dims=tuple(dims))

    def _set(self, op: ShardOp, uid: int, spec: ShardSpec):
        spec = spec.normalized(self._rank(uid))
        for rid, msg in validate(spec, self.g.shape(uid), self.mesh):
            if rid == "PT903":
                self._find(rid, "error", op.index,
                           f"output of op #{op.index} '{op.name}': {msg}",
                           op.name)
        self.env[uid] = spec

    def _gather_spec(self, op: ShardOp, uid: int, axes: Sequence[str],
                     note: str) -> ShardSpec:
        """Charge an all-gather of ``uid`` over ``axes`` and return its
        spec with those axes removed."""
        spec = self.spec(uid)
        axes = [a for a in axes if a in spec.axes()]
        if axes:
            self._event(op, "all_gather", axes, self.g.nbytes(uid),
                        implicit=True, note=note)
            for a in axes:
                spec = spec.drop_axis(a)
        return spec

    def _mismatch(self, op: ShardOp, uid: int, have: ShardSpec,
                  want: ShardSpec, why: str):
        """PT902: producer spec contradicts consumer expectation —
        quantify the implicit reshard and continue with ``want``."""
        moved = _collective_bytes(
            "reshard", self.g.nbytes(uid),
            max(have.factor(self.mesh), 2))
        self._find(
            "PT902", "warning", op.index,
            f"implicit reshard at op #{op.index} '{op.name}': input "
            f"uid {uid} arrives as {have} but {why} expects {want} — "
            f"~{moved / (1 << 20):.2f} MiB moved "
            f"(all-gather/all-to-all) every step", op.name)
        self._event(op, "reshard",
                    tuple(set(have.axes()) | set(want.axes())),
                    self.g.nbytes(uid), implicit=True,
                    note=f"PT902 uid {uid}")

    # -- driver -----------------------------------------------------------
    def run(self) -> ShardingReport:
        plan = self.plan
        for uid, label in self.g.seed_uids():
            spec = None
            if plan is not None:
                if label.startswith("feed:"):
                    spec = plan.feed_specs.get(label[5:])
                if spec is None:
                    spec = plan.external_specs.get(uid)
            spec = (spec or replicated()).normalized(self._rank(uid))
            for rid, msg in validate(spec, self.g.shape(uid), self.mesh):
                sev = "error" if rid in ("PT901", "PT903") else "warning"
                self._find(rid, sev, -1, f"{label}: {msg}", label)
            self.env[uid] = self._sanitize(spec)

        for op in self.g.ops:
            self._consume_partials(op)
            try:
                self._dispatch(op)
            except Exception:
                # a malformed entry must not kill the whole pass —
                # replicate its outputs and move on
                for u in op.out_uids:
                    self.env.setdefault(u, replicated(self._rank(u)))
            if op.index not in self.op_parallel:
                f = 1
                if op.out_uids:
                    f = self.spec(op.out_uids[0]).factor(self.mesh)
                self.op_parallel[op.index] = max(f, 1)

        rep = ShardingReport(
            name=self.g.name, mesh=self.mesh,
            plan_name=getattr(plan, "name", "replicated") if plan
            else "replicated",
            graph=self.g, specs=dict(self.env),
            partial=dict(self.partial), events=self.events,
            findings=self.findings, op_parallel=self.op_parallel)
        return rep

    def _consume_partials(self, op: ShardOp):
        """Any op other than an explicit reducing collective that reads
        a partial-sum value forces the implicit all-reduce the runtime
        would insert (Megatron row-parallel output meeting the residual
        add)."""
        if op.name in ("all_reduce", "reduce_scatter", "reduce"):
            return
        for u in op.in_uids:
            axes = self.partial.pop(u, None)
            if axes:
                self._event(op, "all_reduce", axes,
                            self._nbytes_sharded(u), implicit=True,
                            note=f"partial-sum uid {u} consumed by "
                                 f"'{op.name}'")

    # -- dispatch ---------------------------------------------------------
    def _dispatch(self, op: ShardOp):
        name = op.name.lower()
        if op.name in COLLECTIVE_SET:
            return self._rule_collective(op)
        if op.name in P2P_SET:
            return self._rule_p2p(op)
        if any(k in name for k in _MATMUL) and "fused" not in name:
            return self._rule_matmul(op)
        if op.name in _LASTDIM:
            return self._rule_lastdim(op)
        if op.name == "scaled_dot_product_attention":
            return self._rule_sdpa(op)
        if op.name in _REDUCE_SUM or op.name in _REDUCE_OTHER:
            return self._rule_reduce(op)
        if op.name == "reshape":
            return self._rule_reshape(op)
        if op.name in ("transpose", "moveaxis", "swapaxes"):
            return self._rule_transpose(op)
        if op.name == "index_select":
            return self._rule_index_select(op)
        if op.name in _ELEMENTWISE or name.startswith("fused_") \
                or name.startswith("recompute::"):
            return self._rule_elementwise(op)
        return self._rule_default(op)

    def _rule_default(self, op: ShardOp):
        """Unknown op: carry the first input's spec to same-rank
        outputs, replicate the rest.  Never flags."""
        src = op.in_uids[0] if op.in_uids else None
        src_spec = self.spec(src) if src is not None else replicated()
        for u in op.out_uids:
            if src is not None and self._rank(u) == self._rank(src):
                self._set(op, u, src_spec)
            else:
                self.env[u] = replicated(self._rank(u))

    # resolve one output dim across broadcasting inputs
    def _rule_elementwise(self, op: ShardOp):
        tensor_ins = [u for u in op.in_uids if self.g.shape(u)]
        for out in op.out_uids:
            oshape = self.g.shape(out)
            dims: List[Tuple[str, ...]] = []
            for j, dim in enumerate(oshape):
                cands: List[Tuple[int, Tuple[str, ...]]] = []
                for u in tensor_ins:
                    ishape = self.g.shape(u)
                    i = j - (len(oshape) - len(ishape))
                    if i < 0 or (ishape[i] == 1 and dim != 1):
                        continue
                    ax = self.spec(u).dim_axes(i)
                    if ax:
                        cands.append((u, ax))
                uniq = {ax for _, ax in cands}
                if len(uniq) <= 1:
                    dims.append(cands[0][1] if cands else ())
                    continue
                # conflict: keep the largest operand's sharding, the
                # runtime reshards the rest — PT902 each loser
                cands.sort(key=lambda c: -self.g.nbytes(c[0]))
                win_u, win_ax = cands[0]
                dims.append(win_ax)
                for u, ax in cands[1:]:
                    if ax != win_ax:
                        self._mismatch(
                            op, u, self.spec(u),
                            self.spec(win_u),
                            f"co-input uid {win_u} (dim {j})")
            # the resolved spec may double-map an axis across dims when
            # two inputs shard different dims on the same axis
            spec = self._dedup(op, ShardSpec(dims=tuple(dims)))
            self._set(op, out, spec)

    def _dedup(self, op: ShardOp, spec: ShardSpec) -> ShardSpec:
        seen = set()
        dims = []
        for d in spec.dims:
            kept = []
            for a in d:
                if a in seen:
                    continue
                kept.append(a)
                seen.add(a)
            dims.append(tuple(kept))
        return ShardSpec(dims=tuple(dims))

    def _rule_lastdim(self, op: ShardOp):
        """softmax / rms_norm / layer_norm: elementwise in shape, but
        internally reduce over one dim — that dim must be whole."""
        self._rule_elementwise(op)
        axis = op.attrs.get("axis", -1)
        for out in op.out_uids:
            rank = self._rank(out)
            if rank == 0:
                continue
            ax = axis % rank if isinstance(axis, int) else rank - 1
            spec = self.spec(out)
            shard_axes = spec.dim_axes(ax)
            if shard_axes:
                src = op.in_uids[0] if op.in_uids else out
                spec = self._gather_spec(
                    op, src, shard_axes,
                    f"{op.name} reduces dim {ax}")
                self._set(op, out, spec.normalized(rank))

    def _rule_sdpa(self, op: ShardOp):
        """(batch, seq, heads, head_dim) attention: batch/heads sharding
        flows through; seq or head_dim sharding needs a gather (no ring
        attention modelled here)."""
        self._rule_elementwise(op)
        for out in op.out_uids:
            rank = self._rank(out)
            spec = self.spec(out)
            bad = []
            for d in (1, rank - 1):
                if 0 <= d < rank:
                    bad.extend(spec.dim_axes(d))
            if bad:
                src = op.in_uids[0] if op.in_uids else out
                spec = self._gather_spec(
                    op, src, bad, "attention contracts seq/head_dim")
                self._set(op, out, spec.normalized(rank))

    def _rule_matmul(self, op: ShardOp):
        if len(op.in_uids) < 2 or not op.out_uids:
            return self._rule_default(op)
        a, b = op.in_uids[0], op.in_uids[1]
        out = op.out_uids[0]
        ash, bsh, osh = self.g.shape(a), self.g.shape(b), self.g.shape(out)
        if len(ash) < 2 or len(bsh) < 2 or not osh:
            return self._rule_default(op)
        aspec, bspec = self.spec(a), self.spec(b)

        # orientation: does B carry k on dim -2 (normal) or -1
        # (transpose_y)?  shape-matched; square B defaults to normal.
        k = ash[-1]
        if bsh[-2] == k and bsh[-1] == osh[-1]:
            bk_dim, bn_dim = len(bsh) - 2, len(bsh) - 1
        elif bsh[-1] == k and bsh[-2] == osh[-1]:
            bk_dim, bn_dim = len(bsh) - 1, len(bsh) - 2
        else:
            return self._rule_default(op)

        rank = len(osh)
        if rank < 2:
            return self._rule_default(op)
        dims: List[Tuple[str, ...]] = [() for _ in range(rank)]
        # batch dims (everything left of m/n) aligned right among the
        # batch portions of A and B; both sharded differently = PT902
        for j in range(rank - 2):
            ai = j - ((rank - 2) - (len(ash) - 2))
            a_ax = aspec.dim_axes(ai) if 0 <= ai < len(ash) - 2 else ()
            bi = j - ((rank - 2) - (len(bsh) - 2))
            b_ax = (bspec.dim_axes(bi)
                    if 0 <= bi < len(bsh) - 2 else ())
            if a_ax and b_ax and a_ax != b_ax:
                self._mismatch(op, b, bspec, aspec,
                               f"batch dim {j} of co-input uid {a}")
                b_ax = ()
            dims[j] = a_ax or b_ax
        # m dim from A, n dim from B
        dims[rank - 2] = aspec.dim_axes(len(ash) - 2)
        dims[rank - 1] = bspec.dim_axes(bn_dim)

        # contraction-dim agreement: equal (or one-sided) sharding
        # yields a partial sum; disagreement is an implicit reshard
        ak = aspec.dim_axes(len(ash) - 1)
        bk = bspec.dim_axes(bk_dim)
        partial_axes: Tuple[str, ...] = ()
        if ak and bk and set(ak) != set(bk):
            self._mismatch(op, b, bspec, aspec,
                           "contraction dim of co-input")
        else:
            partial_axes = tuple(dict.fromkeys(ak + bk))

        spec = self._dedup(op, ShardSpec(dims=tuple(dims)))
        self._set(op, out, spec)
        if partial_axes:
            self.partial[out] = partial_axes
        self.op_parallel[op.index] = max(
            1, spec.factor(self.mesh) * self._axes_factor(partial_axes))

    def _rule_reduce(self, op: ShardOp):
        if not op.in_uids or not op.out_uids:
            return self._rule_default(op)
        src, out = op.in_uids[0], op.out_uids[0]
        ish, osh = self.g.shape(src), self.g.shape(out)
        spec = self.spec(src)
        axis = op.attrs.get("axis")
        if isinstance(axis, int):
            reduced = [axis % len(ish)] if ish else []
        elif isinstance(axis, (list, tuple)):
            reduced = [a % len(ish) for a in axis]
        elif len(osh) == len(ish):
            reduced = [i for i in range(len(ish))
                       if osh[i] == 1 and ish[i] != 1]
        else:
            reduced = list(range(len(osh), len(ish)))
        red_axes: List[str] = []
        for d in reduced:
            red_axes.extend(spec.dim_axes(d))
        if len(osh) == len(ish):
            odims = [() if i in reduced else spec.dim_axes(i)
                     for i in range(len(ish))]
        else:
            odims = [spec.dim_axes(i) for i in range(len(osh))]
        self._set(op, out, ShardSpec(dims=tuple(odims)))
        if red_axes:
            if op.name in _REDUCE_SUM:
                self.partial[out] = tuple(dict.fromkeys(red_axes))
            else:
                # max/argmax over a sharded dim: gather the input
                self._gather_spec(op, src, red_axes,
                                  f"{op.name} over sharded dim")
        self.op_parallel[op.index] = max(
            1, self.spec(out).factor(self.mesh)
            * self._axes_factor(red_axes))

    def _rule_reshape(self, op: ShardOp):
        if not op.in_uids or not op.out_uids:
            return self._rule_default(op)
        src, out = op.in_uids[0], op.out_uids[0]
        ish, osh = self.g.shape(src), self.g.shape(out)
        spec = self.spec(src)
        if not ish or not osh:
            return self._rule_default(op)
        odims: List[Tuple[str, ...]] = [() for _ in osh]
        for ins, outs in _reshape_groups(ish, osh):
            sharded = [(pos, d) for pos, d in enumerate(ins)
                       if spec.dim_axes(d)]
            if not sharded:
                continue
            # the GROUP's leading dim shards contiguous blocks of the
            # flattened group, so its sharding carries to the group's
            # leading output dim when divisible; sharding on any later
            # dim is stride-interleaved after the regroup = gather
            lead_axes = spec.dim_axes(ins[0])
            keep_lead = bool(lead_axes) and (
                osh[outs[0]] % self._axes_factor(lead_axes) == 0)
            if keep_lead:
                odims[outs[0]] = lead_axes
            gather = []
            for pos, d in sharded:
                if keep_lead and pos == 0:
                    continue
                gather.extend(spec.dim_axes(d))
            if gather:
                self._gather_spec(op, src, gather,
                                  f"reshape {tuple(ish)}->{tuple(osh)} "
                                  f"regroups a sharded dim")
        self._set(op, out, ShardSpec(dims=tuple(odims)))

    def _rule_transpose(self, op: ShardOp):
        if not op.in_uids or not op.out_uids:
            return self._rule_default(op)
        src, out = op.in_uids[0], op.out_uids[0]
        ish, osh = self.g.shape(src), self.g.shape(out)
        spec = self.spec(src)
        perm = self._perm(op, ish, osh)
        if perm is None:
            if spec.is_replicated:
                self.env[out] = replicated(len(osh))
            else:
                s = self._gather_spec(op, src, spec.axes(),
                                      "ambiguous permutation of a "
                                      "sharded tensor")
                self._set(op, out, s.normalized(len(osh)))
            return
        self._set(op, out, ShardSpec(
            dims=tuple(spec.dim_axes(perm[j]) for j in range(len(osh)))))

    def _perm(self, op: ShardOp, ish, osh) -> Optional[List[int]]:
        perm = op.attrs.get("perm")
        if isinstance(perm, (list, tuple)) and len(perm) == len(ish):
            return [int(p) for p in perm]
        swap = op.attrs.get("swap")
        if isinstance(swap, (list, tuple)) and len(swap) == 2:
            p = list(range(len(ish)))
            i, j = int(swap[0]) % len(ish), int(swap[1]) % len(ish)
            p[i], p[j] = p[j], p[i]
            return p
        src_d = op.attrs.get("source")
        dst_d = op.attrs.get("destination")
        if isinstance(src_d, int) and isinstance(dst_d, int):
            p = list(range(len(ish)))
            v = p.pop(src_d % len(ish))
            p.insert(dst_d % len(ish), v)
            return p
        # infer from shapes when dim sizes are unique
        if sorted(ish) == sorted(osh) and len(set(ish)) == len(ish):
            remaining = list(enumerate(ish))
            perm = []
            for d in osh:
                for pos, (i, sz) in enumerate(remaining):
                    if sz == d:
                        perm.append(i)
                        remaining.pop(pos)
                        break
            return perm
        return None

    def _rule_index_select(self, op: ShardOp):
        if len(op.in_uids) < 2 or not op.out_uids:
            return self._rule_default(op)
        table, idx = op.in_uids[0], op.in_uids[1]
        out = op.out_uids[0]
        axis = int(op.attrs.get("axis", 0))
        tsh = self.g.shape(table)
        idx_rank = len(self.g.shape(idx))
        tspec, ispec = self.spec(table), self.spec(idx)
        axis = axis % len(tsh) if tsh else 0
        if tspec.dim_axes(axis):
            # gathering arbitrary rows of a row-sharded table needs the
            # whole table on every shard
            tspec = self._gather_spec(
                op, table, tspec.dim_axes(axis),
                "index_select over the sharded dim")
        dims: List[Tuple[str, ...]] = []
        for d in range(axis):
            dims.append(tspec.dim_axes(d))
        for d in range(idx_rank):
            dims.append(ispec.dim_axes(d))
        for d in range(axis + 1, len(tsh)):
            dims.append(tspec.dim_axes(d))
        self._set(op, out, self._dedup(op, ShardSpec(dims=tuple(dims))))

    # -- explicit collectives --------------------------------------------
    def _meta(self, op: ShardOp):
        m = self.g.meta_for(op.index) or {}
        axis = m.get("axis")
        size = m.get("axis_size")
        if size is None and m.get("ranks"):
            size = len(m["ranks"])
        if size is None and axis and self.mesh.has(axis):
            size = self.mesh.size(axis)
        is_world = axis in (None, "world") or \
            str(axis or "").startswith("group_")
        return axis, (int(size) if size else self.mesh.n_devices), is_world

    def _rule_collective(self, op: ShardOp):
        axis, size, is_world = self._meta(op)
        src = op.in_uids[0] if op.in_uids else None
        out = op.out_uids[0] if op.out_uids else None
        spec = self.spec(src) if src is not None else replicated()
        nb = self._nbytes_sharded(src) if src is not None else 0
        axes = (axis,) if (axis and self.mesh.has(axis)) else ()
        ctx = f"{op.name}@{axis or 'world'}"

        if op.name in ("all_reduce", "reduce"):
            part = self.partial.get(src) if src is not None else None
            consumed = part and (is_world or (axis in part))
            if consumed:
                self.partial.pop(src, None)
            if not consumed and not is_world and src is not None \
                    and axis not in spec.axes() and not part:
                self._find(
                    "PT904", "warning", op.index,
                    f"all_reduce over axis '{axis}' but its operand is "
                    f"already replicated on that axis (no partial sum, "
                    f"no sharding) — the collective moves "
                    f"~{_collective_bytes('all_reduce', nb, size) / (1 << 20):.2f} "
                    f"MiB to reproduce the same value", ctx)
            self.events.append(CommEvent(
                op.index, op.name, "all_reduce",
                axes or ("world",),
                _collective_bytes("all_reduce", nb, size),
                tier=self._tier(axes), note=ctx))
            if out is not None:
                self._set(op, out, spec)
        elif op.name == "all_gather":
            if src is not None and axis and axis in spec.axes():
                spec = spec.drop_axis(axis)
            elif src is not None and not is_world:
                self._find(
                    "PT904", "warning", op.index,
                    f"all_gather over axis '{axis}' but its operand is "
                    f"not sharded on that axis — every device already "
                    f"holds the full value (redundant collective)", ctx)
            self.events.append(CommEvent(
                op.index, op.name, "all_gather", axes or ("world",),
                _collective_bytes("all_gather", self.g.nbytes(src)
                                  if src is not None else 0, size),
                tier=self._tier(axes), note=ctx))
            if out is not None:
                self._set(op, out, spec.normalized(self._rank(out)))
        elif op.name == "reduce_scatter":
            if src is not None:
                self.partial.pop(src, None)
            if out is not None and axes:
                osh = self.g.shape(out)
                if osh and osh[0] % self._axes_factor(axes) == 0:
                    spec = spec.normalized(len(osh)).with_dim(
                        0, spec.dim_axes(0) + axes)
            self.events.append(CommEvent(
                op.index, op.name, "reduce_scatter", axes or ("world",),
                _collective_bytes("reduce_scatter", nb, size),
                tier=self._tier(axes), note=ctx))
            if out is not None:
                self._set(op, out, spec.normalized(self._rank(out)))
        else:   # all_to_all / broadcast / scatter
            self.events.append(CommEvent(
                op.index, op.name, op.name, axes or ("world",),
                _collective_bytes(op.name, nb, size),
                tier=self._tier(axes), note=ctx))
            if out is not None and src is not None:
                self._set(op, out, spec.normalized(self._rank(out)))
            elif out is not None:
                self.env[out] = replicated(self._rank(out))

    def _rule_p2p(self, op: ShardOp):
        src = op.in_uids[0] if op.in_uids else None
        nb = self._nbytes_sharded(src) if src is not None else 0
        self.events.append(CommEvent(
            op.index, op.name, "p2p", (), nb, tier="ici",
            note=op.name))
        self._rule_default(op)


def _reshape_groups(ish: Sequence[int], osh: Sequence[int]):
    """Two-pointer factor grouping: yields (in_dims, out_dims) index
    lists whose products match — the unit sharding can (or cannot)
    carry across."""
    groups = []
    i = j = 0
    ni, nj = len(ish), len(osh)
    while i < ni and j < nj:
        a, b = int(ish[i]), int(osh[j])
        ins, outs = [i], [j]
        while a != b:
            if a < b:
                i += 1
                if i >= ni:
                    break
                ins.append(i)
                a *= int(ish[i])
            else:
                j += 1
                if j >= nj:
                    break
                outs.append(j)
                b *= int(osh[j])
        groups.append((ins, outs))
        i += 1
        j += 1
    # trailing size-1 dims attach to the last group
    if groups:
        while i < ni:
            groups[-1][0].append(i)
            i += 1
        while j < nj:
            groups[-1][1].append(j)
            j += 1
    return groups


def propagate(graph: ShardGraph, mesh: MeshSpec,
              plan=None) -> ShardingReport:
    """Run sharding propagation over ``graph`` on ``mesh`` under
    ``plan`` (None = everything replicated: the conservative baseline
    that can only flag explicit-collective redundancy)."""
    return _Propagator(graph, mesh, plan).run()


def _fmt_bytes(n: int) -> str:
    for unit, div in (("GiB", 1 << 30), ("MiB", 1 << 20),
                      ("KiB", 1 << 10)):
        if n >= div:
            return f"{n / div:.2f} {unit}"
    return f"{n} B"


def render_sharding_report(rep: ShardingReport, top: int = 8) -> str:
    lines = [f"sharding report — {rep.name} on mesh "
             f"[{rep.mesh.describe()}] plan={rep.plan_name}",
             f"  comm volume   : {_fmt_bytes(rep.comm_bytes())} / step "
             f"(ici {_fmt_bytes(rep.comm_bytes('ici'))}, "
             f"dcn {_fmt_bytes(rep.comm_bytes('dcn'))})"]
    by_kind = rep.comm_by_kind()
    if by_kind:
        kinds = ", ".join(f"{k}={_fmt_bytes(v)}"
                          for k, v in sorted(by_kind.items()))
        lines.append(f"  by kind       : {kinds}")
    ev = sorted(rep.events, key=lambda e: -e.bytes)[:top]
    if ev:
        lines.append("  largest transfers:")
        for e in ev:
            tag = "implicit" if e.implicit else "explicit"
            lines.append(
                f"    op #{e.op_index:<3d} {e.op_name:<24s} {e.kind:<14s}"
                f" {_fmt_bytes(e.bytes):>10s}  [{e.tier}/{tag}]"
                + (f"  {e.note}" if e.note else ""))
    n_err = sum(1 for f in rep.findings if f.severity == "error")
    lines.append(f"  findings      : {len(rep.findings)} "
                 f"({n_err} error)")
    return "\n".join(lines)
