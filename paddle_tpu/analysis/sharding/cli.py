"""ptshard CLI: sharding propagation (PT9xx) over serialized graphs,
with no jax in the process.

Inputs are ``ShardGraph`` JSON files (``ShardGraph.to_json`` — the
capture side needs jax once; this side never does).  Multiple graphs
with ``--pipeline`` are treated as consecutive pipeline stages and get
the PT905 boundary check.  Shares the ptlint reporters
(``--format text|json|sarif``) and the committed
``.ptlint-baseline.json`` grandfather workflow.

For captures living in presets (llama, mlp, decode) use the framework
route instead: ``python -m paddle_tpu.analysis --program llama
--families PT9`` (jax required there for abstract evaluation).
"""
from __future__ import annotations

import argparse
import os
import sys
from typing import List

from .. import engine
from .graph import ShardGraph
from .pipeline import check_stage_boundaries
from .plan import plan_by_name
from .propagate import propagate, render_sharding_report
from .spec import MeshSpec

__all__ = ["main"]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="ptshard",
        description="static sharding-propagation analysis (PT901 bad "
                    "axis, PT902 implicit reshard, PT903 divisibility, "
                    "PT904 redundant collective, PT905 stage boundary)")
    ap.add_argument("graphs", nargs="+", metavar="GRAPH.json",
                    help="serialized ShardGraph file(s)")
    ap.add_argument("--mesh", default="dp=2,mp=2", metavar="SPEC",
                    help="mesh, e.g. 'dp=2,mp=4' or two-tier "
                         "'dp=2@dcn,mp=4' (default: dp=2,mp=2)")
    ap.add_argument("--plan", default="megatron",
                    choices=("megatron", "replicated"))
    ap.add_argument("--pipeline", action="store_true",
                    help="treat the graphs as consecutive pipeline "
                         "stages and check boundaries (PT905)")
    ap.add_argument("--format", choices=("text", "json", "sarif"),
                    default="text")
    ap.add_argument("--report", action="store_true",
                    help="print the full per-graph sharding report "
                         "(comm volume, largest transfers; text format)")
    ap.add_argument("--select", action="append", default=None,
                    metavar="RULE")
    ap.add_argument("--families", default="PT9", metavar="FAMS",
                    help="comma list of rule families (default: PT9)")
    ap.add_argument("--baseline", default=None)
    ap.add_argument("--no-baseline", action="store_true")
    ap.add_argument("--write-baseline", action="store_true")
    ap.add_argument("--update-baseline", action="store_true",
                    help="prune baseline entries whose findings no "
                         "longer fire and exit 0")
    args = ap.parse_args(argv)

    select = list(args.select or [])
    if args.families:
        select += [f"{fam.strip()}xx" for fam in args.families.split(",")
                   if fam.strip()]
    select = select or None

    try:
        mesh = MeshSpec.parse(args.mesh)
    except ValueError as e:
        print(f"ptshard: bad --mesh: {e}", file=sys.stderr)
        return 2

    graphs: List[ShardGraph] = []
    for path in args.graphs:
        if not os.path.isfile(path):
            print(f"ptshard: no such file: {path}", file=sys.stderr)
            return 2
        with open(path) as f:
            try:
                graphs.append(ShardGraph.from_json(f.read()))
            except Exception as e:
                print(f"ptshard: {path}: not a ShardGraph JSON ({e})",
                      file=sys.stderr)
                return 2

    findings, reports = [], []
    plans = [plan_by_name(args.plan, g, mesh) for g in graphs]
    for g, plan in zip(graphs, plans):
        rep = propagate(g, mesh, plan=plan)
        reports.append(rep)
        findings.extend(rep.findings)
    if args.pipeline and len(graphs) > 1:
        findings.extend(check_stage_boundaries(graphs, mesh, plans=plans,
                                               reports=reports))

    baseline = None
    if not args.no_baseline and not args.write_baseline:
        baseline = args.baseline or engine.find_baseline(os.getcwd())
        if baseline and not os.path.isfile(baseline):
            baseline = None

    report = engine.apply_baseline_and_select(
        findings, baseline, select, files=len(graphs))

    if args.write_baseline:
        target = args.baseline or os.path.join(os.getcwd(),
                                               engine.BASELINE_NAME)
        engine.write_baseline(target, report.findings)
        print(f"ptshard: wrote {len(report.findings)} entr"
              f"{'y' if len(report.findings) == 1 else 'ies'} to "
              f"{target}")
        return 0

    if args.update_baseline:
        if not baseline:
            print("ptshard: --update-baseline needs an existing "
                  "baseline", file=sys.stderr)
            return 2
        n_before = sum(engine.load_baseline(baseline).values())
        engine.write_baseline(baseline, report.baselined)
        pruned = n_before - len(report.baselined)
        print(f"ptshard: baseline {baseline}: kept "
              f"{len(report.baselined)} live entr"
              f"{'y' if len(report.baselined) == 1 else 'ies'}, pruned "
              f"{pruned} stale")
        return 0

    if args.format == "json":
        out = engine.render_json(report)
    elif args.format == "sarif":
        out = engine.render_sarif(report, tool_name="ptshard")
    else:
        out = engine.render_text(report, tool_name="ptshard")
        if args.report:
            out = "\n".join([out] + [render_sharding_report(r)
                                     for r in reports])
    print(out)
    return report.exit_code
