"""ptshard — static sharding-propagation analysis (the PT9xx family).

Propagates PartitionSpec-style annotations op-by-op through a recorded
``static.Program`` on a declared mesh — without compiling — and turns
the classic silent-perf-loss classes into CI-gated findings:

- **PT901** spec axis not on the mesh / one axis mapped to two dims
- **PT902** implicit reshard at a producer→consumer sharding mismatch
  (message quantifies the estimated all-gather/all-to-all bytes)
- **PT903** sharded dim not divisible by its mesh-axis size (silent
  padding)
- **PT904** redundant collective (all-reduce over an axis the operand
  is already replicated on; all-gather of an unsharded value)
- **PT905** pipeline-stage boundary sharding mismatch (composes with
  ptprog's ``check_pipeline``)

The same propagation yields per-step communication volume (tiered
ICI/DCN) and per-op parallelism factors — the inputs
``distributed.auto_tuner.static_tuner`` ranks TP×PP×sharding configs
with.  Core modules (`spec`, `graph`, `propagate`, `plan`, `pipeline`)
are stdlib-only so ``tools/ptshard.py`` runs jax-free on serialized
graphs; only :func:`graph_from_program` needs the framework.
"""
from __future__ import annotations

from .graph import ShardGraph, ShardOp, graph_from_ir
from .pipeline import check_stage_boundaries
from .plan import (ShardingPlan, megatron_plan, plan_by_name,
                   replicated_plan)
from .propagate import (CommEvent, ShardingReport, propagate,
                        render_sharding_report)
from .spec import MeshSpec, ShardSpec, parse_spec, replicated

__all__ = [
    "MeshSpec", "ShardSpec", "parse_spec", "replicated",
    "ShardGraph", "ShardOp", "graph_from_ir", "graph_from_program",
    "ShardingPlan", "replicated_plan", "megatron_plan", "plan_by_name",
    "CommEvent", "ShardingReport", "propagate",
    "render_sharding_report", "check_stage_boundaries",
    "check_sharding",
]


def graph_from_program(program, feed_spec=None,
                       name: str = "program") -> ShardGraph:
    """Capture-time bridge: Program -> abstract dataflow -> jax-free
    ShardGraph (the only entry point here that needs jax)."""
    from ..program.dataflow import abstract_run
    from ..program.ir import ProgramIR

    ir = ProgramIR(program, feed_spec=feed_spec, name=name)
    env, _findings = abstract_run(ir)
    return graph_from_ir(ir, env)


def check_sharding(ir, env, mesh, plan=None):
    """The ``analyze()`` pass entry: ProgramIR + abstract env + mesh ->
    (findings, ShardingReport).  ``plan`` is a ShardingPlan or a plan
    name ("replicated" | "megatron"); ``mesh`` is a MeshSpec, a jax
    Mesh, or a parseable string."""
    graph = graph_from_ir(ir, env)
    if isinstance(mesh, str):
        mesh_spec = MeshSpec.parse(mesh)
    else:
        mesh_spec = MeshSpec.from_mesh(mesh)
    if mesh_spec is None:
        return [], None
    if plan is None or isinstance(plan, str):
        plan = plan_by_name(plan, graph, mesh_spec)
    rep = propagate(graph, mesh_spec, plan)
    return list(rep.findings), rep
