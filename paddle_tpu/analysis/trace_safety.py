"""PT1xx — trace-safety rules for ``@to_static``-reachable functions.

Cross-referenced with jit/api.py's graph-break machinery: a traced
function runs ONCE under jax tracing (``StaticFunction._run_compiled``),
and the constructs flagged here either raise one of
``_trace_break_errors()`` (TracerBoolConversionError /
ConcretizationTypeError / ...) — demoting the whole callable to eager
with a RuntimeWarning — or, worse, trace *silently wrong*: a ``print``
fires once at trace time and never again, ``time.time()`` freezes the
timestamp of the first trace into the compiled graph forever, and
``random.random()`` bakes one sample in as a constant.

Reachability is static and module-local: functions decorated with
``to_static`` (any dotted form), functions passed to a ``to_static(...)``
call, plus everything they call *within the same module* (fixpoint).
That is deliberately narrower than true reachability — cross-module
tracing is gated at runtime by ``jit/graph_break_count`` — but it is
exact for the kernel of the problem: the function the user handed to the
compiler.
"""
from __future__ import annotations

import ast

from .engine import call_name, names_in, rule

_WALLCLOCK_ATTRS = {"time", "perf_counter", "monotonic", "process_time",
                    "perf_counter_ns", "time_ns", "monotonic_ns"}


def _is_to_static_ref(node) -> bool:
    """`to_static`, `jit.to_static`, `paddle.jit.to_static`, ..."""
    if isinstance(node, ast.Name):
        return node.id == "to_static"
    if isinstance(node, ast.Attribute):
        return node.attr == "to_static"
    return False


def reachable_functions(mod):
    """FunctionDefs that to_static can trace, module-locally: decorated
    ones, ones passed to a to_static(...) call, and their same-module
    callees (transitive closure). Cached on the module — every PT1xx
    rule shares one traversal."""
    cached = getattr(mod, "_pt_reachable", None)
    if cached is not None:
        return cached
    roots = set()
    for fn in mod.functions.values():
        for dec in fn.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            if _is_to_static_ref(target):
                roots.add(fn.name)
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call) and _is_to_static_ref(node.func):
            for arg in node.args[:1]:
                if isinstance(arg, ast.Name) and arg.id in mod.functions:
                    roots.add(arg.id)
    # fixpoint over same-module calls
    frontier = list(roots)
    while frontier:
        name = frontier.pop()
        fn = mod.functions.get(name)
        if fn is None:
            continue
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                cn = call_name(node)
                if cn in mod.functions and cn not in roots:
                    roots.add(cn)
                    frontier.append(cn)
    result = [mod.functions[n] for n in sorted(roots)
              if n in mod.functions]
    mod._pt_reachable = result
    return result


def _param_names(fn: ast.FunctionDef) -> set:
    a = fn.args
    names = {p.arg for p in a.args + a.posonlyargs + a.kwonlyargs}
    if a.vararg:
        names.add(a.vararg.arg)
    if a.kwarg:
        names.add(a.kwarg.arg)
    names.discard("self")
    names.discard("cls")
    return names


def _walk_body(fn):
    """Walk a function body including nested defs (they trace too when
    called), excluding the decorator list and signature defaults."""
    for stmt in fn.body:
        yield from ast.walk(stmt)


@rule("PT101", "warning",
      "print() inside a to_static-reachable function fires once at "
      "trace time, not per step")
def check_print(mod):
    for fn in reachable_functions(mod):
        for node in _walk_body(fn):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Name) and \
                    node.func.id == "print":
                yield (node.lineno, node.col_offset,
                       f"'print' in traced function '{fn.name}' executes "
                       f"once at trace time and is absent from the "
                       f"compiled graph; use jax.debug.print or log "
                       f"outside the traced region")


@rule("PT102", "warning",
      "wall-clock read inside a traced function is frozen at trace time")
def check_wallclock(mod):
    for fn in reachable_functions(mod):
        for node in _walk_body(fn):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id == "time" and \
                    node.func.attr in _WALLCLOCK_ATTRS:
                yield (node.lineno, node.col_offset,
                       f"'time.{node.func.attr}()' in traced function "
                       f"'{fn.name}' is evaluated once at trace time and "
                       f"baked into the graph as a constant")


@rule("PT103", "error",
      "host RNG inside a traced function bakes one sample into the graph")
def check_host_rng(mod):
    for fn in reachable_functions(mod):
        for node in _walk_body(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            is_py_random = (isinstance(f, ast.Attribute)
                            and isinstance(f.value, ast.Name)
                            and f.value.id == "random")
            is_np_random = (isinstance(f, ast.Attribute)
                            and isinstance(f.value, ast.Attribute)
                            and f.value.attr == "random"
                            and isinstance(f.value.value, ast.Name)
                            and f.value.value.id in ("np", "numpy"))
            if is_py_random or is_np_random:
                yield (node.lineno, node.col_offset,
                       f"host RNG call in traced function '{fn.name}' "
                       f"samples once at trace time; use "
                       f"paddle_tpu.framework.random (traced PRNG keys) "
                       f"instead")


@rule("PT104", "error",
      "nonlocal/global mutation inside a traced function is a hidden "
      "side effect the compiled graph replays never")
def check_nonlocal_mutation(mod):
    for fn in reachable_functions(mod):
        declared = set()
        for node in _walk_body(fn):
            if isinstance(node, (ast.Nonlocal, ast.Global)):
                declared.update(node.names)
        if not declared:
            continue
        for node in _walk_body(fn):
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            for t in targets:
                if isinstance(t, ast.Name) and t.id in declared:
                    yield (node.lineno, node.col_offset,
                           f"assignment to nonlocal/global '{t.id}' in "
                           f"traced function '{fn.name}' happens at "
                           f"trace time only; compiled calls never "
                           f"update it")


@rule("PT105", "error",
      ".numpy() inside a traced function forces a device sync and "
      "breaks the trace")
def check_numpy_call(mod):
    for fn in reachable_functions(mod):
        for node in _walk_body(fn):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "numpy" and not node.args:
                yield (node.lineno, node.col_offset,
                       f"'.numpy()' in traced function '{fn.name}' "
                       f"concretizes a tracer "
                       f"(ConcretizationTypeError -> graph break, see "
                       f"jit/api.py _trace_break_errors)")


@rule("PT106", "error",
      "float()/int()/bool() of a tensor argument concretizes the tracer")
def check_scalar_coercion(mod):
    for fn in reachable_functions(mod):
        params = _param_names(fn)
        if not params:
            continue
        for node in _walk_body(fn):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Name) and \
                    node.func.id in ("float", "int", "bool") and \
                    len(node.args) == 1 and \
                    names_in(node.args[0]) & params:
                yield (node.lineno, node.col_offset,
                       f"'{node.func.id}(...)' over argument data in "
                       f"traced function '{fn.name}' raises "
                       f"TracerBoolConversionError/Concretization at "
                       f"trace time (jit/api.py graph break); keep the "
                       f"value on-device or mark the argument static")


@rule("PT107", "error",
      "data-dependent Python if/while on tensor arguments breaks tracing")
def check_data_dependent_branch(mod):
    for fn in reachable_functions(mod):
        params = _param_names(fn)
        if not params:
            continue
        for node in _walk_body(fn):
            if isinstance(node, (ast.If, ast.While)) and \
                    names_in(node.test) & params:
                kind = "if" if isinstance(node, ast.If) else "while"
                yield (node.lineno, node.col_offset,
                       f"data-dependent '{kind}' on arguments of traced "
                       f"function '{fn.name}': concrete branching on a "
                       f"tracer raises TracerBoolConversionError and "
                       f"falls back to eager (or dy2static retry); use "
                       f"lax.cond/jnp.where")
