"""ptlint rule engine — AST-based static analysis for paddle_tpu.

The three silent failure classes this framework is most exposed to are
invisible to runtime tests until they run on real hardware:

- Python that breaks ``@to_static`` tracing (jit/api.py can only *count*
  graph breaks after the fact, via ``jit/graph_break_count``);
- collectives issued under rank-dependent control flow (an SPMD deadlock
  that only manifests on a multi-host mesh);
- Pallas grid arithmetic that floor-truncates (the varlen-attention bug:
  ``grid = seq // block`` with a block that merely *fits* silently drops
  the trailing ``seq % block`` tokens).

ptlint moves all three — plus registry/metrics drift — into a CI check
that fails in seconds.  This module is the engine: rule registry with
stable IDs (PT1xx trace-safety, PT2xx SPMD-collective ordering, PT3xx
Pallas kernel contracts, PT4xx registry consistency, PT5xx
error-surfacing in distributed/, PT7xx lock-consistency races, PT8xx
fleet-protocol invariants — the last two are the ptrace surface,
analysis/concurrency/), severities,
``# ptlint: disable=PTxxx`` line suppressions, text + JSON reporters, and
a committed-baseline workflow for grandfathered findings.

Deliberately stdlib-only (``ast`` + ``json``): the linter never imports
the code it checks, so it runs in milliseconds and can't be broken by a
bug it is trying to find.
"""
from __future__ import annotations

import ast
import fnmatch
import json
import os
import re
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Tuple

__all__ = ["Finding", "Rule", "rule", "all_rules", "ModuleInfo",
           "Project", "run", "load_baseline", "write_baseline",
           "render_text", "render_json", "render_sarif",
           "PTPROG_RULES", "BASELINE_NAME"]

BASELINE_NAME = ".ptlint-baseline.json"

_SUPPRESS_RE = re.compile(r"#\s*ptlint:\s*disable=([A-Za-z0-9_,\sx]+)")
_SUPPRESS_FILE_RE = re.compile(
    r"#\s*ptlint:\s*disable-file=([A-Za-z0-9_,\sx]+)")


@dataclass(frozen=True)
class Finding:
    rule_id: str
    severity: str            # "error" | "warning"
    path: str                # relative, forward slashes
    line: int                # 1-based
    col: int
    message: str
    line_text: str = ""      # stripped source line (baseline fingerprint)
    # optional (path, line, message) triples pointing at the sites that
    # explain this finding (the guarded write a race skips, both edges
    # of a lock cycle); rendered as SARIF relatedLocations
    related: Tuple = ()

    def key(self) -> Tuple[str, str, str]:
        """Line-number-free identity used for baseline matching — stable
        across unrelated edits that only shift the file."""
        return (self.rule_id, self.path, self.line_text)

    def to_dict(self) -> dict:
        d = {"id": self.rule_id, "severity": self.severity,
             "path": self.path, "line": self.line, "col": self.col,
             "message": self.message}
        if self.related:
            d["related"] = [{"path": p, "line": ln, "message": m}
                            for p, ln, m in self.related]
        return d


@dataclass
class Rule:
    rule_id: str
    severity: str
    summary: str
    scope: str               # "file" | "project"
    fn: Callable


_RULES: Dict[str, Rule] = {}


def rule(rule_id: str, severity: str, summary: str, scope: str = "file"):
    """Register a rule. File-scope rules receive one ModuleInfo and yield
    (line, col, message[, related]); project-scope rules receive the
    Project and yield (module, line, col, message[, related]), where the
    optional `related` is a tuple of (path, line, message) triples."""
    assert severity in ("error", "warning"), severity
    assert scope in ("file", "project"), scope

    def deco(fn):
        _RULES[rule_id] = Rule(rule_id, severity, summary, scope, fn)
        return fn

    return deco


def all_rules() -> Dict[str, Rule]:
    _load_rule_modules()
    return dict(_RULES)


def _load_rule_modules():
    # import for side effect of @rule registration; idempotent
    from . import collective_rules  # noqa: F401
    from . import pallas_rules      # noqa: F401
    from . import registry_rules    # noqa: F401
    from . import resilience_rules  # noqa: F401
    from . import trace_safety      # noqa: F401
    from .concurrency import protocol_rules  # noqa: F401
    from .concurrency import race_rules      # noqa: F401


class ModuleInfo:
    """One parsed file: AST plus the derived tables every rule needs."""

    def __init__(self, path: str, relpath: str, src: str):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.src = src
        self.lines = src.splitlines()
        self.tree = ast.parse(src)
        # parent links (ast has none); used for "is X inside Y" queries
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                child._pt_parent = node  # type: ignore[attr-defined]
        # all function defs by name, module-wide (innermost wins on clash
        # — rules only need a representative body to inspect)
        self.functions: Dict[str, ast.FunctionDef] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions.setdefault(node.name, node)
        # line -> set of suppressed rule ids / family patterns; plus a
        # whole-file set from `# ptlint: disable-file=PTxxx` directives
        self.suppressions: Dict[int, set] = {}
        self.file_suppressions: set = set()
        for i, line in enumerate(self.lines, 1):
            m = _SUPPRESS_FILE_RE.search(line)
            if m:
                self.file_suppressions |= {
                    s.strip() for s in m.group(1).split(",") if s.strip()}
                continue
            m = _SUPPRESS_RE.search(line)
            if m:
                ids = {s.strip() for s in m.group(1).split(",") if s.strip()}
                self.suppressions[i] = ids

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def suppressed(self, rule_id: str, lineno: int) -> bool:
        for ids in (self.file_suppressions,
                    self.suppressions.get(lineno) or ()):
            if not ids:
                continue
            if rule_id in ids or "all" in ids:
                return True
            # family form: disable=PT1xx covers PT101..PT199
            for pat in ids:
                if pat.endswith("xx") and rule_id.startswith(pat[:-2]):
                    return True
        return False

    def enclosing_function(self, node) -> Optional[ast.FunctionDef]:
        cur = getattr(node, "_pt_parent", None)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = getattr(cur, "_pt_parent", None)
        return None


class Project:
    """The full analyzed file set — what project-scope rules see."""

    def __init__(self, modules: List[ModuleInfo], root: str):
        self.modules = modules
        self.root = root


def iter_py_files(paths: Iterable[str]) -> List[str]:
    out = []
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isfile(p):
            out.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", ".git", ".ptlint")]
            for f in sorted(filenames):
                if f.endswith(".py"):
                    out.append(os.path.join(dirpath, f))
    return out


def _common_root(paths: List[str]) -> str:
    if not paths:
        return os.getcwd()
    root = os.path.commonpath([os.path.abspath(p) for p in paths])
    if os.path.isfile(root):
        root = os.path.dirname(root)
    return root


def find_baseline(start: str) -> Optional[str]:
    """Walk up from `start` looking for the committed baseline file."""
    cur = os.path.abspath(start)
    if os.path.isfile(cur):
        cur = os.path.dirname(cur)
    while True:
        cand = os.path.join(cur, BASELINE_NAME)
        if os.path.isfile(cand):
            return cand
        nxt = os.path.dirname(cur)
        if nxt == cur:
            return None
        cur = nxt


def load_baseline(path: str) -> Dict[Tuple[str, str, str], int]:
    """Baseline as a multiset of (rule_id, path, line_text) keys."""
    with open(path) as f:
        data = json.load(f)
    counts: Dict[Tuple[str, str, str], int] = {}
    for e in data.get("entries", []):
        k = (e["id"], e["path"], e.get("context", ""))
        counts[k] = counts.get(k, 0) + 1
    return counts


def write_baseline(path: str, findings: List[Finding]):
    entries = [{"id": f.rule_id, "path": f.path, "context": f.line_text}
               for f in sorted(findings,
                               key=lambda f: (f.path, f.line, f.rule_id))]
    with open(path, "w") as f:
        json.dump({"version": 1,
                   "comment": "grandfathered ptlint findings; regenerate "
                              "with: python -m paddle_tpu.analysis <paths> "
                              "--write-baseline",
                   "entries": entries}, f, indent=1)
        f.write("\n")


@dataclass
class Report:
    findings: List[Finding] = field(default_factory=list)   # active
    baselined: List[Finding] = field(default_factory=list)
    suppressed: int = 0
    files: int = 0
    parse_errors: List[str] = field(default_factory=list)

    @property
    def exit_code(self) -> int:
        return 1 if self.findings else 0


def apply_baseline_and_select(findings: List[Finding],
                              baseline: Optional[str],
                              select: Optional[Iterable[str]],
                              files: int = 1) -> Report:
    """Fold pre-computed findings (ptprog/ptshard: the rules ran outside
    the AST walk) through the shared select filter and the grandfather
    baseline, producing a Report the reporters render unchanged."""
    report = Report(files=files)
    sel = list(select) if select is not None else None

    def selected(rid):
        if sel is None:
            return True
        return any(rid == s or (s.endswith("xx") and rid.startswith(s[:-2]))
                   for s in sel)

    base_counts = load_baseline(baseline) if baseline else {}
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule_id)):
        if not selected(f.rule_id):
            continue
        k = f.key()
        if base_counts.get(k, 0) > 0:
            base_counts[k] -= 1
            report.baselined.append(f)
        else:
            report.findings.append(f)
    return report


def run(paths: Iterable[str], baseline: Optional[str] = None,
        select: Optional[Iterable[str]] = None) -> Report:
    """Lint `paths` (files or directories). `baseline` is a path to a
    baseline JSON (entries there are reported separately and do not fail
    the run). `select` optionally restricts to the given rule ids or
    family patterns (e.g. "PT3xx")."""
    _load_rule_modules()
    files = iter_py_files(paths)
    root = _common_root(files)
    # relpaths are anchored at the repo/package parent so baselines match
    # no matter which subtree was scanned
    report = Report(files=len(files))
    modules: List[ModuleInfo] = []
    for fp in files:
        try:
            with open(fp, encoding="utf-8") as f:
                src = f.read()
            modules.append(ModuleInfo(fp, _repo_rel(fp), src))
        except (SyntaxError, UnicodeDecodeError) as e:
            report.parse_errors.append(f"{fp}: {e}")
    project = Project(modules, root)

    def selected(rid: str) -> bool:
        if select is None:
            return True
        for s in select:
            if rid == s or (s.endswith("xx") and rid.startswith(s[:-2])):
                return True
        return False

    raw: List[Tuple[ModuleInfo, Finding]] = []
    for r in _RULES.values():
        if not selected(r.rule_id):
            continue
        if r.scope == "file":
            for mod in modules:
                for out in r.fn(mod):
                    line, col, msg = out[0], out[1], out[2]
                    rel = tuple(out[3]) if len(out) > 3 and out[3] else ()
                    raw.append((mod, Finding(
                        r.rule_id, r.severity, mod.relpath, line, col, msg,
                        mod.line_text(line), rel)))
        else:
            for out in r.fn(project):
                mod, line, col, msg = out[0], out[1], out[2], out[3]
                rel = tuple(out[4]) if len(out) > 4 and out[4] else ()
                raw.append((mod, Finding(
                    r.rule_id, r.severity, mod.relpath, line, col, msg,
                    mod.line_text(line), rel)))

    base_counts = load_baseline(baseline) if baseline else {}
    for mod, f in sorted(raw, key=lambda mf: (mf[1].path, mf[1].line,
                                              mf[1].rule_id)):
        if mod.suppressed(f.rule_id, f.line):
            report.suppressed += 1
            continue
        k = f.key()
        if base_counts.get(k, 0) > 0:
            base_counts[k] -= 1
            report.baselined.append(f)
            continue
        report.findings.append(f)
    return report


def _repo_rel(path: str) -> str:
    """Path relative to the repo root (the dir holding the baseline or a
    .git), else to cwd — keeps baseline entries location-stable."""
    anchor = find_baseline(path)
    if anchor:
        root = os.path.dirname(anchor)
    else:
        root = _git_root(path) or os.getcwd()
    try:
        return os.path.relpath(os.path.abspath(path), root)
    except ValueError:
        return path


def _git_root(path: str) -> Optional[str]:
    cur = os.path.abspath(path)
    if os.path.isfile(cur):
        cur = os.path.dirname(cur)
    while True:
        if os.path.exists(os.path.join(cur, ".git")):
            return cur
        nxt = os.path.dirname(cur)
        if nxt == cur:
            return None
        cur = nxt


# ---------------------------------------------------------------------------
# reporters
# ---------------------------------------------------------------------------

def render_text(report: Report, tool_name: str = "ptlint") -> str:
    lines = []
    for f in report.findings:
        lines.append(f"{f.path}:{f.line}:{f.col}: {f.rule_id} "
                     f"[{f.severity}] {f.message}")
    for e in report.parse_errors:
        lines.append(f"parse error: {e}")
    noun = "program(s)" if tool_name == "ptprog" else "file(s)"
    lines.append(
        f"{tool_name}: {report.files} {noun}, "
        f"{len(report.findings)} finding(s), "
        f"{len(report.baselined)} baselined, "
        f"{report.suppressed} suppressed")
    return "\n".join(lines)


def render_json(report: Report) -> str:
    return json.dumps({
        "files": report.files,
        "findings": [f.to_dict() for f in report.findings],
        "baselined": [f.to_dict() for f in report.baselined],
        "suppressed": report.suppressed,
        "parse_errors": report.parse_errors,
    }, indent=1)


# PT6xx: the IR-level ptprog families (paddle_tpu/analysis/program/)
# and PT9xx: the sharding-propagation family (analysis/sharding/,
# ptshard).  Kept here — the one jax-free module every CLI always
# loads — so `--list-rules` can show the full inventory without
# importing the analyzers (abstract evaluation needs jax).
PTPROG_RULES = (
    ("PT601", "error", "op entry failed abstract (eval_shape) evaluation"),
    ("PT602", "warning", "op mixes floating dtypes across tensor inputs "
                         "(AMP cast error class)"),
    ("PT603", "error", "cast op output dtype contradicts its tag"),
    ("PT604", "warning", "op output is never consumed or fetched "
                         "(dead op)"),
    ("PT610", "error", "predicted peak memory exceeds the device budget"),
    ("PT620", "error", "collective group axis absent from the mesh"),
    ("PT621", "error", "collective group size/ranks inconsistent with "
                       "the mesh"),
    ("PT622", "error", "p2p peer outside the collective group"),
    ("PT623", "error", "unmatched send/recv pair across pipeline stages"),
    ("PT630", "error", "pass changed a fetchable shape/dtype"),
    ("PT631", "error", "pass made a fetch target unproducible"),
    ("PT901", "error", "sharding spec binds an axis not on the mesh, "
                       "or maps one mesh axis to two tensor dims"),
    ("PT902", "warning", "implicit reshard at a producer->consumer "
                         "sharding mismatch (estimated bytes in the "
                         "message)"),
    ("PT903", "error", "sharded dim not divisible by its mesh-axis "
                       "size (silent padding)"),
    ("PT904", "warning", "redundant collective: operand already "
                         "replicated/unsharded over the axis"),
    ("PT905", "error", "pipeline-stage boundary sharding mismatch "
                       "(output spec != next stage's feed spec)"),
)


def render_sarif(report: Report, tool_name: str = "ptlint") -> str:
    """SARIF 2.1.0 — the format CI services ingest for inline PR
    annotations.  Active findings become `results`; baselined findings
    are included but marked `suppressions` (external), so the feed
    shows grandfathered debt without failing the annotation gate."""
    _load_rule_modules()
    rule_meta = {rid: {"id": rid,
                       "shortDescription": {"text": r.summary},
                       "defaultConfiguration": {
                           "level": "error" if r.severity == "error"
                           else "warning"}}
                 for rid, r in _RULES.items()}
    for rid, sev, summary in PTPROG_RULES:
        rule_meta[rid] = {"id": rid,
                          "shortDescription": {"text": summary},
                          "defaultConfiguration": {"level": sev}}

    def result(f: Finding, suppressed: bool) -> dict:
        r = {
            "ruleId": f.rule_id,
            "level": "error" if f.severity == "error" else "warning",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path.replace("\\", "/")},
                    "region": {"startLine": max(f.line, 1),
                               "startColumn": max(f.col, 0) + 1},
                }
            }],
        }
        if f.related:
            r["relatedLocations"] = [{
                "physicalLocation": {
                    "artifactLocation": {"uri": p.replace("\\", "/")},
                    "region": {"startLine": max(int(ln), 1)},
                },
                "message": {"text": m},
            } for p, ln, m in f.related]
        if suppressed:
            r["suppressions"] = [{"kind": "external",
                                  "justification": "baselined finding "
                                  f"({BASELINE_NAME})"}]
        return r

    used = {f.rule_id for f in report.findings} | \
        {f.rule_id for f in report.baselined}
    sarif = {
        "$schema": "https://raw.githubusercontent.com/oasis-tcs/"
                   "sarif-spec/master/Schemata/sarif-schema-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": tool_name,
                "informationUri":
                    "https://github.com/PaddlePaddle/Paddle",
                "rules": [rule_meta[rid]
                          for rid in sorted(used) if rid in rule_meta],
            }},
            "results": [result(f, False) for f in report.findings]
            + [result(f, True) for f in report.baselined],
        }],
    }
    return json.dumps(sarif, indent=1)


# ---------------------------------------------------------------------------
# shared AST helpers used by several rule modules
# ---------------------------------------------------------------------------

def call_name(node: ast.Call) -> Optional[str]:
    """Trailing name of the called function: f(...) -> 'f',
    a.b.f(...) -> 'f'."""
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


def dotted_name(node) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def names_in(node) -> set:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def match_known(name: str, known: Iterable[str]) -> bool:
    for pat in known:
        if name == pat or ("*" in pat and fnmatch.fnmatchcase(name, pat)):
            return True
    return False
