"""ptprog — IR-level static analysis over recorded ``static.Program``s.

Where ptlint (the sibling rule families PT1xx–PT5xx) sees Python
*source*, ptprog sees the *IR*: the op list a ``static.Program``
actually recorded — post-capture, post-pass-pipeline — plus the jax
callables behind each entry.  That is the level where a wrong-dtype AMP
cast, an OOM-at-batch-size, or a mismatched collective group lives, and
the reference stack checks it there too (infermeta / PIR passes /
GSPMD propagation validate ProgramDesc before anything touches a
device).  Four passes share one abstract-dataflow core:

- **PT60x shape/dtype dataflow** (`dataflow.py`) — abstractly evaluates
  every op entry with ``jax.eval_shape`` (the infermeta analog),
  surfacing ops that cannot infer (PT601), mixed-float-precision inputs
  — the AMP-cast bug class (PT602), cast ops whose output contradicts
  their tag (PT603), and dead ops (PT604).
- **PT61x liveness / peak memory** (`memory.py`) — per-uid live ranges
  over the op list give peak bytes for a feed spec, an OOM check
  against a device budget (PT610), and what ``recompute_pass`` /
  ``amp_insertion`` would save; per-op FLOPs/bytes roofline via
  ``paddle_tpu.cost_model``.
- **PT62x collective consistency** (`collectives.py`) — every recorded
  collective's group/axis is checked against the mesh (PT620/PT621),
  p2p peers against the group (PT622), and send/recv pairs are matched
  across pipeline-stage sub-programs (PT623) — complementing the
  AST-level PT2xx rules, which cannot see dynamically-built groups.
- **PT63x pass equivalence** (`verify.py`) — structural + abstract
  before/after diffing of every registered Program pass; wired into
  ``PassManager.run(program, verify=True)``, which rejects any
  transform that changes fetchable shapes/dtypes (PT630/PT631).

Entry points: ``python -m paddle_tpu.analysis --program <target>`` and
``tools/ptprog.py``.  Findings are ``engine.Finding``s with
``path="program:<name>"`` and ``line`` = 1-based op index, so the
ptlint reporters (text/json/sarif) and the committed-baseline workflow
apply unchanged.

Unlike the AST engine this package imports jax (abstract evaluation
needs it) — it is therefore imported lazily, never from
``paddle_tpu.analysis`` itself, keeping ``tools/ptlint.py`` jax-free.
"""
from __future__ import annotations

# PT6xx inventory (defined in the jax-free engine so `--list-rules`
# never has to import this package; the AST registry can't hold these —
# they run over Programs, not files).
from ..engine import PTPROG_RULES                            # noqa: E402

from .ir import ProgramIR                                    # noqa: E402
from .dataflow import abstract_run, check_dataflow           # noqa: E402
from .memory import MemoryReport, check_memory, estimate_memory  # noqa: E402
from .collectives import check_collectives, check_pipeline   # noqa: E402
from .verify import (PassVerificationError, VerifyReport,    # noqa: E402
                     program_signature, verify_pass)
from .analyze import AnalysisResult, analyze                 # noqa: E402
from .capture import (Capture, PRESETS, capture_llama_block,  # noqa: E402
                      capture_mlp, load_target)

__all__ = ["PTPROG_RULES", "ProgramIR", "abstract_run", "check_dataflow",
           "MemoryReport", "check_memory", "estimate_memory",
           "check_collectives", "check_pipeline",
           "PassVerificationError", "VerifyReport", "program_signature",
           "verify_pass", "AnalysisResult", "analyze", "Capture",
           "PRESETS", "capture_llama_block", "capture_mlp",
           "load_target"]
