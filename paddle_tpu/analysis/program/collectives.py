"""IR-level collective/sharding consistency checks (PT62x).

The AST rules (PT2xx) can only see collectives whose group is a literal
at the call site.  Here the *recorded* state is checked: the explicit
``collective_meta`` log the dispatcher writes while a Program records
(or, for older captures, the ``Group`` recovered from each entry's
closure — see ``ir.collective_info``), validated against the process
mesh that will execute the replay:

- PT620 error — a collective's group binds a mesh axis that does not
  exist on the mesh (the replay's in-graph branch would reference an
  unbound axis name; the eager branch silently degrades to identity).
- PT621 error — group size disagrees with the bound mesh axis size, or
  group ranks fall outside the mesh's device count.
- PT622 error — a p2p send/recv names a peer outside its group.
- PT623 error — ``check_pipeline``: across per-stage sub-programs,
  a send from stage *i* to peer *j* has no matching recv in stage *j*
  from peer *i* (and vice versa) — the classic pipeline-schedule
  deadlock, caught on CPU in milliseconds.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from ..engine import Finding
from .ir import ProgramIR

__all__ = ["check_collectives", "check_pipeline", "active_mesh"]


def active_mesh():
    """The mesh the replay will run under: the explicitly initialized
    topology mesh if any, else None (single-controller eager)."""
    try:
        from ...distributed.topology import get_mesh

        return get_mesh()
    except Exception:
        return None


def _mesh_axes(mesh) -> Optional[Dict[str, int]]:
    if mesh is None:
        return None
    shape = getattr(mesh, "shape", None)
    if shape is None:
        return None
    return dict(shape)


def _finding(ir: ProgramIR, rule: str, index: int, msg: str,
             ctx: str) -> Finding:
    return Finding(rule, "error", f"program:{ir.name}", index + 1, 0,
                   msg, line_text=ctx)


def check_collectives(ir: ProgramIR, mesh=None,
                      world_size: Optional[int] = None) -> List[Finding]:
    """Validate every recorded collective of one program against
    ``mesh`` (defaults to the active topology mesh)."""
    mesh = mesh if mesh is not None else active_mesh()
    axes = _mesh_axes(mesh)
    ndev = None
    if mesh is not None:
        devs = getattr(mesh, "devices", None)
        ndev = int(devs.size) if devs is not None else None
    if world_size is None:
        world_size = ndev

    findings: List[Finding] = []
    for meta in ir.collectives:
        op = meta.get("op", "?")
        idx = int(meta.get("op_index", 0))
        axis = meta.get("axis")
        ranks = meta.get("ranks")
        ctx = f"{op}@{axis or '?'}"
        # the default world group's synthetic axis never binds a mesh
        # axis by name — it is the whole mesh
        is_world = axis in (None, "world") or (
            axis or "").startswith("group_")
        if axes is not None and not is_world and axis not in axes:
            findings.append(_finding(
                ir, "PT620", idx,
                f"collective '{op}' is bound to mesh axis '{axis}' "
                f"which does not exist on the mesh "
                f"(axes: {sorted(axes)}); the in-graph replay cannot "
                f"lower this collective", ctx))
        elif axes is not None and not is_world and ranks is not None \
                and len(ranks) != axes[axis]:
            findings.append(_finding(
                ir, "PT621", idx,
                f"collective '{op}' group has {len(ranks)} rank(s) but "
                f"mesh axis '{axis}' has size {axes[axis]} — the group "
                f"does not tile the axis", ctx))
        if ranks is not None and world_size:
            bad = [r for r in ranks if r < 0 or r >= world_size]
            if bad:
                findings.append(_finding(
                    ir, "PT621", idx,
                    f"collective '{op}' group names rank(s) {bad} "
                    f"outside the world of {world_size}", ctx))
        peer = meta.get("peer")
        if peer is not None and ranks:
            if peer not in ranks:
                findings.append(_finding(
                    ir, "PT622", idx,
                    f"p2p '{op}' targets peer rank {peer} outside its "
                    f"group ranks {sorted(ranks)}", ctx))
    return findings


def _p2p_events(ir: ProgramIR) -> List[dict]:
    return [m for m in ir.collectives
            if m.get("op") in ("send", "recv", "isend", "irecv")]


def check_pipeline(stage_programs: Sequence, mesh=None,
                   names: Optional[Sequence[str]] = None) -> List[Finding]:
    """Match send/recv pairs across pipeline-stage sub-programs.

    ``stage_programs[i]`` is the Program recorded for pipeline stage
    *i* (stage index == rank on the 'pp' axis).  Every send from stage
    i to peer j must have a matching recv in stage j with peer i, in
    both directions; surplus events on either side are PT623 findings.
    Per-stage group/axis checks (PT620–PT622) run too.
    """
    irs = [p if isinstance(p, ProgramIR)
           else ProgramIR(p, name=(names[i] if names else f"stage{i}"))
           for i, p in enumerate(stage_programs)]
    findings: List[Finding] = []
    for ir in irs:
        findings.extend(check_collectives(ir, mesh=mesh))

    # (src stage, dst stage) -> [counts] of sends / recvs
    sends: Dict[Tuple[int, int], int] = {}
    recvs: Dict[Tuple[int, int], int] = {}
    send_at: Dict[Tuple[int, int], Tuple[ProgramIR, int]] = {}
    recv_at: Dict[Tuple[int, int], Tuple[ProgramIR, int]] = {}
    for i, ir in enumerate(irs):
        for ev in _p2p_events(ir):
            peer = ev.get("peer")
            if peer is None:
                continue
            idx = int(ev.get("op_index", 0))
            if ev["op"] in ("send", "isend"):
                key = (i, int(peer))
                sends[key] = sends.get(key, 0) + 1
                send_at.setdefault(key, (ir, idx))
            else:
                key = (int(peer), i)
                recvs[key] = recvs.get(key, 0) + 1
                recv_at.setdefault(key, (ir, idx))

    for key in sorted(set(sends) | set(recvs)):
        ns, nr = sends.get(key, 0), recvs.get(key, 0)
        if ns == nr:
            continue
        src, dst = key
        if ns > nr:
            ir, idx = send_at[key]
            findings.append(_finding(
                ir, "PT623", idx,
                f"stage {src} sends to stage {dst} {ns} time(s) but "
                f"stage {dst} posts only {nr} matching recv(s) — the "
                f"surplus send deadlocks the schedule",
                f"send:{src}->{dst}"))
        else:
            ir, idx = recv_at[key]
            findings.append(_finding(
                ir, "PT623", idx,
                f"stage {dst} expects {nr} recv(s) from stage {src} but "
                f"stage {src} posts only {ns} send(s) — the surplus "
                f"recv blocks forever", f"recv:{src}->{dst}"))
    return findings
