"""Program captures the analyzer can produce on demand.

``--program llama`` (or ``mlp``) needs a Program to chew on; these
presets record one from the shipped models — a llama decoder block and
a small MLP — sized to analyze in well under ten seconds on a CPU.  A
``module:callable`` target loads user code instead: the callable must
return a ``static.Program`` or a ``Capture``.

Captures are *functions* (not cached Programs) because pass-equivalence
verification mutates the program it checks — each shipped pass is
verified against a fresh capture.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, Optional

__all__ = ["Capture", "PRESETS", "capture_mlp", "capture_llama_block",
           "load_target"]


@dataclass
class Capture:
    name: str
    program: object                       # static.Program
    feed_spec: Dict[str, object] = field(default_factory=dict)
    capture_fn: Optional[Callable] = None   # fresh re-capture for verify
    mesh: object = None


def capture_mlp(batch: int = 8, din: int = 64, dhidden: int = 128,
                dout: int = 32) -> Capture:
    """x @ w1 -> relu -> @ w2 -> softmax, recorded into a fresh
    Program (the canonical pass-pipeline fixture)."""
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.static as static

    rng = np.random.RandomState(0)
    w1 = paddle.to_tensor(rng.randn(din, dhidden).astype(np.float32) * .1)
    w2 = paddle.to_tensor(rng.randn(dhidden, dout).astype(np.float32) * .1)
    main = static.Program()
    with static.program_guard(main, static.Program()):
        x = static.data("x", (batch, din), "float32")
        h = paddle.matmul(x, w1)
        h = paddle.nn.functional.relu(h)
        h = paddle.matmul(h, w2)
        out = paddle.nn.functional.softmax(h)
    main.fetch_targets.append(out)
    return Capture(name="mlp", program=main,
                   capture_fn=lambda: capture_mlp(batch, din, dhidden,
                                                  dout).program)


def capture_llama_block(batch: int = 2, seq: int = 64, hidden: int = 128,
                        heads: int = 4, intermediate: int = 256) -> Capture:
    """One LlamaDecoderLayer forward recorded op-by-op — the "llama
    preset program capture" the CI gate analyzes.  Flash attention is
    disabled (the Pallas kernel has its own PT3xx contract checks and
    no CPU abstract path is needed here) and the layer runs in eval
    mode so the capture is the plain dense block."""
    import numpy as np

    import paddle_tpu.static as static
    from ...models.llama import LlamaConfig, LlamaDecoderLayer

    cfg = LlamaConfig(
        vocab_size=256, hidden_size=hidden, intermediate_size=intermediate,
        num_hidden_layers=1, num_attention_heads=heads,
        num_key_value_heads=heads, max_position_embeddings=max(seq, 16),
        dtype="float32", use_flash_attention=False, recompute=False)
    layer = LlamaDecoderLayer(cfg)
    layer.eval()
    main = static.Program()
    with static.program_guard(main, static.Program()):
        x = static.data("x", (batch, seq, hidden), "float32")
        out = layer(x)
    main.fetch_targets.append(out)
    return Capture(
        name="llama-block", program=main,
        capture_fn=lambda: capture_llama_block(batch, seq, hidden, heads,
                                               intermediate).program)


PRESETS: Dict[str, Callable[[], Capture]] = {
    "mlp": capture_mlp,
    "llama": capture_llama_block,
    "llama-block": capture_llama_block,
}


def load_target(target: str) -> Capture:
    """Resolve a ``--program`` target: a preset name, or
    ``package.module:callable`` returning a Program or Capture."""
    if target in PRESETS:
        return PRESETS[target]()
    if ":" not in target:
        raise SystemExit(
            f"ptprog: unknown program target {target!r} — use one of "
            f"{sorted(PRESETS)} or module.path:callable")
    mod_name, _, attr = target.partition(":")
    import importlib

    mod = importlib.import_module(mod_name)
    obj = getattr(mod, attr)()
    if isinstance(obj, Capture):
        return obj
    return Capture(name=target, program=obj)
