"""Program captures the analyzer can produce on demand.

``--program llama`` (or ``mlp``) needs a Program to chew on; these
presets record one from the shipped models — a llama decoder block and
a small MLP — sized to analyze in well under ten seconds on a CPU.  A
``module:callable`` target loads user code instead: the callable must
return a ``static.Program`` or a ``Capture``.

Captures are *functions* (not cached Programs) because pass-equivalence
verification mutates the program it checks — each shipped pass is
verified against a fresh capture.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, Optional

__all__ = ["Capture", "PRESETS", "capture_mlp", "capture_llama_block",
           "capture_decode_step", "decode_step_spec", "load_target"]


@dataclass
class Capture:
    name: str
    program: object                       # static.Program
    feed_spec: Dict[str, object] = field(default_factory=dict)
    capture_fn: Optional[Callable] = None   # fresh re-capture for verify
    mesh: object = None


def capture_mlp(batch: int = 8, din: int = 64, dhidden: int = 128,
                dout: int = 32) -> Capture:
    """x @ w1 -> relu -> @ w2 -> softmax, recorded into a fresh
    Program (the canonical pass-pipeline fixture)."""
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.static as static

    rng = np.random.RandomState(0)
    w1 = paddle.to_tensor(rng.randn(din, dhidden).astype(np.float32) * .1)
    w2 = paddle.to_tensor(rng.randn(dhidden, dout).astype(np.float32) * .1)
    main = static.Program()
    with static.program_guard(main, static.Program()):
        x = static.data("x", (batch, din), "float32")
        h = paddle.matmul(x, w1)
        h = paddle.nn.functional.relu(h)
        h = paddle.matmul(h, w2)
        out = paddle.nn.functional.softmax(h)
    main.fetch_targets.append(out)
    return Capture(name="mlp", program=main,
                   capture_fn=lambda: capture_mlp(batch, din, dhidden,
                                                  dout).program)


def capture_llama_block(batch: int = 2, seq: int = 64, hidden: int = 128,
                        heads: int = 4, intermediate: int = 256) -> Capture:
    """One LlamaDecoderLayer forward recorded op-by-op — the "llama
    preset program capture" the CI gate analyzes.  Flash attention is
    disabled (the Pallas kernel has its own PT3xx contract checks and
    no CPU abstract path is needed here) and the layer runs in eval
    mode so the capture is the plain dense block."""
    import numpy as np

    import paddle_tpu.static as static
    from ...models.llama import LlamaConfig, LlamaDecoderLayer

    cfg = LlamaConfig(
        vocab_size=256, hidden_size=hidden, intermediate_size=intermediate,
        num_hidden_layers=1, num_attention_heads=heads,
        num_key_value_heads=heads, max_position_embeddings=max(seq, 16),
        dtype="float32", use_flash_attention=False, recompute=False)
    layer = LlamaDecoderLayer(cfg)
    layer.eval()
    main = static.Program()
    with static.program_guard(main, static.Program()):
        x = static.data("x", (batch, seq, hidden), "float32")
        out = layer(x)
    main.fetch_targets.append(out)
    return Capture(
        name="llama-block", program=main,
        capture_fn=lambda: capture_llama_block(batch, seq, hidden, heads,
                                               intermediate).program)


def decode_step_spec(rows: int = 4, heads: int = 4, head_dim: int = 16,
                     block_size: int = 8, max_blocks: int = 4,
                     n_pages: int = 16, ffn: int = 128,
                     vocab: int = 256):
    """(fn, input_spec) for one serving DECODE iteration — the callable
    ``jit.lower_stablehlo(fn, spec, auto_fuse=True)`` captures so the
    whole decode step lowers as ONE verified fused region, and the body
    ``capture_decode_step`` records for the ``decode`` preset.

    Structurally the read path of ``PagedCausalLM.forward`` at decode
    shapes: paged KV gather (``index_select`` over the page pool by the
    block table), one-query-per-row attention with an additive length
    mask, RMSNorm chains, swiglu MLP, LM head and the on-device argmax
    sample — the memory-bound elementwise/softmax/norm chains between
    the matmuls are exactly what ``auto_fuse`` is meant to collapse.
    The cache APPEND (a dynamic-update-slice into the pool) is left
    out: it is a write, not a fusion candidate, and needs no roofline.
    """
    import numpy as np

    import paddle_tpu as paddle
    from ...incubate.nn.functional import swiglu
    from ...jit.api import InputSpec

    hidden = heads * head_dim
    S = max_blocks * block_size
    rng = np.random.RandomState(7)

    def w(*shape):
        return paddle.to_tensor(
            rng.randn(*shape).astype(np.float32) * 0.05)

    g1, g2, gf = w(hidden), w(hidden), w(hidden)
    wq, wk, wv = w(hidden, hidden), w(hidden, hidden), w(hidden, hidden)
    wo = w(hidden, hidden)
    w_gate, w_up = w(hidden, ffn), w(hidden, ffn)
    w_down = w(ffn, hidden)
    w_head = w(hidden, vocab)
    scale = 1.0 / float(np.sqrt(head_dim))

    def rms(x, g):
        m = paddle.mean(x * x, axis=-1, keepdim=True)
        return x * paddle.rsqrt(m + 1e-6) * g

    def fn(x, kpages, vpages, bt, mask):
        h = rms(x, g1)
        q = paddle.matmul(h, wq)
        # (decode writes this step's k/v into the pool too; the gather
        # below reads the pool state, which dominates the traffic)
        paddle.matmul(h, wk)
        paddle.matmul(h, wv)

        def heads_first(pages):
            t = paddle.index_select(pages, bt, axis=0)
            t = paddle.reshape(t, [rows, S, heads, head_dim])
            t = paddle.transpose(t, [0, 2, 1, 3])
            return paddle.reshape(t, [rows * heads, S, head_dim])

        k_all = heads_first(kpages)
        v_all = heads_first(vpages)
        q_r = paddle.reshape(q, [rows * heads, 1, head_dim])
        scores = paddle.matmul(q_r, k_all, transpose_y=True) * scale
        scores = paddle.reshape(scores, [rows, heads, 1, S]) + mask
        probs = paddle.nn.functional.softmax(scores, axis=-1)
        probs = paddle.reshape(probs, [rows * heads, 1, S])
        attn = paddle.reshape(paddle.matmul(probs, v_all),
                              [rows, hidden])
        x1 = x + paddle.matmul(attn, wo)
        h2 = rms(x1, g2)
        gate = paddle.matmul(h2, w_gate)
        up = paddle.matmul(h2, w_up)
        x2 = x1 + paddle.matmul(swiglu(gate, up), w_down)
        logits = paddle.matmul(rms(x2, gf), w_head)
        sampled = paddle.argmax(logits, axis=-1)
        return logits, sampled

    spec = [
        InputSpec((rows, hidden), "float32", "x"),
        InputSpec((n_pages, block_size, hidden), "float32", "kpages"),
        InputSpec((n_pages, block_size, hidden), "float32", "vpages"),
        InputSpec((rows * max_blocks,), "int32", "block_tables"),
        InputSpec((rows, 1, 1, S), "float32", "mask"),
    ]
    return fn, spec


def capture_decode_step(rows: int = 4, heads: int = 4, head_dim: int = 16,
                        block_size: int = 8, max_blocks: int = 4,
                        n_pages: int = 16, ffn: int = 128,
                        vocab: int = 256) -> Capture:
    """The ``decode`` preset: ``decode_step_spec``'s iteration recorded
    into a fresh Program for auto_fuse/roofline/StableHLO — the
    inspectable compiler artifact of serving.py's whole-step decode
    executable (tools/fusereport.py --preset decode)."""
    from ...jit.api import capture_program

    fn, spec = decode_step_spec(rows, heads, head_dim, block_size,
                                max_blocks, n_pages, ffn, vocab)
    prog = capture_program(fn, spec)
    return Capture(
        name="decode", program=prog,
        capture_fn=lambda: capture_decode_step(
            rows, heads, head_dim, block_size, max_blocks, n_pages,
            ffn, vocab).program)


PRESETS: Dict[str, Callable[[], Capture]] = {
    "mlp": capture_mlp,
    "llama": capture_llama_block,
    "llama-block": capture_llama_block,
    "decode": capture_decode_step,
}


def load_target(target: str) -> Capture:
    """Resolve a ``--program`` target: a preset name, or
    ``package.module:callable`` returning a Program or Capture."""
    if target in PRESETS:
        return PRESETS[target]()
    if ":" not in target:
        raise SystemExit(
            f"ptprog: unknown program target {target!r} — use one of "
            f"{sorted(PRESETS)} or module.path:callable")
    mod_name, _, attr = target.partition(":")
    import importlib

    mod = importlib.import_module(mod_name)
    obj = getattr(mod, attr)()
    if isinstance(obj, Capture):
        return obj
    return Capture(name=target, program=obj)
