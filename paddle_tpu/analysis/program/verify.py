"""Pass-equivalence verification (PT63x) — the safety net under
``PassManager.run(program, verify=True)``.

A Program pass is a structural rewrite of the recorded op list; the
contract every shipped pass (``dead_op_elimination``,
``constant_folding``, ``fuse_chain``, ``auto_fuse``,
``amp_insertion``, ``recompute_pass``) must honor is that **fetchable values keep their
shapes and dtypes**.  ``verify_pass`` snapshots the program's abstract
signature (fetch uid -> ShapeDtypeStruct via the shared dataflow core,
plus the producer/consumer graph), runs the pass, re-snapshots, and
raises ``PassVerificationError`` on any fetch-signature change — before
a broken rewrite ever reaches ``Executor.run`` on hardware.

The structural diff (ops added/removed per name, edge count) is kept on
the returned ``VerifyReport`` for tooling; it is informational — passes
are *supposed* to restructure the graph — only the fetch signature is
load-bearing.
"""
from __future__ import annotations

from collections import Counter
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from .dataflow import abstract_run
from .ir import ProgramIR

__all__ = ["PassVerificationError", "VerifyReport", "program_signature",
           "verify_pass"]


class PassVerificationError(RuntimeError):
    """A Program pass changed the shape/dtype (or producibility) of a
    fetchable value.  ``diffs`` lists one human-readable line per
    violated fetch uid."""

    def __init__(self, pass_name: str, diffs: List[str]):
        self.pass_name = pass_name
        self.diffs = list(diffs)
        super().__init__(
            f"pass '{pass_name}' is not equivalence-preserving:\n  "
            + "\n  ".join(diffs))


@dataclass
class Signature:
    fetch: Dict[int, Optional[Tuple[Tuple[int, ...], str]]]
    op_names: Counter
    n_edges: int
    eval_errors: int


@dataclass
class VerifyReport:
    pass_name: str
    ops_before: int = 0
    ops_after: int = 0
    added: Counter = field(default_factory=Counter)
    removed: Counter = field(default_factory=Counter)
    edges_before: int = 0
    edges_after: int = 0

    def summary(self) -> str:
        def fmt(c):
            return ", ".join(f"{n}×{k}" if n > 1 else k
                             for k, n in sorted(c.items())) or "-"

        return (f"{self.pass_name}: {self.ops_before} -> "
                f"{self.ops_after} ops (added {fmt(self.added)}; "
                f"removed {fmt(self.removed)})")


def program_signature(program, feed_spec=None,
                      name: str = "program") -> Signature:
    """Abstract signature of a Program: fetch uid -> (shape, dtype)
    (None when the uid is unproducible at abstract level), plus the
    structural fingerprint used for the informational diff."""
    ir = ProgramIR(program, feed_spec=feed_spec, name=name)
    env, findings = abstract_run(ir)
    fetch = {}
    for u in ir.fetch_uids:
        aval = env.get(u)
        fetch[u] = ((tuple(aval.shape), str(aval.dtype))
                    if aval is not None else None)
    n_edges = sum(len(v) for v in ir.consumers.values())
    return Signature(fetch=fetch,
                     op_names=Counter(op.name for op in ir.ops),
                     n_edges=n_edges,
                     eval_errors=sum(1 for f in findings
                                     if f.rule_id == "PT601"))


def verify_pass(program, pass_fn: Callable, feed_spec=None,
                pass_name: Optional[str] = None) -> VerifyReport:
    """Run ``pass_fn(program)`` under equivalence verification.

    Raises PassVerificationError when any fetch target's abstract
    shape/dtype changes (PT630) or becomes unproducible (PT631).  With
    no fetch targets recorded there is nothing load-bearing to compare
    — the pass runs unverified (mirroring dead_op_elimination's own
    no-roots behavior) and the report notes it.
    """
    pname = pass_name or getattr(pass_fn, "__name__", str(pass_fn))
    before = program_signature(program, feed_spec)
    pass_fn(program)
    after = program_signature(program, feed_spec)

    rep = VerifyReport(
        pass_name=pname,
        ops_before=sum(before.op_names.values()),
        ops_after=sum(after.op_names.values()),
        added=after.op_names - before.op_names,
        removed=before.op_names - after.op_names,
        edges_before=before.n_edges, edges_after=after.n_edges)

    diffs: List[str] = []
    for u, sig_b in before.fetch.items():
        if sig_b is None:
            continue          # was already unproducible; nothing to hold
        sig_a = after.fetch.get(u)
        if sig_a is None:
            diffs.append(
                f"[PT631] fetch uid {u} {sig_b[1]}{list(sig_b[0])} is no "
                f"longer producible after the pass")
        elif sig_a != sig_b:
            diffs.append(
                f"[PT630] fetch uid {u} changed "
                f"{sig_b[1]}{list(sig_b[0])} -> "
                f"{sig_a[1]}{list(sig_a[0])}")
    if diffs:
        try:
            from ...profiler import metrics as _metrics

            _metrics.inc("analysis/verify_failures")
        except Exception:
            pass
        raise PassVerificationError(pname, diffs)
    return rep
