"""Liveness-based peak-memory estimation over a ProgramIR.

The live-set model mirrors the Executor replay environment with
free-after-last-use semantics: a uid is live from the step its producer
runs (externals/feeds from step 0) through the last op that consumes it
— or to the end of the program when it is fetched.  Peak bytes is the
maximum over op indices of the summed live bytes; the unit test pins
this to a concrete replay that tracks the same accounting over real
arrays.

Beyond the raw peak, the report quantifies the two standard levers:

- ``recompute_pass`` savings — for k contiguous segments, the live set
  shrinks to (externals + segment-boundary values + the current
  segment's internal peak); the report evaluates k in {2, 4} and keeps
  the best.
- ``amp_insertion`` savings — intermediate floating values held at
  half width (4-byte floats -> bf16), externals (parameters stay
  fp32 master copies in O1) unchanged.

Per-op FLOPs/bytes and arithmetic intensity come from
``paddle_tpu.cost_model.op_flops`` — the roofline columns of the CLI
memory report.  PT610 fires when the predicted peak exceeds the device
budget.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..engine import Finding
from .ir import ProgramIR, aval_nbytes

__all__ = ["MemoryReport", "estimate_memory", "check_memory",
           "render_memory_report"]

_F32 = np.dtype(np.float32)
_F64 = np.dtype(np.float64)


@dataclass
class MemoryReport:
    name: str
    peak_bytes: int = 0
    peak_index: int = -1            # op index where the peak occurs
    external_bytes: int = 0         # params/constants live for the run
    feed_bytes: int = 0
    fetch_bytes: int = 0
    per_op: List[dict] = field(default_factory=list)
    live_ranges: Dict[int, Tuple[int, int]] = field(default_factory=dict)
    budget_bytes: Optional[int] = None
    recompute_savings_bytes: int = 0
    recompute_best_segments: int = 0
    amp_savings_bytes: int = 0
    total_flops: int = 0

    @property
    def peak_gib(self) -> float:
        return self.peak_bytes / (1 << 30)


def _sizes(ir: ProgramIR, env) -> Dict[int, int]:
    return {u: aval_nbytes(a) for u, a in env.items()}


def _live_ranges(ir: ProgramIR) -> Dict[int, Tuple[int, int]]:
    """uid -> (birth op index, death op index) in the replay model.
    Externals and feeds are born at 0; fetched uids die at the end."""
    n = len(ir.ops)
    last = ir.last_use()
    ranges: Dict[int, Tuple[int, int]] = {}
    for u in ir.initial_env:
        ranges[u] = (0, last.get(u, 0))
    for op in ir.ops:
        for u in op.out_uids:
            birth = ir.producer.get(u, op.index)
            ranges[u] = (birth, last.get(u, birth))
    return ranges


def _peak(ranges: Dict[int, Tuple[int, int]], sizes: Dict[int, int],
          n_ops: int) -> Tuple[int, int]:
    """(peak bytes, op index) via an event sweep over births/deaths."""
    if n_ops == 0:
        total = sum(sizes.get(u, 0) for u in ranges)
        return total, -1
    delta = [0] * (n_ops + 1)
    for u, (b, d) in ranges.items():
        sz = sizes.get(u, 0)
        delta[b] += sz
        if d + 1 <= n_ops:
            delta[d + 1] -= sz
    peak = cur = 0
    peak_i = 0
    for i in range(n_ops):
        cur += delta[i]
        if cur > peak:
            peak, peak_i = cur, i
    return peak, peak_i


def _segment_peak(ir: ProgramIR, sizes: Dict[int, int],
                  num_segments: int) -> int:
    """Predicted peak if the op list ran under ``recompute_pass``
    (k contiguous segments, internals freed at segment exit): externals
    + live segment-boundary values + the current segment's own peak."""
    n = len(ir.ops)
    if n == 0 or num_segments < 1:
        return 0
    bounds = [round(i * n / num_segments)
              for i in range(num_segments + 1)]
    ext = sum(sizes.get(u, 0) for u in ir.initial_env)
    last = ir.last_use()
    peak = 0
    for si in range(num_segments):
        lo, hi = bounds[si], bounds[si + 1]
        if lo >= hi:
            continue
        # boundary values alive while this segment runs: produced before
        # lo (or external) and still used at/after lo
        boundary = 0
        for u, d in last.items():
            b = ir.producer.get(u, 0 if u in ir.initial_env else None)
            if b is None or u in ir.initial_env:
                continue            # externals counted once above
            if b < lo and d >= lo:
                boundary += sizes.get(u, 0)
        # internal running live-set of the segment
        seg_ranges = {}
        for op in ir.ops[lo:hi]:
            for u in op.out_uids:
                seg_ranges[u] = (ir.producer.get(u, op.index),
                                 min(last.get(u, op.index), hi - 1))
        seg_peak, _ = _peak(
            {u: (b - lo, d - lo) for u, (b, d) in seg_ranges.items()},
            sizes, hi - lo)
        peak = max(peak, ext + boundary + seg_peak)
    return peak


def estimate_memory(ir: ProgramIR, env: Dict[int, jax.ShapeDtypeStruct],
                    budget_bytes: Optional[int] = None) -> MemoryReport:
    from ... import cost_model as _cm

    sizes = _sizes(ir, env)
    ranges = _live_ranges(ir)
    peak, peak_i = _peak(ranges, sizes, len(ir.ops))

    rep = MemoryReport(name=ir.name, peak_bytes=peak, peak_index=peak_i,
                       budget_bytes=budget_bytes, live_ranges=ranges)
    feed_uids = set(ir.feed_uids.values())
    rep.feed_bytes = sum(sizes.get(u, 0) for u in feed_uids)
    rep.external_bytes = sum(sizes.get(u, 0) for u in ir.initial_env
                             if u not in feed_uids)
    rep.fetch_bytes = sum(sizes.get(u, 0) for u in set(ir.fetch_uids))

    # per-op roofline rows
    running = 0
    delta = {}
    for u, (b, d) in ranges.items():
        delta.setdefault(b, 0)
        delta[b] += sizes.get(u, 0)
        delta.setdefault(d + 1, 0)
        delta[d + 1] -= sizes.get(u, 0)
    for op in ir.ops:
        running += delta.get(op.index, 0)
        in_avals = [env[u] for u in op.in_uids if u in env]
        out_avals = [env[u] for u in op.out_uids if u in env]
        flops = _cm.op_flops(op.name, in_avals, out_avals)
        bytes_moved = (sum(aval_nbytes(a) for a in in_avals)
                       + sum(aval_nbytes(a) for a in out_avals))
        rep.per_op.append({
            "index": op.index, "name": op.name,
            "out_bytes": sum(aval_nbytes(a) for a in out_avals),
            "live_bytes": running, "flops": flops,
            "bytes_moved": bytes_moved,
            "intensity": (flops / bytes_moved) if bytes_moved else 0.0,
        })
        rep.total_flops += flops

    # recompute savings: best of 2 / 4 contiguous segments
    best_k, best_peak = 0, peak
    for k in (2, 4):
        if len(ir.ops) >= k:
            p = _segment_peak(ir, sizes, k)
            if p < best_peak:
                best_k, best_peak = k, p
    rep.recompute_best_segments = best_k
    rep.recompute_savings_bytes = max(0, peak - best_peak)

    # amp savings: intermediates' 4-byte floats at half width
    amp_sizes = dict(sizes)
    for u, a in env.items():
        if u in ir.initial_env:
            continue
        if np.dtype(a.dtype) in (_F32, _F64):
            amp_sizes[u] = sizes[u] // 2
    amp_peak, _ = _peak(ranges, amp_sizes, len(ir.ops))
    rep.amp_savings_bytes = max(0, peak - amp_peak)
    return rep


def check_memory(ir: ProgramIR, env: Dict[int, jax.ShapeDtypeStruct],
                 budget_bytes: Optional[int] = None,
                 ) -> Tuple[List[Finding], MemoryReport]:
    rep = estimate_memory(ir, env, budget_bytes)
    findings: List[Finding] = []
    if budget_bytes is not None and rep.peak_bytes > budget_bytes:
        at = (ir.ops[rep.peak_index].name
              if 0 <= rep.peak_index < len(ir.ops) else "?")
        findings.append(Finding(
            "PT610", "error", f"program:{ir.name}", rep.peak_index + 1, 0,
            f"predicted peak memory {rep.peak_bytes / (1 << 20):.1f} MiB "
            f"exceeds the device budget "
            f"{budget_bytes / (1 << 20):.1f} MiB (peak at op "
            f"#{rep.peak_index} '{at}'; recompute_pass would save "
            f"{rep.recompute_savings_bytes / (1 << 20):.1f} MiB, "
            f"amp_insertion "
            f"{rep.amp_savings_bytes / (1 << 20):.1f} MiB)",
            line_text=at))
    try:
        from ...profiler import metrics as _metrics

        _metrics.set_gauge("analysis/peak_bytes", rep.peak_bytes)
    except Exception:
        pass
    return findings, rep


def _fmt_bytes(n: int) -> str:
    for unit, div in (("GiB", 1 << 30), ("MiB", 1 << 20),
                      ("KiB", 1 << 10)):
        if n >= div:
            return f"{n / div:.2f} {unit}"
    return f"{n} B"


def render_memory_report(rep: MemoryReport, top: int = 12) -> str:
    lines = [f"memory report — {rep.name}",
             f"  peak live set : {_fmt_bytes(rep.peak_bytes)} "
             f"(at op #{rep.peak_index})",
             f"  externals     : {_fmt_bytes(rep.external_bytes)}   "
             f"feeds: {_fmt_bytes(rep.feed_bytes)}   "
             f"fetches: {_fmt_bytes(rep.fetch_bytes)}",
             f"  total flops   : {rep.total_flops:,}"]
    if rep.budget_bytes is not None:
        verdict = "OVER" if rep.peak_bytes > rep.budget_bytes else "ok"
        lines.append(f"  budget        : "
                     f"{_fmt_bytes(rep.budget_bytes)} [{verdict}]")
    if rep.recompute_best_segments:
        lines.append(
            f"  recompute_pass(num_segments="
            f"{rep.recompute_best_segments}) would save "
            f"{_fmt_bytes(rep.recompute_savings_bytes)}")
    lines.append(f"  amp_insertion would save "
                 f"{_fmt_bytes(rep.amp_savings_bytes)}")
    rows = sorted(rep.per_op, key=lambda r: -r["live_bytes"])[:top]
    if rows:
        lines.append("  hottest ops (live bytes | flops | "
                     "arith intensity):")
        for r in rows:
            lines.append(
                f"    #{r['index']:<4d} {r['name']:<28s} "
                f"{_fmt_bytes(r['live_bytes']):>10s}  "
                f"{r['flops']:>14,}  {r['intensity']:8.1f}")
    return "\n".join(lines)
