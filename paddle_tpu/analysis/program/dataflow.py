"""Shape/dtype dataflow over a ProgramIR — the infermeta analog.

``abstract_run`` walks the op list in order, abstractly evaluating
every entry with ``jax.eval_shape`` on the *recorded callable* (so it
checks what will actually replay, not a re-derivation), and returns the
full uid -> ShapeDtypeStruct environment.  All other analysis passes
(memory, collectives, pass-equivalence) run on top of that environment;
``check_dataflow`` additionally emits the PT60x findings:

- PT601 error   — abstract evaluation raised (a real infermeta failure:
  the op cannot trace at the recorded input shapes/dtypes).
- PT602 warning — an op consumes a MIX of floating dtypes (e.g. bf16
  and fp32): the silent-promotion signature of a broken/missing AMP
  cast.  Cast ops are exempt (mixing is their job).
- PT603 error   — a ``cast_<tag>`` entry's floating output contradicts
  its tag (an AMP pass rewired casts wrongly).
- PT604 warning — an op's outputs are never consumed nor fetched: dead
  weight in the replay (run ``dead_op_elimination``).

Host-side RNG draws inside a recorded op (dropout etc.) are isolated
under an ``rng_guard`` so analysis never perturbs the global stream.
"""
from __future__ import annotations

import contextlib
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..engine import Finding
from .ir import OpView, ProgramIR

__all__ = ["abstract_run", "check_dataflow"]

_FLOATS = tuple(np.dtype(d) for d in
                (np.float16, np.float32, np.float64)) + (
    np.dtype(jnp.bfloat16),)

# cast_<tag> entries inserted by amp_insertion: tag -> required output
_CAST_TAGS = {
    "cast_bfloat16": np.dtype(jnp.bfloat16),
    "cast_bf16": np.dtype(jnp.bfloat16),
    "cast_float16": np.dtype(np.float16),
    "cast_fp16": np.dtype(np.float16),
    "cast_fp32": np.dtype(np.float32),
    "cast_fp32out": np.dtype(np.float32),
}


@contextlib.contextmanager
def _isolated_rng():
    """Abstract evaluation may execute host-side RNG key derivation in
    recorded callables; pin it to a throwaway guard key so analysis is
    side-effect free on the global stream."""
    try:
        from ...framework import random as _rand
    except Exception:
        yield
        return
    try:
        with _rand.rng_guard(jax.random.PRNGKey(0)):
            yield
    except Exception:
        # rng_guard unavailable mid-version: run unguarded rather than
        # fail the analysis
        yield


def _op_finding(ir: ProgramIR, op: OpView, rule: str, severity: str,
                msg: str) -> Finding:
    return Finding(rule, severity, f"program:{ir.name}", op.index + 1, 0,
                   msg, line_text=op.name)


def abstract_run(ir: ProgramIR,
                 env: Optional[Dict[int, jax.ShapeDtypeStruct]] = None,
                 findings: Optional[List[Finding]] = None,
                 ) -> Tuple[Dict[int, jax.ShapeDtypeStruct],
                            List[Finding]]:
    """Abstractly evaluate every op of ``ir`` in record order.

    Returns ``(env, findings)`` where env maps every resolvable uid to
    its ShapeDtypeStruct.  Ops whose inputs are unresolved (because an
    upstream op already failed) are skipped without piling on findings —
    one PT601 per root cause.
    """
    env = dict(ir.initial_env) if env is None else env
    findings = [] if findings is None else findings
    with _isolated_rng():
        for op in ir.ops:
            if any(u not in env for u in op.in_uids):
                missing_roots = [u for u in op.in_uids if u not in env
                                 and u not in ir.producer]
                if missing_roots:
                    findings.append(_op_finding(
                        ir, op, "PT601", "error",
                        f"op '{op.name}' reads uid(s) {missing_roots} "
                        f"that no feed, external, or earlier op "
                        f"produces"))
                continue
            in_sig = ", ".join(
                f"{env[u].dtype}{list(env[u].shape)}" for u in op.in_uids)
            try:
                updates, in_avals = ir.abstract_eval_op(op, env)
            except Exception as e:  # noqa: BLE001 — surfaced as finding
                findings.append(_op_finding(
                    ir, op, "PT601", "error",
                    f"op '{op.name}' failed abstract evaluation at "
                    f"inputs ({in_sig}): {type(e).__name__}: {e}"))
                continue
            env.update(updates)
            _check_float_mix(ir, op, in_avals, findings)
            _check_cast_tag(ir, op, updates, findings)
    return env, findings


def _check_float_mix(ir: ProgramIR, op: OpView, in_avals, findings):
    if op.name.startswith("cast_") or len(in_avals) < 2:
        return
    float_dts = {np.dtype(a.dtype) for a in in_avals
                 if np.dtype(a.dtype) in _FLOATS}
    if len(float_dts) > 1:
        findings.append(_op_finding(
            ir, op, "PT602", "warning",
            f"op '{op.name}' mixes floating dtypes "
            f"{sorted(d.name for d in float_dts)} across its tensor "
            f"inputs — a missing/broken AMP cast (the replay will "
            f"silently promote)"))


def _check_cast_tag(ir: ProgramIR, op: OpView, updates, findings):
    want = _CAST_TAGS.get(op.name)
    if want is None:
        return
    for u, aval in updates.items():
        got = np.dtype(aval.dtype)
        if got in _FLOATS and got != want:
            findings.append(_op_finding(
                ir, op, "PT603", "error",
                f"cast op '{op.name}' produces {got.name}, contradicting "
                f"its tag ({want.name}) — the AMP pass wired this cast "
                f"wrongly"))


def check_dataflow(ir: ProgramIR,
                   env: Optional[Dict[int, jax.ShapeDtypeStruct]] = None,
                   ) -> Tuple[Dict[int, jax.ShapeDtypeStruct],
                              List[Finding]]:
    """The full PT60x pass: abstract_run + dead-op detection, including
    a recursive walk into control-flow regions (the PIR Region analog)."""
    env, findings = abstract_run(ir, env)

    fetch = set(ir.fetch_uids)
    for op in ir.ops:
        if op.out_uids and not any(
                u in ir.consumers or u in fetch for u in op.out_uids):
            findings.append(_op_finding(
                ir, op, "PT604", "warning",
                f"op '{op.name}' outputs are never consumed or fetched "
                f"— dead weight in the replay "
                f"(run dead_op_elimination)"))
        for tag, sub in op.regions:
            sub_ir = ProgramIR(sub, name=f"{ir.name}/op{op.index}"
                                         f"[{tag}]")
            _senv, sfind = check_dataflow(sub_ir)
            findings.extend(sfind)
    return env, findings
