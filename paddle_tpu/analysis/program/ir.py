"""Normalized view of a recorded ``static.Program`` op list.

A Program entry is the raw 8-tuple ``(name, fn, entry_flat, tensor_pos,
in_uids, treedef, out_positions, out_uids)`` (plus ``.regions`` on
control-flow entries).  ``ProgramIR`` wraps it with the derived tables
every analysis pass needs — producer/consumer indices, initial abstract
environment (feeds + externals as ``jax.ShapeDtypeStruct``), fetch
roots, and best-effort collective metadata recovered from the entry's
closure when the Program carries no ``collective_meta`` log.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import jax

__all__ = ["OpView", "ProgramIR", "aval_of", "aval_nbytes",
           "COLLECTIVE_OPS", "P2P_OPS", "collective_info"]

# op names the dispatcher records for paddle_tpu.distributed collectives
COLLECTIVE_OPS = frozenset({
    "all_reduce", "all_gather", "reduce_scatter", "all_to_all",
    "all_to_all_single", "broadcast", "scatter", "reduce"})
P2P_OPS = frozenset({"send", "recv", "isend", "irecv"})


def aval_of(value) -> Optional[jax.ShapeDtypeStruct]:
    """ShapeDtypeStruct for a Tensor / array / ShapeDtypeStruct."""
    if value is None:
        return None
    if isinstance(value, jax.ShapeDtypeStruct):
        return value
    v = getattr(value, "_value", value)       # Tensor -> jax array
    shape = getattr(v, "shape", None)
    dtype = getattr(v, "dtype", None)
    if shape is None or dtype is None:
        return None
    return jax.ShapeDtypeStruct(tuple(shape), np.dtype(dtype))


def aval_nbytes(aval) -> int:
    if aval is None:
        return 0
    n = 1
    for d in aval.shape:
        n *= int(d)
    return n * np.dtype(aval.dtype).itemsize


class OpView:
    """One op entry with its index and region children exposed."""

    __slots__ = ("index", "name", "entry", "fn", "in_uids", "out_uids",
                 "regions")

    def __init__(self, index: int, entry):
        self.index = index
        self.entry = entry
        (self.name, self.fn, _flat, _tpos, self.in_uids, _treedef,
         _out_pos, self.out_uids) = entry[:8]
        self.regions = list(getattr(entry, "regions", ()))

    def __repr__(self):
        return (f"OpView({self.index}: {self.name} "
                f"{list(self.in_uids)} -> {list(self.out_uids)})")


def collective_info(op: OpView) -> Optional[Dict[str, Any]]:
    """Best-effort group metadata from a collective entry's CLOSURE —
    the fallback for Programs recorded before ``collective_meta``
    logging existed.  The recorded jax fn closes over the ``Group`` (and
    usually the resolved axis name), which is exactly the
    dynamically-built state the AST-level PT2xx rules cannot see."""
    if op.name not in COLLECTIVE_OPS | P2P_OPS:
        return None
    fn = op.fn
    code = getattr(fn, "__code__", None)
    cells = getattr(fn, "__closure__", None) or ()
    if code is None:
        return None
    info: Dict[str, Any] = {"op": op.name, "op_index": op.index,
                            "gid": None, "ranks": None, "axis": None,
                            "peer": None}
    for var, cell in zip(code.co_freevars, cells):
        try:
            val = cell.cell_contents
        except ValueError:              # empty cell
            continue
        if var in ("group", "g") and val is not None \
                and hasattr(val, "ranks") and hasattr(val, "axis_name"):
            info["gid"] = getattr(val, "id", None)
            info["ranks"] = tuple(val.ranks)
            info["axis"] = val.axis_name
        elif var == "ax" and isinstance(val, str):
            info.setdefault("axis", None)
            info["axis"] = info["axis"] or val
    if info["ranks"] is None and info["axis"] is None:
        return None
    return info


class ProgramIR:
    """Derived tables over one Program: ops, producer/consumer maps,
    initial abstract environment, fetch roots, collective log."""

    def __init__(self, program, feed_spec: Optional[Dict[str, Any]] = None,
                 name: str = "program"):
        self.program = program
        self.name = name
        self.ops: List[OpView] = [OpView(i, e)
                                  for i, e in enumerate(program.ops)]

        uid_of = type(program)._uid
        self.feed_uids: Dict[str, int] = {
            n: uid_of(t) for n, t in program.feed_targets.items()}
        self.fetch_uids: List[int] = [uid_of(t)
                                      for t in program.fetch_targets]

        # initial abstract environment: feeds (spec override wins) then
        # the remaining externals from the live-read table
        self.initial_env: Dict[int, jax.ShapeDtypeStruct] = {}
        for fname, t in program.feed_targets.items():
            spec = (feed_spec or {}).get(fname)
            aval = aval_of(spec) if spec is not None else aval_of(t)
            if aval is not None:
                self.initial_env[uid_of(t)] = aval
        feed_uid_set = set(self.feed_uids.values())
        self.external_uids: List[int] = []
        for u, t in program._live.items():
            if u in feed_uid_set:
                continue
            aval = aval_of(t)
            if aval is not None:
                self.initial_env.setdefault(u, aval)
                self.external_uids.append(u)

        self.producer: Dict[int, int] = {}
        self.consumers: Dict[int, List[int]] = {}
        for op in self.ops:
            for u in op.out_uids:
                self.producer.setdefault(u, op.index)
            for u in op.in_uids:
                self.consumers.setdefault(u, []).append(op.index)

        # collective log: the explicit meta recorded by
        # distributed.collective (preferred — includes eager p2p that
        # never becomes an op entry), else closure recovery per entry
        meta = list(getattr(program, "collective_meta", ()) or ())
        if not meta:
            meta = [m for m in (collective_info(op) for op in self.ops)
                    if m is not None]
        self.collectives: List[Dict[str, Any]] = meta

    def abstract_eval_op(self, op: OpView,
                         env: Dict[int, jax.ShapeDtypeStruct]):
        """infermeta for one entry: rebuild the flat arg list with
        ShapeDtypeStructs from ``env`` and run ``jax.eval_shape`` over
        the recorded callable.  Returns (updates, input_avals); raises
        whatever the abstract trace raises (the caller turns that into
        a PT601 finding)."""
        (name, fn, entry_flat, tpos, in_uids, treedef, out_positions,
         out_uids) = op.entry[:8]
        flat2 = list(entry_flat)
        in_avals = []
        for i, u in zip(tpos, in_uids):
            aval = env.get(u)
            if aval is None:
                raise KeyError(
                    f"input uid {u} of op #{op.index} ({name}) has no "
                    f"known abstract value (producer missing or failed)")
            flat2[i] = aval
            in_avals.append(aval)
        a2, k2 = jax.tree_util.tree_unflatten(treedef, flat2)
        out = jax.eval_shape(fn, *a2, **k2)
        leaves = jax.tree_util.tree_leaves(out)
        updates = {}
        for pos, u in zip(out_positions, out_uids):
            leaf = leaves[pos]
            updates[u] = jax.ShapeDtypeStruct(tuple(leaf.shape),
                                              np.dtype(leaf.dtype))
        return updates, in_avals

    def jaxpr(self, op: OpView, env: Dict[int, jax.ShapeDtypeStruct]):
        """The jaxpr behind one entry, traced at the abstract input
        types from ``env`` — the drill-down view for tooling."""
        (_name, fn, entry_flat, tpos, in_uids, treedef) = op.entry[:6]
        flat2 = list(entry_flat)
        for i, u in zip(tpos, in_uids):
            flat2[i] = env[u]
        a2, k2 = jax.tree_util.tree_unflatten(treedef, flat2)
        return jax.make_jaxpr(lambda *a, **k: fn(*a, **k))(*a2, **k2)

    def last_use(self) -> Dict[int, int]:
        """uid -> index of its last consuming op; fetched uids are
        pinned to the final index (they must survive to the end)."""
        n = len(self.ops)
        out: Dict[int, int] = {}
        for u, idxs in self.consumers.items():
            out[u] = max(idxs)
        for u in self.fetch_uids:
            out[u] = n - 1 if n else 0
        return out
