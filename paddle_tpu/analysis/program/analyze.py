"""The ptprog driver: run all four IR passes over one Program and
assemble an ``engine.Report`` so the ptlint reporters and baseline
workflow apply unchanged."""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from .. import engine
from .collectives import check_collectives, check_pipeline
from .dataflow import check_dataflow
from .ir import ProgramIR
from .memory import MemoryReport, check_memory
from .verify import PassVerificationError, VerifyReport, verify_pass

__all__ = ["AnalysisResult", "analyze", "shipped_passes"]


def shipped_passes():
    """The six registered Program passes, as (name, callable) — what
    pass-equivalence verification exercises by default."""
    import functools

    from ...static import passes as P

    return [
        ("dead_op_elimination", P.dead_op_elimination),
        ("constant_folding", P.constant_folding),
        ("fuse_chain[matmul,relu]",
         functools.partial(P.fuse_chain, names=["matmul", "relu"])),
        ("auto_fuse", P.auto_fuse),
        ("amp_insertion", P.amp_insertion),
        ("recompute_pass", P.recompute_pass),
    ]


@dataclass
class AnalysisResult:
    report: engine.Report
    memory: Optional[MemoryReport] = None
    verify: List[VerifyReport] = field(default_factory=list)
    env: Dict[int, object] = field(default_factory=dict)

    @property
    def exit_code(self) -> int:
        return self.report.exit_code


def _apply_baseline_and_select(findings, baseline, select) -> engine.Report:
    report = engine.Report(files=1)

    def selected(rid):
        if select is None:
            return True
        return any(rid == s or (s.endswith("xx") and rid.startswith(s[:-2]))
                   for s in select)

    base_counts = engine.load_baseline(baseline) if baseline else {}
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule_id)):
        if not selected(f.rule_id):
            continue
        k = f.key()
        if base_counts.get(k, 0) > 0:
            base_counts[k] -= 1
            report.baselined.append(f)
        else:
            report.findings.append(f)
    return report


def analyze(program=None, name: str = "program", feed_spec=None,
            mesh=None, budget_bytes: Optional[int] = None,
            capture_fn=None, stage_programs: Optional[Sequence] = None,
            baseline: Optional[str] = None,
            select: Optional[Sequence[str]] = None) -> AnalysisResult:
    """Run the four IR passes over ``program``.

    - dataflow (PT60x) and memory (PT61x) always run;
    - collective consistency (PT62x) runs against ``mesh`` (default:
      the active topology mesh), plus cross-stage send/recv matching
      when ``stage_programs`` is given;
    - pass equivalence (PT63x) runs when ``capture_fn`` can produce a
      fresh Program per shipped pass (passes mutate what they verify).
    """
    findings: List[engine.Finding] = []
    memrep = None
    verify_reports: List[VerifyReport] = []
    env: Dict[int, object] = {}

    if program is not None:
        ir = ProgramIR(program, feed_spec=feed_spec, name=name)
        env, findings = check_dataflow(ir)
        mem_f, memrep = check_memory(ir, env, budget_bytes)
        findings.extend(mem_f)
        findings.extend(check_collectives(ir, mesh=mesh))

    if stage_programs:
        findings.extend(check_pipeline(stage_programs, mesh=mesh))

    if capture_fn is not None:
        for pname, p in shipped_passes():
            fresh = capture_fn()
            try:
                verify_reports.append(
                    verify_pass(fresh, p, feed_spec=feed_spec,
                                pass_name=pname))
            except PassVerificationError as e:
                for d in e.diffs:
                    rid = "PT631" if d.startswith("[PT631]") else "PT630"
                    findings.append(engine.Finding(
                        rid, "error", f"program:{name}", 0, 0,
                        f"pass '{pname}': "
                        + d.split("] ", 1)[-1], line_text=pname))

    try:
        from ...profiler import metrics as _metrics

        _metrics.inc("analysis/programs_analyzed")
        if program is not None:
            _metrics.inc("analysis/ops_analyzed", len(program.ops))
    except Exception:
        pass

    report = _apply_baseline_and_select(findings, baseline, select)
    try:
        from ...profiler import metrics as _metrics

        _metrics.inc("analysis/findings", len(report.findings))
    except Exception:
        pass
    return AnalysisResult(report=report, memory=memrep,
                          verify=verify_reports, env=env)
