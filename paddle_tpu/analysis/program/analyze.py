"""The ptprog driver: run all four IR passes over one Program and
assemble an ``engine.Report`` so the ptlint reporters and baseline
workflow apply unchanged."""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from .. import engine
from .collectives import check_collectives, check_pipeline
from .dataflow import check_dataflow
from .ir import ProgramIR
from .memory import MemoryReport, check_memory
from .verify import PassVerificationError, VerifyReport, verify_pass

__all__ = ["AnalysisResult", "analyze", "shipped_passes"]


def shipped_passes():
    """The six registered Program passes, as (name, callable) — what
    pass-equivalence verification exercises by default."""
    import functools

    from ...static import passes as P

    return [
        ("dead_op_elimination", P.dead_op_elimination),
        ("constant_folding", P.constant_folding),
        ("fuse_chain[matmul,relu]",
         functools.partial(P.fuse_chain, names=["matmul", "relu"])),
        ("auto_fuse", P.auto_fuse),
        ("amp_insertion", P.amp_insertion),
        ("recompute_pass", P.recompute_pass),
    ]


@dataclass
class AnalysisResult:
    report: engine.Report
    memory: Optional[MemoryReport] = None
    verify: List[VerifyReport] = field(default_factory=list)
    env: Dict[int, object] = field(default_factory=dict)
    sharding: Optional[object] = None      # sharding.ShardingReport

    @property
    def exit_code(self) -> int:
        return self.report.exit_code


def _apply_baseline_and_select(findings, baseline, select) -> engine.Report:
    return engine.apply_baseline_and_select(findings, baseline, select)


def _shard_metrics(shard_rep, shard_findings) -> None:
    try:
        from ...profiler import metrics as _metrics

        _metrics.inc("analysis/shard_runs")
        _metrics.inc("analysis/shard_findings", len(shard_findings))
    except Exception:
        pass


def _stage_sharding(stage_programs, shard_mesh, shard_plan):
    """PT905: cross-stage boundary sharding mismatches.  Builds one
    ShardGraph per pipeline stage and pairs stage ``i`` fetches with
    stage ``i+1`` feeds under the propagated specs."""
    from ..sharding import (MeshSpec, check_stage_boundaries,
                            graph_from_program, plan_by_name)

    try:
        mesh = (shard_mesh if isinstance(shard_mesh, MeshSpec)
                else MeshSpec.parse(shard_mesh)
                if isinstance(shard_mesh, str)
                else MeshSpec.from_mesh(shard_mesh))
    except Exception:
        return []
    graphs, plans = [], []
    for i, sp in enumerate(stage_programs):
        try:
            g = graph_from_program(sp, None, name=f"stage{i}")
        except Exception:
            return []      # un-analyzable stage: PT62x already covers it
        graphs.append(g)
        if shard_plan is None or isinstance(shard_plan, str):
            plans.append(plan_by_name(shard_plan or "replicated", g, mesh))
        else:
            plans.append(shard_plan)
    return check_stage_boundaries(graphs, mesh, plans=plans)


def analyze(program=None, name: str = "program", feed_spec=None,
            mesh=None, budget_bytes: Optional[int] = None,
            capture_fn=None, stage_programs: Optional[Sequence] = None,
            baseline: Optional[str] = None,
            select: Optional[Sequence[str]] = None,
            shard_mesh=None, shard_plan=None) -> AnalysisResult:
    """Run the IR passes over ``program``.

    - dataflow (PT60x) and memory (PT61x) always run;
    - collective consistency (PT62x) runs against ``mesh`` (default:
      the active topology mesh), plus cross-stage send/recv matching
      when ``stage_programs`` is given;
    - pass equivalence (PT63x) runs when ``capture_fn`` can produce a
      fresh Program per shipped pass (passes mutate what they verify);
    - sharding propagation (PT9xx) runs when ``shard_mesh`` is given
      (a MeshSpec, jax Mesh, or ``"dp=2,mp=2"``-style string; falls
      back to ``mesh``), seeded from ``shard_plan`` ("replicated" |
      "megatron" | a ShardingPlan).  Stage programs additionally get
      the PT905 boundary check.
    """
    findings: List[engine.Finding] = []
    memrep = None
    verify_reports: List[VerifyReport] = []
    env: Dict[int, object] = {}
    shard_rep = None

    if shard_mesh is None:
        shard_mesh = mesh

    if program is not None:
        ir = ProgramIR(program, feed_spec=feed_spec, name=name)
        env, findings = check_dataflow(ir)
        mem_f, memrep = check_memory(ir, env, budget_bytes)
        findings.extend(mem_f)
        findings.extend(check_collectives(ir, mesh=mesh))
        if shard_mesh is not None:
            from ..sharding import check_sharding

            shard_f, shard_rep = check_sharding(
                ir, env, shard_mesh, plan=shard_plan)
            findings.extend(shard_f)
            _shard_metrics(shard_rep, shard_f)

    if stage_programs:
        findings.extend(check_pipeline(stage_programs, mesh=mesh))
        if shard_mesh is not None:
            findings.extend(_stage_sharding(stage_programs, shard_mesh,
                                            shard_plan))

    if capture_fn is not None:
        for pname, p in shipped_passes():
            fresh = capture_fn()
            try:
                verify_reports.append(
                    verify_pass(fresh, p, feed_spec=feed_spec,
                                pass_name=pname))
            except PassVerificationError as e:
                for d in e.diffs:
                    rid = "PT631" if d.startswith("[PT631]") else "PT630"
                    findings.append(engine.Finding(
                        rid, "error", f"program:{name}", 0, 0,
                        f"pass '{pname}': "
                        + d.split("] ", 1)[-1], line_text=pname))

    try:
        from ...profiler import metrics as _metrics

        _metrics.inc("analysis/programs_analyzed")
        if program is not None:
            _metrics.inc("analysis/ops_analyzed", len(program.ops))
    except Exception:
        pass

    report = _apply_baseline_and_select(findings, baseline, select)
    try:
        from ...profiler import metrics as _metrics

        _metrics.inc("analysis/findings", len(report.findings))
    except Exception:
        pass
    return AnalysisResult(report=report, memory=memrep,
                          verify=verify_reports, env=env,
                          sharding=shard_rep)
