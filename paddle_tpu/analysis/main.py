"""Shared CLI for the two analysis surfaces:

- **ptlint** (source level): ``python -m paddle_tpu.analysis <paths>``
  or ``tools/ptlint.py`` — the jax-free AST rule families PT1xx–PT5xx.
- **ptprog** (IR level): ``python -m paddle_tpu.analysis --program
  <target>`` or ``tools/ptprog.py`` — the PT6xx passes over a recorded
  ``static.Program`` (needs jax for abstract evaluation).

Both share reporters (``--format text|json|sarif``) and the committed
``.ptlint-baseline.json`` grandfather workflow; ``--update-baseline``
prunes entries whose findings no longer fire.
"""
from __future__ import annotations

import argparse
import os
import sys

from . import engine


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="ptlint",
        description="paddle_tpu framework-aware static analysis "
                    "(PT1xx trace-safety, PT2xx SPMD collectives, "
                    "PT3xx Pallas grid contracts, PT4xx registry "
                    "consistency, PT5xx error surfacing; "
                    "--conc: PT7xx race detector + PT8xx fleet "
                    "protocols; --program: PT6xx IR-level Program "
                    "analysis)")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files or directories to lint "
                         "(default: paddle_tpu/)")
    ap.add_argument("--format", choices=("text", "json", "sarif"),
                    default="text")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON path (default: nearest "
                         f"{engine.BASELINE_NAME} above the first path)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore any baseline file")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write all current findings as the new baseline "
                         "and exit 0")
    ap.add_argument("--update-baseline", action="store_true",
                    help="prune baseline entries whose findings no "
                         "longer fire (keeps the grandfather list "
                         "honest) and exit 0")
    ap.add_argument("--select", action="append", default=None,
                    metavar="RULE",
                    help="restrict to rule id(s); family form PT3xx ok "
                         "(repeatable)")
    ap.add_argument("--families", default=None, metavar="FAMS",
                    help="comma list of rule families, e.g. PT7,PT8 "
                         "(shorthand for --select PT7xx --select PT8xx)")
    ap.add_argument("--conc", action="store_true",
                    help="concurrency mode (ptrace): only the PT7xx "
                         "race-detector and PT8xx fleet-protocol "
                         "families")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--program", default=None, metavar="TARGET",
                    help="IR mode: analyze a recorded static.Program "
                         "instead of source files. TARGET is a preset "
                         "(llama, mlp) or module.path:callable returning "
                         "a Program/Capture")
    ap.add_argument("--budget-gb", type=float, default=None,
                    help="device memory budget for the peak-memory "
                         "check (PT610), in GiB")
    ap.add_argument("--memory-report", action="store_true",
                    help="print the full per-op memory/roofline table "
                         "(IR mode, text format)")
    ap.add_argument("--mesh", default=None, metavar="SPEC",
                    help="IR mode: mesh for sharding propagation "
                         "(PT9xx), e.g. 'dp=2,mp=4' or two-tier "
                         "'dp=2@dcn,mp=4' (default: dp=2,mp=2; "
                         "'none' disables the pass)")
    ap.add_argument("--plan", default="megatron",
                    choices=("megatron", "replicated"),
                    help="IR mode: sharding plan seeding the PT9xx "
                         "propagation (default: megatron)")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid, r in sorted(engine.all_rules().items()):
            print(f"{rid}  [{r.severity:7s}] ({r.scope}) {r.summary}")
        for rid, sev, summary in engine.PTPROG_RULES:
            print(f"{rid}  [{sev:7s}] (program) {summary}")
        return 0

    # fold --families/--conc into --select before branching: program
    # mode honors the same selection syntax (--families PT9, PT6xx, ...)
    select = list(args.select or [])
    if args.families:
        select += [f"{fam.strip()}xx" for fam in args.families.split(",")
                   if fam.strip()]
    if args.conc:
        select += ["PT7xx", "PT8xx"]
    args.select = select or None
    tool = "ptrace" if args.conc else "ptlint"

    if args.program is not None:
        return _run_program_mode(args)

    paths = args.paths or ["paddle_tpu"]
    for p in paths:
        if not os.path.exists(p):
            print(f"ptlint: no such path: {p}", file=sys.stderr)
            return 2

    baseline = None
    if not args.no_baseline and not args.write_baseline:
        baseline = args.baseline or engine.find_baseline(paths[0])
        if baseline and not os.path.isfile(baseline):
            print(f"ptlint: baseline not found: {baseline}",
                  file=sys.stderr)
            return 2

    report = engine.run(paths, baseline=baseline, select=args.select)
    _emit_conc_metrics(args, report)

    if args.write_baseline:
        target = args.baseline or os.path.join(
            os.path.dirname(engine.find_baseline(paths[0]) or
                            os.path.join(os.getcwd(), "x")),
            engine.BASELINE_NAME)
        engine.write_baseline(target, report.findings)
        print(f"ptlint: wrote {len(report.findings)} entr"
              f"{'y' if len(report.findings) == 1 else 'ies'} to "
              f"{target}")
        return 0

    if args.update_baseline:
        if not baseline:
            print("ptlint: --update-baseline needs an existing baseline",
                  file=sys.stderr)
            return 2
        n_before = sum(engine.load_baseline(baseline).values())
        engine.write_baseline(baseline, report.baselined)
        pruned = n_before - len(report.baselined)
        print(f"ptlint: baseline {baseline}: kept "
              f"{len(report.baselined)} live entr"
              f"{'y' if len(report.baselined) == 1 else 'ies'}, pruned "
              f"{pruned} stale")
        return 0

    print(_render(report, args.format, tool=tool))
    return report.exit_code


def _emit_conc_metrics(args, report) -> None:
    """Count ptrace runs/findings when the metrics registry is
    importable (full-framework invocation); the jax-free tools/ptrace.py
    path stays import-light and just skips this."""
    if not args.conc:
        return
    try:
        from ..profiler import metrics as _metrics
    except Exception:
        return
    _metrics.counter("analysis/conc_runs").inc()
    if report.findings:
        _metrics.counter("analysis/conc_findings").inc(
            len(report.findings))


def _render(report, fmt: str, tool: str = "ptlint") -> str:
    if fmt == "json":
        return engine.render_json(report)
    if fmt == "sarif":
        return engine.render_sarif(report, tool_name=tool)
    return engine.render_text(report, tool_name=tool)


def _run_program_mode(args) -> int:
    # imported lazily: the IR analyzer needs jax; plain lint runs stay
    # milliseconds-fast and jax-free
    from .program import analyze, load_target
    from .program.memory import render_memory_report

    cap = load_target(args.program)

    baseline = None
    if not args.no_baseline:
        baseline = args.baseline or engine.find_baseline(os.getcwd())
        if baseline and not os.path.isfile(baseline):
            baseline = None

    budget = (int(args.budget_gb * (1 << 30))
              if args.budget_gb is not None else None)
    shard_mesh = None
    mesh_arg = getattr(args, "mesh", None)
    if mesh_arg is None:
        mesh_arg = "dp=2,mp=2"     # demo mesh: every program-mode run
        #                            exercises the PT9xx pass by default
    if mesh_arg.lower() not in ("none", "off", ""):
        from .sharding import MeshSpec

        shard_mesh = MeshSpec.parse(mesh_arg)
    res = analyze(cap.program, name=cap.name, feed_spec=cap.feed_spec,
                  mesh=cap.mesh, budget_bytes=budget,
                  capture_fn=cap.capture_fn, baseline=baseline,
                  select=args.select, shard_mesh=shard_mesh,
                  shard_plan=getattr(args, "plan", None) or "megatron")

    out = _render(res.report, args.format, tool="ptprog")
    if args.format == "text":
        extra = []
        if res.memory is not None:
            extra.append(render_memory_report(
                res.memory, top=10_000 if args.memory_report else 12))
        if res.sharding is not None:
            from .sharding import render_sharding_report

            extra.append(render_sharding_report(res.sharding))
        if res.verify:
            extra.append("pass verification:")
            extra.extend(f"  {v.summary()}" for v in res.verify)
        out = "\n".join([out] + extra)
    print(out)
    return res.report.exit_code
