"""ptlint CLI (shared by ``python -m paddle_tpu.analysis`` and
``tools/ptlint.py``)."""
from __future__ import annotations

import argparse
import os
import sys

from . import engine


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="ptlint",
        description="paddle_tpu framework-aware static analysis "
                    "(PT1xx trace-safety, PT2xx SPMD collectives, "
                    "PT3xx Pallas grid contracts, PT4xx registry "
                    "consistency)")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files or directories to lint "
                         "(default: paddle_tpu/)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON path (default: nearest "
                         f"{engine.BASELINE_NAME} above the first path)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore any baseline file")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write all current findings as the new baseline "
                         "and exit 0")
    ap.add_argument("--select", action="append", default=None,
                    metavar="RULE",
                    help="restrict to rule id(s); family form PT3xx ok "
                         "(repeatable)")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid, r in sorted(engine.all_rules().items()):
            print(f"{rid}  [{r.severity:7s}] ({r.scope}) {r.summary}")
        return 0

    paths = args.paths or ["paddle_tpu"]
    for p in paths:
        if not os.path.exists(p):
            print(f"ptlint: no such path: {p}", file=sys.stderr)
            return 2

    baseline = None
    if not args.no_baseline and not args.write_baseline:
        baseline = args.baseline or engine.find_baseline(paths[0])
        if baseline and not os.path.isfile(baseline):
            print(f"ptlint: baseline not found: {baseline}",
                  file=sys.stderr)
            return 2

    report = engine.run(paths, baseline=baseline, select=args.select)

    if args.write_baseline:
        target = args.baseline or os.path.join(
            os.path.dirname(engine.find_baseline(paths[0]) or
                            os.path.join(os.getcwd(), "x")),
            engine.BASELINE_NAME)
        engine.write_baseline(target, report.findings)
        print(f"ptlint: wrote {len(report.findings)} entr"
              f"{'y' if len(report.findings) == 1 else 'ies'} to "
              f"{target}")
        return 0

    out = engine.render_json(report) if args.format == "json" \
        else engine.render_text(report)
    print(out)
    return report.exit_code
