"""PT2xx — SPMD-collective ordering rules.

Collectives from ``distributed/collective.py`` are *collective* by
contract: every rank of the group must issue the same sequence of them,
or the mesh deadlocks — and that deadlock only manifests on a real
multi-host run (the single-process CPU test mesh reduces every
collective to identity/local math, so tier-1 tests can never catch it).

The SPMD-safe idioms are value-level selects (``jnp.where(stage == 0,
...)``, as the compiled pipeline engines do) or *mirrored* branches
(``if rank == 0: send(...) else: recv(...)``). What these rules catch
is the broken middle ground: a collective issued under rank-dependent
Python control flow with nothing matching it on the other side, and
mirrored send/recv pairs wired to different groups.
"""
from __future__ import annotations

import ast

from .engine import call_name, dotted_name, rule

# the collective surface of distributed/collective.py (+ stream aliases)
COLLECTIVE_NAMES = frozenset({
    "all_reduce", "all_gather", "all_gather_object", "reduce_scatter",
    "all_to_all", "all_to_all_single", "alltoall", "broadcast",
    "broadcast_object_list", "reduce", "scatter", "scatter_object_list",
    "gather", "send", "recv", "isend", "irecv", "barrier",
})

_SENDS = {"send", "isend"}
_RECVS = {"recv", "irecv"}

_RANK_NAMES = {"rank", "local_rank", "global_rank", "rank_id",
               "stage_id", "pp_rank", "mp_rank", "dp_rank"}
_RANK_CALLS = {"get_rank", "global_rank", "local_rank", "axis_index",
               "get_group_rank", "get_stage_id"}
_RANK_ATTRS = _RANK_NAMES | {"is_first_stage", "is_last_stage",
                             "is_first_rank", "is_last_rank"}


def _rank_dependent(test) -> bool:
    """Does this branch condition read the process identity?"""
    for node in ast.walk(test):
        if isinstance(node, ast.Name) and node.id in _RANK_NAMES:
            return True
        if isinstance(node, ast.Attribute) and node.attr in _RANK_ATTRS:
            return True
        if isinstance(node, ast.Call):
            cn = call_name(node)
            if cn in _RANK_CALLS:
                return True
    return False


def _collective_calls(stmts):
    """All collective Call nodes in a statement list (subtree walk,
    excluding nested function defs — those run on their own schedule)."""
    out = []
    for stmt in stmts:
        for node in ast.walk(stmt):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            if isinstance(node, ast.Call):
                cn = call_name(node)
                if cn in COLLECTIVE_NAMES:
                    out.append((cn, node))
    return out


def _group_kwarg(call: ast.Call):
    for kw in call.keywords:
        if kw.arg == "group":
            return dotted_name(kw.value) or ast.dump(kw.value)
    return None


def _rank_conditional_ifs(mod):
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.If) and _rank_dependent(node.test):
            yield node


@rule("PT201", "error",
      "collective under a rank-conditional branch with no matching "
      "collective on the other side (SPMD deadlock)")
def check_unmatched_collective(mod):
    seen_ifs = set()
    for if_node in _rank_conditional_ifs(mod):
        if id(if_node) in seen_ifs:
            continue
        seen_ifs.add(id(if_node))
        body_calls = _collective_calls(if_node.body)
        else_calls = _collective_calls(if_node.orelse)
        if body_calls and not else_calls:
            flagged, missing = body_calls, "else"
        elif else_calls and not body_calls:
            flagged, missing = else_calls, "if"
        else:
            continue
        for cn, node in flagged:
            yield (node.lineno, node.col_offset,
                   f"'{cn}' issued under a rank-dependent branch "
                   f"(line {if_node.lineno}) with no collective in the "
                   f"{missing} branch: ranks taking the other path never "
                   f"enter the collective and the group deadlocks on a "
                   f"real mesh; mirror the call in both branches or use "
                   f"a value-level select (jnp.where / lax.cond)")


@rule("PT202", "error",
      "mirrored send/recv branches wired to different groups")
def check_send_recv_group_mismatch(mod):
    for if_node in _rank_conditional_ifs(mod):
        body_calls = _collective_calls(if_node.body)
        else_calls = _collective_calls(if_node.orelse)
        if not body_calls or not else_calls:
            continue
        body_sends = [c for n, c in body_calls if n in _SENDS]
        body_recvs = [c for n, c in body_calls if n in _RECVS]
        else_sends = [c for n, c in else_calls if n in _SENDS]
        else_recvs = [c for n, c in else_calls if n in _RECVS]
        for sends, recvs in ((body_sends, else_recvs),
                             (else_sends, body_recvs)):
            if not sends or not recvs:
                continue
            send_groups = {_group_kwarg(c) for c in sends}
            recv_groups = {_group_kwarg(c) for c in recvs}
            # only meaningful when both sides name a group explicitly
            if None in send_groups or None in recv_groups:
                continue
            if send_groups != recv_groups:
                c = sends[0]
                yield (c.lineno, c.col_offset,
                       f"paired send/recv across the rank branch at line "
                       f"{if_node.lineno} use different group= arguments "
                       f"({sorted(send_groups)} vs {sorted(recv_groups)}): "
                       f"the two sides rendezvous on different "
                       f"communicators and hang")
