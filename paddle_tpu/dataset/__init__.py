"""paddle.dataset compat (reference: python/paddle/dataset/ — the legacy
reader-creator dataset family: mnist, cifar, uci_housing, imdb, imikolov,
movielens, conll05, flowers, voc2012, wmt14, wmt16, image, common).

TPU-native stance: datasets are host-side input-pipeline concerns; these
readers keep the reference's generator contract (`train()(…) -> yields
sample tuples`) so Fleet-style scripts run unchanged. Network downloads
are out (no egress). Families with an open standard file format — mnist
(idx-gzip), cifar (python pickles), uci_housing, imikolov (ptb text),
movielens (ml-1m .dat) — parse the REAL files when staged under
`~/.cache/paddle_tpu/dataset/<name>` (or `PPTPU_DATASET_HOME`); absent
files, and the remaining families (imdb, conll05, flowers, voc2012,
wmt14/16 — whose archives need project-specific pipelines), yield
deterministic synthetic data with the documented shapes. Every reader
carries `reader.synthetic` so callers can tell which they got.
"""
from __future__ import annotations

import gzip
import os
import pickle
import tarfile

import numpy as np

__all__ = ["uci_housing", "mnist", "cifar", "imdb", "imikolov",
           "movielens", "conll05", "flowers", "voc2012", "wmt14",
           "wmt16", "image", "common"]


def _data_home():
    return os.environ.get(
        "PPTPU_DATASET_HOME",
        os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                     "dataset"))


def _mark(reader, synthetic):
    reader.synthetic = synthetic
    return reader


def _synthetic_reader(make, n):
    def reader():
        rng = np.random.RandomState(0)
        for _ in range(n):
            yield make(rng)

    return _mark(reader, True)


class common:
    """reference: dataset/common.py — cache-dir + reader utilities."""

    @staticmethod
    def md5file(fname):
        import hashlib

        h = hashlib.md5()
        with open(fname, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
        return h.hexdigest()

    @staticmethod
    def download(url, module_name, md5sum=None, save_name=None):
        """Offline build: never fetches. Returns the expected local path
        and raises with instructions when the file is absent."""
        d = os.path.join(_data_home(), module_name)
        path = os.path.join(d, save_name or url.split("/")[-1])
        if os.path.exists(path):
            if md5sum and len(str(md5sum)) == 32 \
                    and common.md5file(path) != md5sum:
                raise RuntimeError(
                    f"dataset file {path} exists but its md5 does not "
                    f"match {md5sum} (truncated copy?)")
            return path
        raise RuntimeError(
            f"dataset file {path} not found and this build has no "
            f"network egress; place the file there manually (source: "
            f"{url})")

    @staticmethod
    def split(reader, line_count, suffix="%05d.pickle", dumper=None):
        import pickle as pk

        dumper = dumper or pk.dump
        out, buf, idx = [], [], 0
        for item in reader():
            buf.append(item)
            if len(buf) == line_count:
                fn = suffix % idx
                with open(fn, "wb") as f:
                    dumper(buf, f)
                out.append(fn)
                buf, idx = [], idx + 1
        if buf:
            fn = suffix % idx
            with open(fn, "wb") as f:
                dumper(buf, f)
            out.append(fn)
        return out

    @staticmethod
    def cluster_files_reader(files_pattern, trainer_count, trainer_id,
                             loader=None):
        import glob
        import pickle as pk

        loader = loader or pk.load

        def reader():
            flist = sorted(glob.glob(files_pattern))
            for i, fn in enumerate(flist):
                if i % trainer_count == trainer_id:
                    with open(fn, "rb") as f:
                        for item in loader(f):
                            yield item

        return _mark(reader, False)


class uci_housing:
    feature_num = 13

    @staticmethod
    def _load():
        path = os.path.join(_data_home(), "uci_housing", "housing.data")
        if not os.path.exists(path):
            return None
        data = np.loadtxt(path)
        feat = data[:, :-1].astype(np.float32)
        feat = (feat - feat.mean(0)) / (feat.std(0) + 1e-8)
        return feat, data[:, -1:].astype(np.float32)

    @staticmethod
    def _reader(split, n):
        loaded = uci_housing._load()
        if loaded is None:
            return _synthetic_reader(
                lambda rng: (rng.randn(13).astype(np.float32),
                             rng.randn(1).astype(np.float32)),
                n if n is not None else 404)
        feat, target = loaded
        cut = int(len(feat) * 0.8)
        sl = slice(0, cut) if split == "train" else slice(cut, None)

        def reader():
            for i, (x, y) in enumerate(zip(feat[sl], target[sl])):
                if n is not None and i >= n:
                    return           # n stays a hard cap with real files
                yield x, y

        return _mark(reader, False)

    @staticmethod
    def train(n=None):
        return uci_housing._reader("train", n)

    @staticmethod
    def test(n=None):
        return uci_housing._reader("test", n)


class mnist:
    """Parses the standard idx-gzip files when present."""

    @staticmethod
    def _load(images_name, labels_name):
        d = os.path.join(_data_home(), "mnist")
        ip = os.path.join(d, images_name)
        lp = os.path.join(d, labels_name)
        if not (os.path.exists(ip) and os.path.exists(lp)):
            return None
        with gzip.open(ip, "rb") as f:
            buf = f.read()
            n = int.from_bytes(buf[4:8], "big")
            imgs = np.frombuffer(buf, np.uint8, offset=16) \
                .reshape(n, 784).astype(np.float32) / 127.5 - 1.0
        with gzip.open(lp, "rb") as f:
            buf = f.read()
            labels = np.frombuffer(buf, np.uint8, offset=8)
        return imgs, labels

    @staticmethod
    def _reader(images_name, labels_name, n):
        loaded = mnist._load(images_name, labels_name)
        if loaded is None:
            return _synthetic_reader(
                lambda rng: (rng.rand(784).astype(np.float32) * 2 - 1,
                             int(rng.randint(0, 10))),
                n if n is not None else 256)
        imgs, labels = loaded

        def reader():
            for i, (x, y) in enumerate(zip(imgs, labels)):
                if n is not None and i >= n:
                    return           # n stays a hard cap with real files
                yield x, int(y)

        return _mark(reader, False)

    @staticmethod
    def train(n=None):
        return mnist._reader("train-images-idx3-ubyte.gz",
                             "train-labels-idx1-ubyte.gz", n)

    @staticmethod
    def test(n=None):
        return mnist._reader("t10k-images-idx3-ubyte.gz",
                             "t10k-labels-idx1-ubyte.gz", n)


class cifar:
    """Parses the standard python-pickle tarballs when present."""

    @staticmethod
    def _tar_reader(tar_name, sub_match, n, n_classes):
        path = os.path.join(_data_home(), "cifar", tar_name)
        if not os.path.exists(path):
            return _synthetic_reader(
                lambda rng: (rng.rand(3072).astype(np.float32),
                             int(rng.randint(0, n_classes))),
                n if n is not None else 256)

        def reader():
            count = 0
            with tarfile.open(path) as tf:
                for m in tf.getmembers():
                    if sub_match not in m.name or m.isdir():
                        continue
                    batch = pickle.load(tf.extractfile(m),
                                        encoding="latin1")
                    labels = batch.get("labels",
                                       batch.get("fine_labels"))
                    for img, lab in zip(batch["data"], labels):
                        if n is not None and count >= n:
                            return   # n stays a hard cap with real files
                        count += 1
                        yield (img.astype(np.float32) / 255.0, int(lab))

        return _mark(reader, False)

    @staticmethod
    def train10(n=None):
        return cifar._tar_reader("cifar-10-python.tar.gz", "data_batch",
                                 n, 10)

    @staticmethod
    def test10(n=None):
        return cifar._tar_reader("cifar-10-python.tar.gz", "test_batch",
                                 n, 10)

    @staticmethod
    def train100(n=None):
        return cifar._tar_reader("cifar-100-python.tar.gz", "train",
                                 n, 100)

    @staticmethod
    def test100(n=None):
        return cifar._tar_reader("cifar-100-python.tar.gz", "test",
                                 n, 100)


class imdb:
    @staticmethod
    def word_dict():
        return {f"w{i}": i for i in range(128)}

    @staticmethod
    def train(word_idx, n=128):
        v = len(word_idx)
        return _synthetic_reader(
            lambda rng: (rng.randint(0, v, rng.randint(5, 40)).tolist(),
                         int(rng.randint(0, 2))), n)

    @staticmethod
    def test(word_idx, n=32):
        return imdb.train(word_idx, n)


class imikolov:
    """PTB language-model readers (reference dataset/imikolov.py):
    NGRAM yields n-gram index tuples, SEQ yields (ids[:-1], ids[1:])."""

    class DataType:
        NGRAM = 1
        SEQ = 2

    _SYN_VOCAB = 64

    @staticmethod
    def _corpus(split):
        path = os.path.join(_data_home(), "imikolov",
                            f"ptb.{split}.txt")
        if os.path.exists(path):
            with open(path) as f:
                return [ln.strip().split() for ln in f if ln.strip()]
        rng = np.random.RandomState(7)
        words = [f"tok{i}" for i in range(imikolov._SYN_VOCAB - 4)]
        return [[words[i] for i in
                 rng.randint(0, len(words), rng.randint(4, 12))]
                for _ in range(200 if split == "train" else 40)]

    @staticmethod
    def build_dict(min_word_freq=1):
        freq = {}
        for line in imikolov._corpus("train"):
            for w in line:
                freq[w] = freq.get(w, 0) + 1
        freq.pop("<unk>", None)
        kept = sorted(w for w, c in freq.items() if c >= min_word_freq)
        word_idx = {w: i for i, w in enumerate(kept)}
        word_idx["<unk>"] = len(word_idx)
        return word_idx

    @staticmethod
    def _reader(split, word_idx, n, data_type):
        def reader():
            UNK = word_idx["<unk>"]
            for line in imikolov._corpus(split):
                ids = [word_idx.get("<s>", UNK)] \
                    + [word_idx.get(w, UNK) for w in line] \
                    + [word_idx.get("<e>", UNK)]
                if data_type == imikolov.DataType.NGRAM:
                    if len(ids) >= n:
                        for i in range(n - 1, len(ids)):
                            yield tuple(ids[i - n + 1:i + 1])
                else:
                    yield ids[:-1], ids[1:]

        return _mark(reader, not os.path.exists(
            os.path.join(_data_home(), "imikolov", f"ptb.{split}.txt")))

    @staticmethod
    def train(word_idx, n, data_type=DataType.NGRAM):
        return imikolov._reader("train", word_idx, n, data_type)

    @staticmethod
    def test(word_idx, n, data_type=DataType.NGRAM):
        return imikolov._reader("valid", word_idx, n, data_type)


class movielens:
    """ml-1m readers (reference dataset/movielens.py): each sample is
    (user_id, gender, age_idx, job, movie_id, categories, title_ids,
    score). Parses the standard ml-1m .dat files when present."""

    _AGES = [1, 18, 25, 35, 45, 50, 56]
    _CATEGORIES = ["Action", "Adventure", "Animation", "Children's",
                   "Comedy", "Crime", "Documentary", "Drama", "Fantasy",
                   "Film-Noir", "Horror", "Musical", "Mystery",
                   "Romance", "Sci-Fi", "Thriller", "War", "Western"]

    @staticmethod
    def _dir():
        return os.path.join(_data_home(), "movielens", "ml-1m")

    @staticmethod
    def _have_files():
        d = movielens._dir()
        return all(os.path.exists(os.path.join(d, f))
                   for f in ("ratings.dat", "users.dat", "movies.dat"))

    @staticmethod
    def _synthetic(n_users=32, n_movies=48, n_ratings=256):
        rng = np.random.RandomState(11)
        users = {u: (u, int(rng.randint(0, 2)),
                     int(rng.randint(0, len(movielens._AGES))),
                     int(rng.randint(0, 21)))
                 for u in range(1, n_users)}
        movies = {m: (m, sorted(set(rng.randint(
            0, len(movielens._CATEGORIES),
            rng.randint(1, 3)).tolist())),
            [int(t) for t in rng.randint(0, 64, rng.randint(1, 5))])
            for m in range(1, n_movies)}
        pairs = {(int(rng.randint(1, n_users)),
                  int(rng.randint(1, n_movies)))
                 for _ in range(n_ratings)}
        ratings = [(u, m, float(rng.randint(1, 6)))
                   for u, m in sorted(pairs)]
        return users, movies, ratings

    _cache = None

    @staticmethod
    def _load():
        if movielens._cache is not None:
            return movielens._cache
        movielens._cache = movielens._load_uncached()
        return movielens._cache

    @staticmethod
    def _load_uncached():
        if not movielens._have_files():
            return movielens._synthetic()
        d = movielens._dir()
        users = {}
        with open(os.path.join(d, "users.dat"),
                  encoding="latin1") as f:
            for ln in f:
                uid, gender, age, job, _zip = ln.strip().split("::")
                users[int(uid)] = (int(uid), int(gender == "M"),
                                   movielens._AGES.index(int(age)),
                                   int(job))
        title_vocab = {}
        movies = {}
        with open(os.path.join(d, "movies.dat"),
                  encoding="latin1") as f:
            for ln in f:
                mid, title, cats = ln.strip().split("::")
                cat_ids = [movielens._CATEGORIES.index(c)
                           for c in cats.split("|")
                           if c in movielens._CATEGORIES]
                tids = [title_vocab.setdefault(w, len(title_vocab))
                        for w in title.lower().split()]
                movies[int(mid)] = (int(mid), cat_ids, tids)
        ratings = []
        with open(os.path.join(d, "ratings.dat"),
                  encoding="latin1") as f:
            for ln in f:
                uid, mid, score, _ts = ln.strip().split("::")
                ratings.append((int(uid), int(mid), float(score)))
        return users, movies, ratings

    @staticmethod
    def _reader(is_test, test_ratio=0.1, rand_seed=0):
        def reader():
            users, movies, ratings = movielens._load()
            rng = np.random.RandomState(rand_seed)
            for uid, mid, score in ratings:
                if uid not in users or mid not in movies:
                    continue
                in_test = bool(rng.rand() < test_ratio)
                if in_test != is_test:
                    continue
                u = users[uid]
                m = movies[mid]
                yield (u[0], u[1], u[2], u[3], m[0], m[1], m[2], score)

        return _mark(reader, not movielens._have_files())

    @staticmethod
    def train():
        return movielens._reader(False)

    @staticmethod
    def test():
        return movielens._reader(True)

    @staticmethod
    def movie_categories():
        return {c: i for i, c in enumerate(movielens._CATEGORIES)}

    @staticmethod
    def max_movie_id():
        _, movies, _ = movielens._load()
        return max(movies)

    @staticmethod
    def max_user_id():
        users, _, _ = movielens._load()
        return max(users)

    @staticmethod
    def max_job_id():
        users, _, _ = movielens._load()
        return max(u[3] for u in users.values())

    @staticmethod
    def movie_info():
        _, movies, _ = movielens._load()
        return movies

    @staticmethod
    def user_info():
        users, _, _ = movielens._load()
        return users


class conll05:
    """SRL readers: each sample is (words, pred, ctx_n2..ctx_p2, marks,
    label ids) — the reference's 9-slot layout."""

    _WORDS = 200
    _LABELS = 20
    _PREDS = 40

    @staticmethod
    def get_dict():
        word_dict = {f"w{i}": i for i in range(conll05._WORDS)}
        verb_dict = {f"v{i}": i for i in range(conll05._PREDS)}
        label_dict = {f"L{i}": i for i in range(conll05._LABELS)}
        return word_dict, verb_dict, label_dict

    @staticmethod
    def _reader(n):
        def make(rng):
            ln = int(rng.randint(4, 20))
            words = rng.randint(0, conll05._WORDS, ln).tolist()
            pred = int(rng.randint(0, conll05._PREDS))
            ctx = [rng.randint(0, conll05._WORDS, ln).tolist()
                   for _ in range(5)]
            marks = rng.randint(0, 2, ln).tolist()
            labels = rng.randint(0, conll05._LABELS, ln).tolist()
            return tuple([words, [pred] * ln] + ctx + [marks, labels])

        return _synthetic_reader(make, n)

    @staticmethod
    def test(n=64):
        return conll05._reader(n)


class flowers:
    """102-flowers image readers: (chw float32 image, label)."""

    @staticmethod
    def _reader(n, size=32):
        return _synthetic_reader(
            lambda rng: (rng.rand(3, size, size).astype(np.float32),
                         int(rng.randint(0, 102))), n)

    @staticmethod
    def train(*a, n=128, **kw):
        return flowers._reader(n)

    @staticmethod
    def test(*a, n=32, **kw):
        return flowers._reader(n)

    @staticmethod
    def valid(*a, n=32, **kw):
        return flowers._reader(n)


class voc2012:
    """Segmentation readers: (chw image, hw label mask)."""

    @staticmethod
    def _reader(n, size=32):
        return _synthetic_reader(
            lambda rng: (rng.rand(3, size, size).astype(np.float32),
                         rng.randint(0, 21, (size, size))
                         .astype(np.int64)), n)

    @staticmethod
    def train(n=64):
        return voc2012._reader(n)

    @staticmethod
    def test(n=16):
        return voc2012._reader(n)

    @staticmethod
    def val(n=16):
        return voc2012._reader(n)


class _wmt_base:
    _SRC_V = 96
    _TRG_V = 96

    @classmethod
    def get_dict(cls, *a, **kw):
        src = {f"s{i}": i for i in range(cls._SRC_V)}
        trg = {f"t{i}": i for i in range(cls._TRG_V)}
        for d in (src, trg):
            d["<s>"] = len(d)
            d["<e>"] = len(d)
            d["<unk>"] = len(d)
        return src, trg

    @classmethod
    def _reader(cls, n):
        sv, tv = cls._SRC_V, cls._TRG_V

        def make(rng):
            sl = int(rng.randint(3, 15))
            tl = int(rng.randint(3, 15))
            src = rng.randint(0, sv, sl).tolist()
            trg = rng.randint(0, tv, tl).tolist()
            return src, trg, trg[1:] + [tv + 1]

        return _synthetic_reader(make, n)

    @classmethod
    def train(cls, *a, n=128, **kw):
        return cls._reader(n)

    @classmethod
    def test(cls, *a, n=32, **kw):
        return cls._reader(n)

    @classmethod
    def validation(cls, *a, n=32, **kw):
        return cls._reader(n)


class wmt14(_wmt_base):
    pass


class wmt16(_wmt_base):
    pass


class image:
    """reference dataset/image.py — numpy image utilities (the reference
    shells out to cv2; these are pure-numpy equivalents over HWC
    uint8/float arrays)."""

    @staticmethod
    def resize_short(im, size):
        h, w = im.shape[:2]
        scale = size / min(h, w)
        nh, nw = int(round(h * scale)), int(round(w * scale))
        yy = (np.arange(nh) * (h / nh)).astype(np.int64).clip(0, h - 1)
        xx = (np.arange(nw) * (w / nw)).astype(np.int64).clip(0, w - 1)
        return im[yy][:, xx]

    @staticmethod
    def center_crop(im, size, is_color=True):
        h, w = im.shape[:2]
        hs = max((h - size) // 2, 0)
        ws = max((w - size) // 2, 0)
        return im[hs:hs + size, ws:ws + size]

    @staticmethod
    def random_crop(im, size, is_color=True, rng=None):
        rng = rng or np.random
        h, w = im.shape[:2]
        hs = rng.randint(0, max(h - size, 0) + 1)
        ws = rng.randint(0, max(w - size, 0) + 1)
        return im[hs:hs + size, ws:ws + size]

    @staticmethod
    def left_right_flip(im, is_color=True):
        return im[:, ::-1]

    @staticmethod
    def to_chw(im, order=(2, 0, 1)):
        return im.transpose(order)

    @staticmethod
    def simple_transform(im, resize_size, crop_size, is_train,
                         is_color=True, mean=None):
        im = image.resize_short(im, resize_size)
        if is_train:
            im = image.random_crop(im, crop_size, is_color)
            if np.random.randint(2):
                im = image.left_right_flip(im, is_color)
        else:
            im = image.center_crop(im, crop_size, is_color)
        if im.ndim == 3:
            im = image.to_chw(im)
        im = im.astype(np.float32)
        if mean is not None:
            m = np.asarray(mean, np.float32)
            if m.ndim == 1 and im.ndim == 3:
                m = m.reshape(-1, 1, 1)        # per-channel over CHW
            im -= m
        return im
