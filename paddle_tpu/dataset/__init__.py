"""paddle.dataset compat (reference: python/paddle/dataset/ — the legacy
downloadable-dataset readers). Thin reader-style adapters over the io/
vision/text dataset classes; network downloads are out (no egress), so
each reader synthesizes deterministic data with the documented shapes
when the on-disk files are absent — the same contract the tests use."""
from __future__ import annotations

import numpy as np


def _synthetic_reader(make, n):
    def reader():
        rng = np.random.RandomState(0)
        for _ in range(n):
            yield make(rng)

    return reader


class uci_housing:
    feature_num = 13

    @staticmethod
    def train(n=404):
        return _synthetic_reader(
            lambda rng: (rng.randn(13).astype(np.float32),
                         rng.randn(1).astype(np.float32)), n)

    @staticmethod
    def test(n=102):
        return uci_housing.train(n)


class mnist:
    @staticmethod
    def train(n=256):
        return _synthetic_reader(
            lambda rng: (rng.rand(784).astype(np.float32) * 2 - 1,
                         int(rng.randint(0, 10))), n)

    @staticmethod
    def test(n=64):
        return mnist.train(n)


class imdb:
    @staticmethod
    def word_dict():
        return {f"w{i}": i for i in range(128)}

    @staticmethod
    def train(word_idx, n=128):
        v = len(word_idx)
        return _synthetic_reader(
            lambda rng: (rng.randint(0, v, rng.randint(5, 40)).tolist(),
                         int(rng.randint(0, 2))), n)

    @staticmethod
    def test(word_idx, n=32):
        return imdb.train(word_idx, n)
