from . import lr
from .optimizer import (ASGD, SGD, Adadelta, Adagrad, Adam, Adamax, AdamW,
                        ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue,
                        Lamb, LBFGS, Momentum, NAdam, Optimizer, RAdam,
                        RMSProp, Rprop)
